"""Request micro-batching for the serving path.

TF Serving's ``BatchingSession`` equivalent (SURVEY.md §3.5), designed for
the XLA serving reality rather than ported: a jitted predict function
recompiles per input *shape*, so serving raw per-request row counts would
compile once per distinct batch size and dispatch once per request.  The
batcher fixes both:

  - concurrent requests coalesce into one device call (dispatch amortized,
    MXU fed bigger matmuls);
  - the coalesced batch is padded by row-repetition up to a fixed bucket
    size (powers of two up to ``max_batch_size``), so jit sees a handful of
    shapes ever — after warmup, no request pays a compile.

Rows are padded with copies of the batch's first row (always a valid feature
row, unlike zeros which may violate vocab/string constraints) and the pad
tail is sliced off before replies fan back out.

Two batch-close policies govern how long the worker gathers:

  - **fixed window** (default): gather for ``batch_timeout_s`` — the
    TF-Serving ``batch_timeout_micros`` knob.
  - **SLO-driven deadline** (``slo_p99_s > 0``): gather for
    ``SLO_WINDOW_FRAC x slo_p99_s - 2 x EWMA(model step time)`` — the
    spendable share of the p99 budget minus the request's own device
    call plus (worst case) the batch already in flight.  Most of the
    budget is deliberately held back for everything the step EWMA cannot
    see: HTTP parse, thread scheduling, GC, and — decisive when p99 is
    judged from a Prometheus scrape — the log-2 latency buckets, which
    can make a measured p99 read up to ~2x the true tail.  Spending the
    whole budget would put measured p99 asymptotically AT the target;
    the margin keeps it comfortably under.  The window adapts as the
    observed step time drifts (bigger model, busier device -> shorter
    gather) and degenerates to immediate dispatch when the steps alone
    consume the spendable share.  Until the first step has been
    observed, the fixed window applies.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from tpu_pipelines.observability import request_trace

Batch = Dict[str, np.ndarray]


def bucket_sizes(max_batch_size: int) -> List[int]:
    """[1, 2, 4, ..., max_batch_size] — the shapes jit will ever see."""
    sizes = []
    b = 1
    while b < max_batch_size:
        sizes.append(b)
        b *= 2
    sizes.append(max_batch_size)
    return sizes


def pad_to_bucket(batch: Batch, n_rows: int, buckets: Sequence[int]) -> Batch:
    """Pad every feature to the smallest bucket >= n_rows by repeating row 0.

    A request larger than the top bucket passes through unpadded (it runs
    alone, unsplit — its shape is the caller's to manage)."""
    target = next((b for b in buckets if b >= n_rows), n_rows)
    if target == n_rows:
        return batch
    pad = target - n_rows

    def _pad(v: np.ndarray) -> np.ndarray:
        reps = np.repeat(v[:1], pad, axis=0)
        return np.concatenate([v, reps], axis=0)

    return {k: _pad(np.asarray(v)) for k, v in batch.items()}


# ------------------------------------------------- generation parameters

# The generate-request parameter surface.  Kept deliberately tiny: every
# key here is validated at SUBMIT time, so a malformed request is refused
# with a caller-classified error (HTTP 400 / gRPC INVALID_ARGUMENT)
# instead of failing inside the model step — where it would drain-fail
# every sequence co-batched with it.
GENERATION_PARAM_KEYS = frozenset({"max_new_tokens"})


def validate_generation_params(
    raw: Optional[Dict[str, Any]], *, max_decode_len: int
) -> Dict[str, int]:
    """Validate and normalize a generate request's parameters at submit.

    Raises ``ValueError`` (the server's 4xx classification) for unknown
    keys, non-integer or out-of-range ``max_new_tokens``.  Returns the
    normalized ``{"max_new_tokens": int}`` with the default (the model's
    full decode budget) filled in."""
    raw = dict(raw or {})
    unknown = sorted(set(raw) - GENERATION_PARAM_KEYS)
    if unknown:
        raise ValueError(
            f"unknown generation parameter(s) {unknown}; "
            f"supported: {sorted(GENERATION_PARAM_KEYS)}"
        )
    m = raw.get("max_new_tokens", max_decode_len)
    if isinstance(m, bool) or not isinstance(m, (int, np.integer)):
        raise ValueError(
            f"max_new_tokens must be an integer, got {type(m).__name__}"
        )
    m = int(m)
    if not 1 <= m <= int(max_decode_len):
        raise ValueError(
            f"max_new_tokens must be in [1, {max_decode_len}], got {m}"
        )
    return {"max_new_tokens": m}


def token_deadline_s(
    arrival_s: float, max_new_tokens: int, slo_ms_per_token: float
) -> Optional[float]:
    """Per-token SLO deadline for one generation.

    A request decoding N tokens earns N x the per-token budget from its
    arrival instant — the decode analog of the request-level ``slo_p99_s``
    window: admission control and the engine's eviction policy reason
    about *tokens*, because that is the unit the hardware spends time on.
    ``None`` when no per-token SLO is configured."""
    if slo_ms_per_token <= 0:
        return None
    return arrival_s + max_new_tokens * slo_ms_per_token / 1e3


class RequestBatcher:
    """Coalesces concurrent ``submit`` calls into padded device batches.

    One daemon worker drains the queue: it blocks for the first pending
    request, then gathers more until the group's deadline (the oldest
    request's enqueue time + the gather window — fixed ``batch_timeout_s``
    or the SLO-derived window) or until ``max_batch_size`` rows,
    concatenates, pads to a bucket, runs ``predict_fn`` ONCE, and
    distributes row slices back to each caller's future.  A request bigger
    than ``max_batch_size`` runs alone, unsplit.
    """

    # The deadline budgets TWO step times: the request's own device call
    # plus, worst case, the batch already in flight ahead of it.
    SLO_STEP_BUDGET = 2.0
    # Fraction of the p99 budget the gather window may spend; the rest is
    # safety margin for un-modeled latency (transport, scheduling jitter,
    # scrape-histogram bucket rounding).  Strictly below 0.5 on purpose:
    # p99 judged from the log-2-bucketed scrape can read up to ~2x the
    # true value (it lands at the enclosing bucket's upper bound), so a
    # window at half the budget would make the MEASURED p99 ride the
    # target even when the true tail is under it.
    SLO_WINDOW_FRAC = 0.35
    # Re-derivation against the sqrt(2) fine ladder (metrics.
    # fine_latency_buckets, what serving_replica_latency_seconds and the
    # decode per-token series observe into): measured p99 <= sqrt(2) x
    # true, so keeping measured under budget needs true < budget/sqrt(2)
    # ~= 0.707 x budget; applying the same un-modeled-latency margin
    # ratio the default frac keeps (0.35/0.5 = 0.7) gives 0.7 x 0.707
    # ~= 0.5.  Opt in via ``window_frac=RequestBatcher.
    # SLO_WINDOW_FRAC_FINE`` ONLY where the p99 verdict is read from a
    # fine-ladder series; the default stays 0.35 because
    # serving_request_latency_seconds keeps the x2 ladder.
    SLO_WINDOW_FRAC_FINE = 0.5
    # EWMA smoothing for the observed model step time: heavy enough to
    # ride out one slow batch (GC pause), light enough to track a real
    # drift (hot-swap to a bigger version) within a few batches.
    STEP_EWMA_ALPHA = 0.25

    def __init__(
        self,
        predict_fn: Callable[[Batch], Any],
        *,
        max_batch_size: int = 64,
        batch_timeout_s: float = 0.005,
        slo_p99_s: float = 0.0,
        window_frac: Optional[float] = None,
        registry=None,
        name: str = "",
    ):
        if max_batch_size < 1:
            raise ValueError(f"max_batch_size must be >= 1, got {max_batch_size}")
        self.predict_fn = predict_fn
        self.max_batch_size = max_batch_size
        self.batch_timeout_s = batch_timeout_s
        self.slo_p99_s = max(0.0, slo_p99_s)
        self.window_frac = (
            self.SLO_WINDOW_FRAC if window_frac is None else float(window_frac)
        )
        # Identifies this batcher in request-trace spans (the replica
        # name in fleet mode); group ids are "<name>-<batch index>".
        self.name = name
        self._step_ewma_s: Optional[float] = None
        self._last_window_s = batch_timeout_s
        self.buckets = bucket_sizes(max_batch_size)
        self.batches_run = 0          # observability: device calls issued
        self.requests_served = 0
        self._queue: "queue.Queue" = queue.Queue()
        self._closed = False
        self._close_lock = threading.Lock()
        # Futures of the group currently inside predict_fn: what close()
        # must fail if the worker never comes back (a wedged device call
        # would otherwise leave submit() callers hanging to their full
        # timeout_s).  Written only by the worker thread.
        self._inflight: List["Future[np.ndarray]"] = []
        # Enqueue instant of the oldest request in the group currently
        # inside predict_fn — the supervisor's wedge signal: queued work
        # ages visibly while a device call never returns.  Written only
        # by the worker thread.
        self._inflight_since: Optional[float] = None
        # Live telemetry (observability/metrics.py), opt-in via registry:
        # queue depth is read at scrape time (the gauge calls qsize()),
        # batch sizes/counts update per device call.
        self._m_batch_size = None
        self._m_batches = None
        self._m_requests = None
        self._m_deadline = None
        self._m_step = None
        if registry is not None:
            registry.gauge(
                "serving_batcher_queue_depth",
                "Requests waiting in the micro-batcher queue.",
            ).set_function(self._queue.qsize)
            self._m_batch_size = registry.gauge(
                "serving_batch_size",
                "Rows in the most recent coalesced device batch.",
            )
            self._m_batches = registry.counter(
                "serving_batches_total",
                "Coalesced device calls issued by the micro-batcher.",
            )
            self._m_requests = registry.counter(
                "serving_batched_requests_total",
                "Requests served through the micro-batcher.",
            )
            self._m_deadline = registry.gauge(
                "serving_batch_deadline_seconds",
                "Effective batch-gather window (SLO-derived when "
                "slo_p99_s is configured, else the fixed timeout).",
            )
            self._m_step = registry.gauge(
                "serving_model_step_seconds",
                "EWMA wall time of one coalesced device call.",
            )
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    # --------------------------------------------------- SLO batch window

    def gather_window_s(self) -> float:
        """The wait budget for coalescing the batch that opens NOW.

        SLO mode spends what the spendable half of the p99 budget leaves
        after reserving ``SLO_STEP_BUDGET`` observed step times;
        unconfigured (or before the first observed step) it is the fixed
        ``batch_timeout_s``."""
        if self.slo_p99_s <= 0 or self._step_ewma_s is None:
            window = self.batch_timeout_s
        else:
            window = max(
                0.0,
                self.slo_p99_s * self.window_frac
                - self.SLO_STEP_BUDGET * self._step_ewma_s,
            )
        if self._m_deadline is not None:
            self._m_deadline.set(window)
        self._last_window_s = window
        return window

    def _observe_step(self, step_s: float) -> None:
        if self._step_ewma_s is None:
            self._step_ewma_s = step_s
        else:
            a = self.STEP_EWMA_ALPHA
            self._step_ewma_s = (1 - a) * self._step_ewma_s + a * step_s
        if self._m_step is not None:
            self._m_step.set(self._step_ewma_s)

    # ------------------------------------------------------------- client

    def submit(
        self,
        batch: Batch,
        n_rows: int,
        timeout_s: float = 300.0,
        ctx=None,
    ) -> np.ndarray:
        """Blocking predict for one request's feature batch (n_rows rows).

        ``timeout_s`` bounds the wait (covers first-bucket XLA compiles with
        room to spare); a closed batcher raises immediately.  ``ctx`` is
        the request-trace context riding the queue item (contextvars do
        not cross into the worker thread); None falls back to the
        calling thread's current trace, so the single-server path traces
        without any caller plumbing."""
        if ctx is None:
            ctx = request_trace.current()
        fut: "Future[np.ndarray]" = Future()
        with self._close_lock:
            # Checked under the close lock: a submit racing close() must
            # either enqueue before the worker's final drain or raise — never
            # land in a queue nobody services.
            if self._closed:
                raise RuntimeError("batcher is closed")
            # The enqueue instant anchors the gather deadline: a request
            # that waited out the PREVIOUS group's gather must not pay a
            # second full window.
            self._queue.put((batch, n_rows, fut, time.monotonic(), ctx))
        return fut.result(timeout=timeout_s)

    def oldest_work_age_s(self) -> float:
        """Age of the oldest request this batcher owes an answer —
        queued OR inside the current device call.  A healthy batcher
        keeps this near the gather window; a wedged predict (dead
        device, stuck transfer) lets it grow without bound, which is the
        supervisor's wedge-detection signal.  Lock-free on the hot
        fields; the queue peek holds the queue mutex only long enough to
        read the head entry's enqueue instant."""
        oldest = self._inflight_since
        with self._queue.mutex:
            for item in self._queue.queue:
                if item is not None:  # skip the close sentinel
                    t = item[3]
                    if oldest is None or t < oldest:
                        oldest = t
                    break  # FIFO: the first real entry is the oldest
        if oldest is None:
            return 0.0
        return max(0.0, time.monotonic() - oldest)

    def close(self, timeout_s: float = 5.0) -> None:
        """Shut down: reject new submits, serve-or-fail everything queued.

        Every pre-close ``submit`` either completes normally (the worker
        drains the queue ahead of the close sentinel) or gets a
        ``RuntimeError`` — never a silently hanging future.  If the
        worker does not come back within ``timeout_s`` (predict_fn
        wedged), the in-flight group's futures are failed too, so
        blocked callers return immediately instead of waiting out their
        own submit timeout.

        Fleet note: ``close`` joins THIS batcher's worker for up to
        ``timeout_s``, so closing N replica batchers serially would cost
        up to N x timeout.  ``ReplicaPool.close`` instead calls
        :meth:`request_close` on every batcher first (all workers drain
        concurrently) and then :meth:`join_close` against one shared
        deadline — the two halves this method simply runs back to back.
        """
        self.request_close()
        self.join_close(timeout_s)

    def request_close(self) -> None:
        """Phase 1 (non-blocking): reject new submits and sentinel the
        worker so it starts draining.  Idempotent."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
            self._queue.put(None)  # wake the worker

    def join_close(self, timeout_s: float = 5.0) -> None:
        """Phase 2: wait for the drain started by :meth:`request_close`;
        past the deadline, fail the wedged in-flight futures."""
        self._worker.join(timeout=timeout_s)
        if self._worker.is_alive():
            # Wedged device call: its group's futures would otherwise
            # hang until each caller's submit timeout.  Fail them now —
            # if predict_fn eventually returns, the worker's set_result
            # on a done future is swallowed below.
            for fut in list(self._inflight):
                if not fut.done():
                    try:
                        fut.set_exception(RuntimeError(
                            "batcher closed while request was in flight"
                        ))
                    except Exception:  # noqa: BLE001 — lost the race: done
                        pass
        self._drain_failures("batcher closed")  # anything the worker missed

    # ------------------------------------------------------------- worker

    @staticmethod
    def _signature(batch: Batch):
        """Feature names + per-row shapes + dtype kinds: what must agree for
        requests to share one concatenated device batch."""
        return tuple(sorted(
            (k, np.asarray(v).shape[1:], np.asarray(v).dtype.kind)
            for k, v in batch.items()
        ))

    def _run(self) -> None:
        carry = None  # request popped but deferred to keep batches in budget
        while True:
            item = carry if carry is not None else self._queue.get()
            carry = None
            if item is None:
                self._drain_failures("batcher closed")
                return
            group = [item]
            rows = item[1]
            sig = self._signature(item[0])
            # Gather more requests within the window / size budget.  The
            # window is fixed (batch_timeout_s) or SLO-derived — computed
            # per group so it tracks the step-time EWMA as it drifts — and
            # anchored at the OLDEST request's enqueue instant, so time a
            # request already spent queued behind the previous group
            # counts against its window (per-request wait stays bounded
            # by ~one window, not one per preceding group).
            t_end = item[3] + self.gather_window_s()
            while rows < self.max_batch_size:
                remaining = t_end - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is None:
                    self._queue.put(None)  # re-post the close sentinel
                    break
                if (
                    rows + nxt[1] > self.max_batch_size
                    or self._signature(nxt[0]) != sig
                ):
                    # Over budget or schema-incompatible (a malformed request
                    # must not poison whoever it queued next to): defer it to
                    # open the next group.
                    carry = nxt
                    break
                group.append(nxt)
                rows += nxt[1]
            self._inflight = [entry[2] for entry in group]
            self._inflight_since = group[0][3]
            try:
                self._execute(group)
            finally:
                self._inflight = []
                self._inflight_since = None

    def _predict_group(self, group) -> None:
        merged = {
            k: np.concatenate(
                [np.asarray(b[k])[:n] for b, n, *_ in group], axis=0
            )
            for k in group[0][0]
        }
        total = sum(n for _, n, *_ in group)
        padded = pad_to_bucket(merged, total, self.buckets)
        group_id = f"{self.name or 'b'}-{self.batches_run}"
        t0_wall = time.time()
        t0 = time.monotonic()
        preds = np.asarray(self.predict_fn(padded))[:total]
        step_s = time.monotonic() - t0
        self._emit_group_spans(group, group_id, total, t0_wall, t0, step_s)
        self._observe_step(step_s)
        self.batches_run += 1
        self.requests_served += len(group)
        if self._m_batches is not None:
            self._m_batches.inc()
            self._m_requests.inc(len(group))
            self._m_batch_size.set(total)
        offset = 0
        for _, n, fut, *_ in group:
            if not fut.done():  # close() may have failed a wedged group
                try:
                    fut.set_result(preds[offset:offset + n])
                except Exception:  # noqa: BLE001 — lost the close race
                    pass
            offset += n

    def _emit_group_spans(
        self, group, group_id: str, total: int,
        t0_wall: float, t0_mono: float, step_s: float,
    ) -> None:
        """Request-trace spans for one dispatched group: per sampled
        request, the gather wait it paid (enqueue -> dispatch, which
        group it rode) and the shared device call (the model step, with
        the version the fleet leased for it — request_trace.note from
        inside predict_fn).  No-op for untraced requests."""
        if not any(entry[4] is not None for entry in group):
            if request_trace.tracing_active():
                request_trace.take_notes()  # leased version, now stale
            return
        notes = request_trace.take_notes()
        for _batch, n, _fut, t_enq, ctx in group:
            if ctx is None:
                continue
            wait_s = max(0.0, t0_mono - t_enq)
            ctx.complete_span(
                "batch.wait", t0_wall - wait_s, t_enq, wait_s,
                group=group_id, replica=self.name,
                window_s=round(self._last_window_s, 6),
                requests=len(group),
            )
            ctx.complete_span(
                "model.step", t0_wall, t0_mono, step_s,
                group=group_id, replica=self.name, rows=total,
                request_rows=n, **notes,
            )
            if notes:
                ctx.annotate(**notes)

    def _execute(self, group) -> None:
        try:
            self._predict_group(group)
        except Exception:  # noqa: BLE001 — isolate, then fail only the culprit
            # Same-signature requests can still differ in value validity
            # (vocab misses, NaNs the transform rejects): retry one-by-one so
            # a bad request fails alone, TF-Serving style.
            for entry in group:
                try:
                    self._predict_group([entry])
                except Exception as e:  # noqa: BLE001
                    if not entry[2].done():
                        entry[2].set_exception(e)

    def _drain_failures(self, msg: str) -> None:
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                return
            if item is not None:
                item[2].set_exception(RuntimeError(msg))
