"""Standalone model server: ``python -m tpu_pipelines.serving``.

The ``tensorflow_model_server`` equivalent (SURVEY.md §3.5 / §2b TF Serving
row) for the framework's payload format: serves Pusher's versioned layout
over TF-Serving-style REST, watches the base dir for newly pushed versions
(``--poll-seconds``) and hot-swaps to the highest one, exactly like TF
Serving's file-system version watcher.  This is the process the emitted
serving Deployment manifest runs (orchestration/cluster_runner.py).

    python -m tpu_pipelines.serving \
        --model-name taxi --base-dir /pipeline/serving/taxi --port 8501
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading

from tpu_pipelines.serving.server import ModelServer

log = logging.getLogger("tpu_pipelines.serving")


def main(argv=None) -> int:
    from tpu_pipelines.utils.compile_cache import maybe_enable_compile_cache

    maybe_enable_compile_cache()
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--model-name", required=True)
    parser.add_argument("--base-dir", required=True,
                        help="versioned model dir (Pusher destination)")
    parser.add_argument("--port", type=int, default=8501)
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--transformed-inputs", action="store_true",
                        help="serve predict_transformed (callers send "
                             "materialized features, not raw examples)")
    parser.add_argument("--batching", action="store_true",
                        help="micro-batch concurrent requests (bucketed "
                             "shapes, one device call per batch)")
    parser.add_argument("--max-batch-size", type=int, default=64)
    parser.add_argument("--batch-timeout-ms", type=float, default=5.0)
    parser.add_argument("--poll-seconds", type=float, default=30.0,
                        help="version-watch interval; 0 disables hot reload")
    parser.add_argument("--max-queue-depth", type=int, default=0,
                        help="admission-control bound: refuse (429 + "
                             "Retry-After) predict/generate requests once "
                             "in-flight + queued work reaches this; 0 = "
                             "env TPP_SERVING_MAX_QUEUE, else unbounded")
    parser.add_argument("--replicas", type=int, default=0,
                        help="serving-fleet worker replicas behind the "
                             "latency-aware router (one micro-batcher + "
                             "model runner each, own device when the host "
                             "has several); 0 = env TPP_SERVING_REPLICAS, "
                             "else 1 (single-server mode)")
    parser.add_argument("--max-versions", type=int, default=0,
                        help="model versions kept resident for instant "
                             "hot-swap/rollback (old versions drain, then "
                             "evict); 0 = env TPP_SERVING_MAX_VERSIONS, "
                             "else 1")
    parser.add_argument("--slo-p99-ms", type=float, default=-1.0,
                        help="p99 latency budget driving the dynamic "
                             "batch deadline (gather window = budget - "
                             "2x observed model step time); negative = "
                             "env TPP_SERVING_SLO_P99_MS, 0 = fixed "
                             "--batch-timeout-ms window")
    parser.add_argument("--model-type", default="",
                        choices=["", "predict", "generative"],
                        help='"generative" = continuous-batching decode '
                             "for :generate (sequences join the running "
                             "batch per decode step, leave at EOS; "
                             "docs/SERVING.md); empty = env "
                             "TPP_SERVING_MODEL_TYPE, else predict")
    parser.add_argument("--decode-page-size", type=int, default=0,
                        help="KV-cache bucket granularity for generative "
                             "decode (0 = one bucket, the whole cache; "
                             "env TPP_SERVING_PAGE_SIZE)")
    parser.add_argument("--max-queue-tokens", type=int, default=0,
                        help="generative admission bound in outstanding "
                             "decode TOKENS (429 past it); 0 = env "
                             "TPP_SERVING_MAX_TOKENS, else unbounded")
    parser.add_argument("--slo-ms-per-token", type=float, default=-1.0,
                        help="per-token latency budget pricing each "
                             "generation's deadline; negative = env "
                             "TPP_SERVING_SLO_MS_PER_TOKEN, 0 = none")
    parser.add_argument("--grpc-port", type=int, default=-1,
                        help="also serve gRPC predict on this port "
                             "(0 = ephemeral; -1 = REST only)")
    parser.add_argument("--request-trace", default="",
                        help="request-scoped tracing: off | sample:N | "
                             "all (empty = env TPP_REQUEST_TRACE, "
                             "default off — zero files, byte-identical "
                             "/metrics)")
    parser.add_argument("--trace-dir", default="",
                        help="flush sampled request spans to "
                             "<dir>/serving/events.jsonl (read back with "
                             "`python -m tpu_pipelines trace serve "
                             "<dir>`); empty = env TPP_REQUEST_TRACE_DIR, "
                             "else in-memory ring only")
    parser.add_argument("--slo-monitor", type=float, default=-1.0,
                        help="SLO burn-rate monitor evaluation interval "
                             "(seconds; fleet mode with --slo-p99-ms): "
                             "breaches inside the TPP_SWAP_PROBATION_S "
                             "window auto-roll back to the prior "
                             "version; negative = env TPP_SLO_MONITOR, "
                             "0 = off")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    # "Pushing IS deploying": the Deployment may come up before the first
    # Pusher run, so wait for the first version instead of crash-looping.
    import time

    while True:
        try:
            server = ModelServer(
                args.model_name,
                args.base_dir,
                raw=not args.transformed_inputs,
                batching=args.batching,
                max_batch_size=args.max_batch_size,
                batch_timeout_s=args.batch_timeout_ms / 1000.0,
                max_queue_depth=args.max_queue_depth,
                replicas=args.replicas,
                max_versions=args.max_versions,
                slo_p99_ms=args.slo_p99_ms,
                model_type=args.model_type,
                decode_page_size=args.decode_page_size,
                max_queue_tokens=args.max_queue_tokens,
                slo_ms_per_token=args.slo_ms_per_token,
                request_trace_mode=args.request_trace,
                trace_dir=args.trace_dir,
                slo_monitor_interval_s=args.slo_monitor,
            )
            break
        except FileNotFoundError:
            log.info(
                "no model versions under %r yet; waiting for the first push",
                args.base_dir,
            )
            time.sleep(max(args.poll_seconds, 1.0))
        except Exception as e:  # noqa: BLE001
            # A version dir observed mid-write (a non-atomic pusher, scp, …)
            # can fail with anything; keep waiting like TF Serving's watcher
            # instead of crash-looping the pod — the next poll sees the
            # finished payload.
            log.warning(
                "model under %r not loadable yet (%s); retrying",
                args.base_dir, e,
            )
            time.sleep(max(args.poll_seconds, 1.0))
    port = server.start(port=args.port, host=args.host)
    log.info(
        "serving %r (version %s) on %s:%d",
        args.model_name, server.version, args.host, port,
    )
    grpc_server = None
    if args.grpc_port >= 0:
        from tpu_pipelines.serving.grpc_server import start_grpc_server

        grpc_server, grpc_port = start_grpc_server(
            server, port=args.grpc_port, host=args.host
        )
        log.info("grpc predict on %s:%d", args.host, grpc_port)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    try:
        while not stop.wait(args.poll_seconds or None):
            try:
                before = server.version
                after = server.reload()
                if after != before:
                    log.info("hot-swapped to version %s", after)
            except Exception as e:  # noqa: BLE001 — keep serving old version
                log.warning("version rescan failed: %s", e)
    finally:
        if grpc_server is not None:
            grpc_server.stop(grace=2)
        server.stop()
        log.info("server stopped")
    return 0


if __name__ == "__main__":
    sys.exit(main())
