"""Continuous batching for autoregressive decode — the generative engine.

The PR 10 fleet batches *whole requests*: an autoregressive request owns
its replica for its entire decode, so one long generation stalls every
request co-batched behind it, and a replica decoding a 4-token reply and
one decoding a 500-token reply cost the router the same.  This engine
batches at the *decode-step* level instead (iteration-level scheduling,
Orca OSDI '22): sequences join the running batch the moment a slot is
free and leave the moment they emit EOS or hit ``max_new_tokens`` —
every device step serves exactly the sequences that still need tokens.

Mechanics (the vLLM/PagedAttention shape of the idea, on the repo's
static-shape substrate):

  * **Arena.**  One device-resident state pool sized ``max_batch_size``:
    the flax decode cache (self-attention K/V at ``max_decode_len``,
    cross-attention K/V at the encoder length), per-slot last token,
    position, live flag, encoder output and mask.  Live sequences occupy
    the compacted prefix ``[0, n_live)``; a departure moves the last live
    row into the hole (one scatter), an arrival lands at ``n_live`` (one
    scatter) — no host-side repacking of the cache, ever.
  * **Bucketed steps.**  Each decode step runs one pre-compiled program
    keyed ``(batch_bucket, kv_bucket)``: the batch bucket is the smallest
    power-of-two >= the live count (serving/batching.py's bucket rule),
    the KV bucket the smallest page multiple covering the deepest live
    position.  ``warm()`` compiles every combination up front — the
    fleet's canary gate calls it BEFORE a version becomes eligible, so no
    decode step pays an XLA compile mid-traffic (``compiles_after_warm``
    is the auditable contract).  Pages are an allocation/accounting unit:
    ``serving_decode_cache_pages_in_use`` is what capacity planning reads.
  * **Identity.**  The per-row decode math is exactly the scalar-position
    math greedy/beam run (models/transformer.py vector ``decode_pos``;
    the batch dimension is bitwise row-independent), so a sequence's
    token stream is bit-identical to an isolated single-request greedy
    decode regardless of who it shared steps with.  KV bucketing keeps
    masked positions at exact zero contribution, but XLA tiles a
    contraction differently per length, so *across different KV buckets*
    logits can drift by ~1 ulp — the same property every paged-attention
    kernel has.  ``page_size=0`` (one bucket = the whole cache) makes the
    stream bitwise under any schedule; the identity test pins that mode.
  * **Per-token SLO.**  Admission control counts outstanding *tokens*
    (``max_queue_tokens``), not requests: a queued 500-token generation
    is 125x the work of a 4-token one and the door should know.  With
    ``slo_ms_per_token`` each sequence carries a token-proportional
    deadline (serving/batching.py ``token_deadline_s``); ``hard_deadline``
    evicts a sequence that blows it (``GenerationEvicted``), freeing its
    slot for work that can still meet SLO.

Metrics (``serving_decode_*``, labeled per replica; catalog in
docs/SERVING.md): steps/s, tokens/s, batch occupancy, cache pages in
use, active/queued sequences + outstanding tokens, per-token latency
histogram, evictions, step-time EWMA (what the router reads).
"""

from __future__ import annotations

import collections
import contextlib
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from tpu_pipelines.serving.batching import (
    bucket_sizes,
    token_deadline_s,
    validate_generation_params,
)

log = logging.getLogger("tpu_pipelines.serving")


class EngineOverloaded(RuntimeError):
    """Token-level admission control refused the sequence: outstanding
    decode work (live + queued tokens) already exceeds the configured
    bound.  Maps to HTTP 429 + Retry-After, like ``ServerOverloaded`` —
    shed at the door, counted, never dropped mid-decode."""

    retry_after_s = 1


class GenerationEvicted(RuntimeError):
    """The sequence was evicted before finishing — its per-token SLO
    deadline passed under ``hard_deadline=True``, or the engine closed.
    Maps to a retriable 503: the server is healthy, this generation lost
    its latency race."""


@dataclass
class _Sequence:
    """Host-side bookkeeping for one generation (the engine's unit of
    scheduling).  ``tokens`` mirrors the device state: its length IS the
    sequence's next decode position."""

    inputs: np.ndarray              # [max_input_len] padded token ids
    input_mask: np.ndarray          # [max_input_len] 1/0 validity
    max_new_tokens: int
    arrival_s: float
    deadline_s: Optional[float]
    tokens: List[int] = field(default_factory=list)
    _done: threading.Event = field(default_factory=threading.Event)
    result: Optional[np.ndarray] = None
    error: Optional[BaseException] = None
    # Request-trace context (None = untraced): rides the sequence across
    # the client->worker thread boundary so decode-step slot events land
    # on the originating request's trace.
    ctx: Any = None
    arrival_wall_s: float = 0.0

    def finish(self, error: Optional[BaseException] = None) -> None:
        if self._done.is_set():
            return
        if error is not None:
            self.error = error
        else:
            self.result = np.asarray(self.tokens, np.int32)
        self._done.set()

    def wait(self, timeout_s: float) -> np.ndarray:
        if not self._done.wait(timeout_s):
            raise TimeoutError("generation did not complete in time")
        if self.error is not None:
            raise self.error
        return self.result


def kv_bucket_sizes(max_decode_len: int, page_size: int) -> List[int]:
    """KV-cache length buckets: page, 2*page, 4*page, ... capped at the
    full cache.  ``page_size <= 0`` means one bucket — the whole cache —
    which is also the bitwise-exact mode (see module docstring)."""
    max_decode_len = int(max_decode_len)
    if page_size <= 0 or page_size >= max_decode_len:
        return [max_decode_len]
    out = []
    k = int(page_size)
    while k < max_decode_len:
        out.append(k)
        k *= 2
    out.append(max_decode_len)
    return sorted(set(out))


def _is_enc_leaf(path) -> bool:
    """Cross-attention K/V leaves keep the ENCODER length on axis 1 (not
    the decode cache length) and are never written by a decode step."""
    return any("cached_enc" in str(getattr(p, "key", p)) for p in path)


class GenerativeEngine:
    """One continuous-batching decode engine over one (model, params).

    ``fns`` is the duck-typed decode contract (see
    ``models/t5.py make_continuous_decode_fns``): ``prefill``/``step``
    plus geometry constants.  The engine owns a single worker thread; all
    device work — prefill, bucketed steps, arena scatters — happens
    there, so the jit-compiled programs never race.  ``submit`` blocks
    like ``RequestBatcher.submit``; ``submit_nowait`` returns a handle
    the fleet uses to run one request's rows concurrently.
    """

    # EWMA smoothing for the observed decode-step wall time (the router's
    # cost signal); same constant family as RequestBatcher.
    STEP_EWMA_ALPHA = 0.25

    def __init__(
        self,
        fns,
        params,
        *,
        max_batch_size: int = 8,
        page_size: int = 0,
        max_queue_tokens: int = 0,
        slo_ms_per_token: float = 0.0,
        hard_deadline: bool = False,
        device: Any = None,
        telemetry: Optional["DecodeTelemetry"] = None,
        registry=None,
        replica: str = "0",
    ):
        self.fns = fns
        self.params = params
        self.max_decode_len = int(fns.max_decode_len)
        self.eos_id = int(fns.eos_id)
        self.pad_id = int(fns.pad_id)
        self.max_input_len = int(getattr(fns, "max_input_len", 64))
        self.max_batch_size = max(1, int(max_batch_size))
        self.page_size = int(page_size)
        self.max_queue_tokens = max(0, int(max_queue_tokens))
        self.slo_ms_per_token = max(0.0, float(slo_ms_per_token))
        self.hard_deadline = bool(hard_deadline)
        self.device = device
        self.batch_buckets = bucket_sizes(self.max_batch_size)
        self.kv_buckets = kv_bucket_sizes(self.max_decode_len, self.page_size)
        self._page = (
            self.page_size if 0 < self.page_size < self.max_decode_len
            else self.max_decode_len
        )
        self.telemetry = telemetry or DecodeTelemetry(registry, replica)

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: "collections.deque[_Sequence]" = collections.deque()
        self._slots: List[Optional[_Sequence]] = (
            [None] * self.max_batch_size
        )
        self._n_live = 0
        self._closed = False
        self._arena = None
        self._warmed = False
        self.compiles_after_warm = 0
        self.steps_run = 0
        self.step_ewma_s: Optional[float] = None

        self._step_fns: Dict[Tuple[int, int], Any] = {}
        self._jit_prefill = None
        self._jit_insert = None
        self._jit_move = None
        self._jit_clear = None

        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    # ---------------------------------------------------------- device ctx

    def _dev(self):
        if self.device is None:
            return contextlib.nullcontext()
        import jax

        return jax.default_device(self.device)

    # ------------------------------------------------------- compiled fns

    def _build_jits(self) -> None:
        import jax
        import jax.numpy as jnp

        fns = self.fns

        def prefill(params, inputs, input_mask):
            cache, encoded, logits = fns.prefill(params, inputs, input_mask)
            return cache, encoded, jnp.argmax(logits[0], -1).astype(jnp.int32)

        def insert(state, pcache, encoded, enc_mask, tok0, slot):
            cache, tok, pos, live, enc, mask = state
            cache = jax.tree_util.tree_map(
                lambda a, p: a.at[slot].set(p[0].astype(a.dtype)),
                cache, pcache,
            )
            return (
                cache,
                tok.at[slot].set(tok0),
                pos.at[slot].set(1),
                live.at[slot].set(True),
                enc.at[slot].set(encoded[0].astype(enc.dtype)),
                mask.at[slot].set(jnp.asarray(enc_mask[0], mask.dtype)),
            )

        def move(state, src, dst):
            return tuple(
                jax.tree_util.tree_map(lambda a: a.at[dst].set(a[src]), part)
                for part in state
            )

        def clear(state, slot):
            cache, tok, pos, live, enc, mask = state
            return (
                cache,
                tok.at[slot].set(self.pad_id),
                pos.at[slot].set(0),
                live.at[slot].set(False),
                enc,
                mask,
            )

        self._jit_prefill = jax.jit(prefill)
        self._jit_insert = jax.jit(insert)
        self._jit_move = jax.jit(move)
        self._jit_clear = jax.jit(clear)

    def _build_step(self, b: int, kv: int):
        import jax
        import jax.numpy as jnp

        fns = self.fns
        pad = self.pad_id

        def run(params, state):
            cache, tok, pos, live, encoded, enc_mask = state
            sub = jax.tree_util.tree_map_with_path(
                lambda p, x: x[:b] if _is_enc_leaf(p) else x[:b, :kv], cache
            )
            new_sub, logits = fns.step(
                params, sub, tok[:b], pos[:b], encoded[:b], enc_mask[:b], kv
            )
            nxt = jnp.where(
                live[:b], jnp.argmax(logits, -1).astype(jnp.int32), pad
            )
            cache = jax.tree_util.tree_map_with_path(
                lambda p, a, n: a if _is_enc_leaf(p) else a.at[:b, :kv].set(n),
                cache, new_sub,
            )
            tok = tok.at[:b].set(nxt)
            pos = pos.at[:b].set(pos[:b] + live[:b].astype(jnp.int32))
            return (cache, tok, pos, live, encoded, enc_mask), nxt

        return jax.jit(run)

    def _step_for(self, b: int, kv: int):
        fn = self._step_fns.get((b, kv))
        if fn is None:
            if self._warmed:
                # The warmup contract: every (batch, kv) bucket program is
                # compiled before traffic.  A post-warm build means a
                # bucket the warmup missed — counted, loud, and the
                # warmup-contract test's assertion.
                self.compiles_after_warm += 1
                self.telemetry.on_compile_after_warm()
                log.warning(
                    "generative engine: compiling step (%d, %d) AFTER "
                    "warmup — bucket missed by warm()", b, kv,
                )
            fn = self._build_step(b, kv)
            self._step_fns[(b, kv)] = fn
        return fn

    # ------------------------------------------------------------- arena

    def _ensure_arena(self) -> None:
        if self._arena is not None:
            return
        import jax
        import jax.numpy as jnp

        if self._jit_prefill is None:
            self._build_jits()
        with self._dev():
            # Commit params AND the arena to one device up front.  The
            # jit program cache keys on each argument's placement, not
            # just its shape: an exported payload's params arrive
            # COMMITTED (orbax restore), so step outputs — the next
            # step's arena — are committed too, and a warmup that ran on
            # an uncommitted pristine arena would silently recompile
            # every bucket program on its first real-traffic step (~1 s
            # stalls that defeat the whole warm() contract).  One
            # explicit placement makes warm and traffic byte-identical
            # cache keys — the warmup-contract test pins this.
            dev = self.device
            if dev is None:
                dev = jax.local_devices()[0]
            self.params = jax.device_put(self.params, dev)
            zin = jnp.full((1, self.max_input_len), self.pad_id, jnp.int32)
            zmask = jnp.zeros((1, self.max_input_len), jnp.int32)
            cache1, encoded1, _ = self._jit_prefill(self.params, zin, zmask)
            B = self.max_batch_size
            cache = jax.tree_util.tree_map(
                lambda x: jnp.zeros((B,) + x.shape[1:], x.dtype), cache1
            )
            # Free rows keep an all-ONES encoder mask: cross-attention over
            # their zero K/V then averages zeros instead of softmaxing an
            # all-masked row into NaN.  Live rows overwrite it on insert.
            self._arena = jax.device_put((
                cache,
                jnp.full((B,), self.pad_id, jnp.int32),
                jnp.zeros((B,), jnp.int32),
                jnp.zeros((B,), bool),
                jnp.zeros((B,) + encoded1.shape[1:], encoded1.dtype),
                jnp.ones((B, self.max_input_len), jnp.int32),
            ), dev)

    def warm(self) -> None:
        """Pre-compile every program traffic can pose: prefill, insert /
        move / clear, and one step per ``(batch_bucket, kv_bucket)``.
        The fleet's canary gate runs this BEFORE a version becomes
        eligible — the decode analog of the predict-bucket warmup — so a
        hot-swap never pays an XLA compile mid-traffic.  Results are
        discarded; the arena is untouched (jax arrays are immutable).
        Arguments mirror the traffic paths exactly — host numpy inputs,
        the committed arena — so every call lands on the SAME program
        cache key traffic will use (see _ensure_arena on placement)."""
        with self._dev():
            self._ensure_arena()
            zin = np.full((1, self.max_input_len), self.pad_id, np.int32)
            zmask = np.zeros((1, self.max_input_len), np.int32)
            cache1, encoded1, tok0 = self._jit_prefill(
                self.params, zin, zmask
            )
            self._jit_insert(
                self._arena, cache1, encoded1, zmask, tok0, np.int32(0)
            )
            self._jit_move(self._arena, np.int32(0), np.int32(0))
            self._jit_clear(self._arena, np.int32(0))
            for b in self.batch_buckets:
                for kv in self.kv_buckets:
                    self._step_for(b, kv)(self.params, self._arena)
        self._warmed = True

    # ------------------------------------------------------------- client

    def outstanding_tokens(self) -> int:
        """Decode work still owed: remaining tokens of live sequences plus
        every queued sequence's full budget — the admission-control and
        routing unit."""
        with self._lock:
            live = sum(
                max(0, s.max_new_tokens - len(s.tokens))
                for s in self._slots[: self._n_live] if s is not None
            )
            queued = sum(s.max_new_tokens for s in self._queue)
        return live + queued

    def active_sequences(self) -> int:
        with self._lock:
            return self._n_live + len(self._queue)

    def idle(self) -> bool:
        with self._lock:
            return self._n_live == 0 and not self._queue

    def submit_nowait(
        self,
        inputs,
        *,
        max_new_tokens: Optional[int] = None,
        input_mask=None,
        ctx=None,
    ) -> _Sequence:
        params = validate_generation_params(
            {} if max_new_tokens is None
            else {"max_new_tokens": max_new_tokens},
            max_decode_len=self.max_decode_len,
        )
        m = params["max_new_tokens"]
        inputs = np.asarray(inputs, np.int32).reshape(-1)
        if inputs.size == 0 or inputs.size > self.max_input_len:
            raise ValueError(
                f"input length must be in [1, {self.max_input_len}], "
                f"got {inputs.size}"
            )
        if input_mask is None:
            mask = np.ones(inputs.shape, np.int32)
        else:
            mask = np.asarray(input_mask, np.int32).reshape(-1)
        pad = self.max_input_len - inputs.size
        inputs = np.pad(inputs, (0, pad), constant_values=self.pad_id)
        mask = np.pad(mask, (0, pad))
        if self.max_queue_tokens > 0:
            owed = self.outstanding_tokens()
            if owed + m > self.max_queue_tokens:
                self.telemetry.on_shed()
                raise EngineOverloaded(
                    f"outstanding decode tokens {owed} + {m} exceed the "
                    f"bound {self.max_queue_tokens}"
                )
        now = time.monotonic()
        seq = _Sequence(
            inputs=inputs,
            input_mask=mask,
            max_new_tokens=m,
            arrival_s=now,
            deadline_s=token_deadline_s(now, m, self.slo_ms_per_token),
            ctx=ctx,
            arrival_wall_s=time.time(),
        )
        with self._cond:
            if self._closed:
                raise RuntimeError("engine is closed")
            self._queue.append(seq)
            self.telemetry.on_queue(self.outstanding_tokens_locked())
            self._cond.notify_all()
        return seq

    def outstanding_tokens_locked(self) -> int:
        # Caller holds self._lock (the condition's underlying lock).
        live = sum(
            max(0, s.max_new_tokens - len(s.tokens))
            for s in self._slots[: self._n_live] if s is not None
        )
        return live + sum(s.max_new_tokens for s in self._queue)

    def submit(
        self,
        inputs,
        *,
        max_new_tokens: Optional[int] = None,
        input_mask=None,
        timeout_s: float = 300.0,
    ) -> np.ndarray:
        """Blocking generate for one sequence; returns the emitted token
        ids (EOS included when hit within budget)."""
        return self.submit_nowait(
            inputs, max_new_tokens=max_new_tokens, input_mask=input_mask
        ).wait(timeout_s)

    def close(self, timeout_s: float = 5.0) -> None:
        """Reject new submits and fail everything unfinished.  Sequences
        mid-decode get ``GenerationEvicted`` (the zero-drop contract is
        the fleet's: it only closes engines after the drain)."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._worker.join(timeout=timeout_s)
        with self._lock:
            pending = list(self._queue) + [
                s for s in self._slots[: self._n_live] if s is not None
            ]
            self._queue.clear()
            self._n_live = 0
            self._slots = [None] * self.max_batch_size
        for seq in pending:
            self._trace_end(seq, "evicted")
            seq.finish(GenerationEvicted("engine closed"))

    # ------------------------------------------------------------- worker

    def _run(self) -> None:
        try:
            while True:
                with self._cond:
                    while (
                        not self._closed
                        and not self._queue
                        and self._n_live == 0
                    ):
                        self._cond.wait()
                    if self._closed:
                        return
                self._admit()
                if self._n_live:
                    self._step_once()
        except Exception as e:  # noqa: BLE001 — device fault: fail loudly
            log.exception("generative engine worker died")
            with self._lock:
                pending = list(self._queue) + [
                    s for s in self._slots[: self._n_live] if s is not None
                ]
                self._queue.clear()
                self._n_live = 0
            for seq in pending:
                self._trace_end(seq, "error")
                seq.finish(e)

    def _admit(self) -> None:
        """Iteration-level admission: fill free slots from the queue NOW —
        between two decode steps — instead of waiting for the batch to
        drain.  One prefill (encoder + step-0 decode, the greedy math)
        per admitted sequence, then one scatter into the arena."""
        while True:
            with self._lock:
                if not self._queue or self._n_live >= self.max_batch_size:
                    return
                seq = self._queue.popleft()
            with self._dev():
                self._ensure_arena()
                cache1, enc1, tok0 = self._jit_prefill(
                    self.params, seq.inputs[None], seq.input_mask[None]
                )
                t0 = int(tok0)
                seq.tokens.append(t0)
                if t0 == self.eos_id or seq.max_new_tokens <= 1:
                    self._complete(seq)
                    continue
                slot = self._n_live
                self._arena = self._jit_insert(
                    self._arena, cache1, enc1, seq.input_mask[None], tok0,
                    np.int32(slot),
                )
            if seq.ctx is not None:
                # Slot event: the sequence joined the continuous batch —
                # the wait it paid in the queue is arrival -> now.
                seq.ctx.span_from_mono(
                    "decode.join", seq.arrival_s,
                    slot=slot, budget_tokens=seq.max_new_tokens,
                )
            with self._lock:
                self._slots[slot] = seq
                self._n_live += 1

    def _step_once(self) -> None:
        n = self._n_live
        b = next(bk for bk in self.batch_buckets if bk >= n)
        deepest = max(
            len(s.tokens) for s in self._slots[:n] if s is not None
        )
        kv = next(k for k in self.kv_buckets if k >= deepest + 1)
        fn = self._step_for(b, kv)
        t0 = time.perf_counter()
        with self._dev():
            self._arena, nxt = fn(self.params, self._arena)
            toks = np.asarray(nxt)  # the one device->host sync per step
        dt = time.perf_counter() - t0
        if self.step_ewma_s is None:
            self.step_ewma_s = dt
        else:
            a = self.STEP_EWMA_ALPHA
            self.step_ewma_s = (1 - a) * self.step_ewma_s + a * dt
        self.steps_run += 1
        pages = sum(
            -(-(len(s.tokens) + 1) // self._page)
            for s in self._slots[:n] if s is not None
        )
        self.telemetry.on_step(dt, self.step_ewma_s, n, b, pages, int(n))
        now = time.monotonic()
        for slot in range(n - 1, -1, -1):
            seq = self._slots[slot]
            t = int(toks[slot])
            seq.tokens.append(t)
            self.telemetry.on_token()
            if seq.ctx is not None:
                # Per decode-step slot event: which step, which program
                # bucket pair — the trace shows exactly which steps this
                # sequence rode and with how much co-batched company.
                seq.ctx.instant(
                    "decode.step", slot=slot, token=len(seq.tokens),
                    batch_bucket=b, kv_bucket=kv, live=n,
                    step_s=round(dt, 6),
                )
            done = (
                t == self.eos_id or len(seq.tokens) >= seq.max_new_tokens
            )
            # Retire the slot BEFORE waking the waiter: the client thread
            # resumes to consistent accounting (outstanding_tokens of a
            # finished sequence is already 0, its slot already free).
            if done:
                if seq.ctx is not None and t == self.eos_id:
                    seq.ctx.instant(
                        "decode.eos", slot=slot, tokens=len(seq.tokens)
                    )
                self._retire(slot)
                self._complete(seq)
            elif (
                self.hard_deadline
                and seq.deadline_s is not None
                and now > seq.deadline_s
            ):
                self.telemetry.on_evicted()
                self._retire(slot)
                self._evict_seq(
                    seq, slot,
                    f"per-token SLO deadline exceeded after "
                    f"{len(seq.tokens)}/{seq.max_new_tokens} tokens",
                )

    def _retire(self, slot: int) -> None:
        with self._dev():
            last = self._n_live - 1
            if slot != last:
                self._arena = self._jit_move(
                    self._arena, np.int32(last), np.int32(slot)
                )
            self._arena = self._jit_clear(self._arena, np.int32(last))
        with self._lock:
            if slot != self._n_live - 1:
                self._slots[slot] = self._slots[self._n_live - 1]
            self._slots[self._n_live - 1] = None
            self._n_live -= 1

    def _complete(self, seq: _Sequence) -> None:
        latency = time.monotonic() - seq.arrival_s
        self.telemetry.on_done(latency, len(seq.tokens))
        self._trace_end(seq, "complete")
        seq.finish()

    def _evict_seq(self, seq: _Sequence, slot: int, reason: str) -> None:
        if seq.ctx is not None:
            seq.ctx.instant(
                "decode.evict", slot=slot, tokens=len(seq.tokens),
                reason=reason,
            )
        self._trace_end(seq, "evicted")
        seq.finish(GenerationEvicted(reason))

    def _trace_end(self, seq: _Sequence, status: str) -> None:
        """The whole-lifetime ``decode`` span (arrival -> end): emitted
        for EVERY terminal edge — EOS, budget, eviction, engine death —
        so a stream's trace always covers its full decode lifetime."""
        if seq.ctx is None:
            return
        seq.ctx.complete_span(
            "decode", seq.arrival_wall_s, seq.arrival_s,
            time.monotonic() - seq.arrival_s,
            status=status, tokens=len(seq.tokens),
            budget_tokens=seq.max_new_tokens,
        )


class DecodeTelemetry:
    """The ``serving_decode_*`` family, shared by every engine of one
    replica (one label set per replica, however many versions are
    resident mid-drain).  All methods are no-ops without a registry."""

    def __init__(self, registry=None, replica: str = "0"):
        self.replica = str(replica)
        self._steps = self._tokens = self._seqs = self._evicted = None
        self._shed = self._occ = self._pages = self._active = None
        self._queue_tokens = self._step_s = self._per_token = None
        self._compiles = None
        if registry is None:
            return
        from tpu_pipelines.observability.metrics import fine_latency_buckets

        lab = ("replica",)
        self._steps = registry.counter(
            "serving_decode_steps_total",
            "Continuous-batch decode steps executed.", labels=lab,
        ).labels(self.replica)
        self._tokens = registry.counter(
            "serving_decode_tokens_total",
            "Tokens emitted by the continuous-batch engine.", labels=lab,
        ).labels(self.replica)
        self._seqs = registry.counter(
            "serving_decode_sequences_total",
            "Generations completed (EOS or max_new_tokens).", labels=lab,
        ).labels(self.replica)
        self._evicted = registry.counter(
            "serving_decode_evicted_total",
            "Sequences evicted before finishing (per-token SLO deadline "
            "or engine shutdown).", labels=lab,
        ).labels(self.replica)
        self._shed = registry.counter(
            "serving_decode_shed_total",
            "Sequences refused by token-level admission control.",
            labels=lab,
        ).labels(self.replica)
        self._occ = registry.gauge(
            "serving_decode_batch_occupancy",
            "Live sequences / batch bucket of the most recent step.",
            labels=lab,
        ).labels(self.replica)
        self._pages = registry.gauge(
            "serving_decode_cache_pages_in_use",
            "KV-cache pages covering every live sequence's positions.",
            labels=lab,
        ).labels(self.replica)
        self._active = registry.gauge(
            "serving_decode_sequences_active",
            "Sequences live in the decode arena.", labels=lab,
        ).labels(self.replica)
        self._queue_tokens = registry.gauge(
            "serving_decode_queue_tokens",
            "Outstanding decode tokens (live remainder + queued budgets).",
            labels=lab,
        ).labels(self.replica)
        self._step_s = registry.gauge(
            "serving_decode_step_seconds",
            "EWMA wall time of one continuous-batch decode step.",
            labels=lab,
        ).labels(self.replica)
        # Fine sqrt(2) ladder (metrics.fine_latency_buckets): a decode
        # step runs in the tens-to-hundreds of µs, BELOW the default x2
        # ladder's 100µs floor — on the default ladder every per-token
        # observation piled into the first two buckets and a scraped
        # quantile was meaningless.
        self._per_token = registry.histogram(
            "serving_decode_per_token_latency_seconds",
            "Completed-generation latency divided by tokens emitted — "
            "the per-token SLO judge (fine sqrt(2) buckets).",
            labels=lab, buckets=fine_latency_buckets(),
        ).labels(self.replica)
        self._compiles = registry.counter(
            "serving_decode_compiles_after_warm_total",
            "Decode-step programs compiled AFTER warm() — each one is a "
            "broken warmup contract (an XLA compile paid mid-traffic); "
            "the SLO monitor treats any increase as a breach.",
            labels=lab,
        ).labels(self.replica)

    def on_step(self, dt, ewma, live, bucket, pages, active) -> None:
        if self._steps is None:
            return
        self._steps.inc()
        self._occ.set(live / max(1, bucket))
        self._pages.set(pages)
        self._active.set(active)
        self._step_s.set(ewma)

    def on_token(self) -> None:
        if self._tokens is not None:
            self._tokens.inc()

    def on_done(self, latency_s: float, n_tokens: int) -> None:
        if self._seqs is None:
            return
        self._seqs.inc()
        self._per_token.observe(latency_s / max(1, n_tokens))

    def on_evicted(self) -> None:
        if self._evicted is not None:
            self._evicted.inc()

    def on_shed(self) -> None:
        if self._shed is not None:
            self._shed.inc()

    def on_queue(self, outstanding_tokens: int) -> None:
        if self._queue_tokens is not None:
            self._queue_tokens.set(outstanding_tokens)

    def on_compile_after_warm(self) -> None:
        if self._compiles is not None:
            self._compiles.inc()
