"""Continuous batching for autoregressive decode — the generative engine.

The PR 10 fleet batches *whole requests*: an autoregressive request owns
its replica for its entire decode, so one long generation stalls every
request co-batched behind it, and a replica decoding a 4-token reply and
one decoding a 500-token reply cost the router the same.  This engine
batches at the *decode-step* level instead (iteration-level scheduling,
Orca OSDI '22): sequences join the running batch the moment a slot is
free and leave the moment they emit EOS or hit ``max_new_tokens`` —
every device step serves exactly the sequences that still need tokens.

Mechanics (the vLLM/PagedAttention shape of the idea, on the repo's
static-shape substrate):

  * **Arena.**  One device-resident state pool sized ``max_batch_size``:
    the flax decode cache (self-attention K/V at ``max_decode_len``,
    cross-attention K/V at the encoder length), per-slot last token,
    position, live flag, encoder output and mask.  Live sequences occupy
    the compacted prefix ``[0, n_live)``; a departure moves the last live
    row into the hole (one scatter), an arrival lands at ``n_live`` (one
    scatter) — no host-side repacking of the cache, ever.
  * **Bucketed steps.**  Each decode step runs one pre-compiled program
    keyed ``(batch_bucket, kv_bucket)``: the batch bucket is the smallest
    power-of-two >= the live count (serving/batching.py's bucket rule),
    the KV bucket the smallest page multiple covering the deepest live
    position.  ``warm()`` compiles every combination up front — the
    fleet's canary gate calls it BEFORE a version becomes eligible, so no
    decode step pays an XLA compile mid-traffic (``compiles_after_warm``
    is the auditable contract).  Pages are an allocation/accounting unit:
    ``serving_decode_cache_pages_in_use`` is what capacity planning reads.
  * **Identity.**  The per-row decode math is exactly the scalar-position
    math greedy/beam run (models/transformer.py vector ``decode_pos``;
    the batch dimension is bitwise row-independent), so a sequence's
    token stream is bit-identical to an isolated single-request greedy
    decode regardless of who it shared steps with.  KV bucketing keeps
    masked positions at exact zero contribution, but XLA tiles a
    contraction differently per length, so *across different KV buckets*
    logits can drift by ~1 ulp — the same property every paged-attention
    kernel has.  ``page_size=0`` (one bucket = the whole cache) makes the
    stream bitwise under any schedule; the identity test pins that mode.
  * **Per-token SLO.**  Admission control counts outstanding *tokens*
    (``max_queue_tokens``), not requests: a queued 500-token generation
    is 125x the work of a 4-token one and the door should know.  With
    ``slo_ms_per_token`` each sequence carries a token-proportional
    deadline (serving/batching.py ``token_deadline_s``); ``hard_deadline``
    evicts a sequence that blows it (``GenerationEvicted``), freeing its
    slot for work that can still meet SLO.

Decode optimisations (ISSUE 16) — three composable levers behind the
same ``make_decode_fns`` contract, each off by default:

  * **Prefix caching** (``prefix_cache_entries > 0``).  Prompts are
    hashed as a chain of ``page_size``-granular token blocks
    (:meth:`PrefixCache.key_of`); a full-chain hit means an identical
    (masked-inputs, mask) prompt already ran prefill, so ``_admit``
    reuses the cached device-resident prefill result — cache row,
    encoder output, first token — and skips the encoder pass entirely.
    Entries are REFCOUNTED: every live sequence admitted from an entry
    holds a reader reference, and an entry's pages are freed only when
    its last reader retires (LRU eviction considers only entries with
    zero readers).  Hits are bitwise-exact: the cached arrays are the
    actual outputs of the same compiled prefill program on the same
    input, so greedy logits equal the uncached path exactly (the ~1 ulp
    cross-KV-bucket caveat above is unchanged).
  * **Chunked prefill** (``prefill_chunk_pages > 0``).  Admission work
    is metered in prompt pages: each decode step earns the scheduler
    ``prefill_chunk_pages`` credits, and an admission costs the prompt's
    page count (1 for a prefix-cache hit) — so a burst of long-prompt
    arrivals is spread across decode steps instead of running
    back-to-back and stalling every live sequence's token deadline.  On
    this substrate one prompt's prefill is a single device program (the
    encoder is bidirectional — not token-chunkable without changing the
    math), so chunking bounds the admission work *between* steps; the
    compiled programs are identical with the knob on or off, which keeps
    token streams bitwise-identical either way.
  * **Speculative decoding** (``spec_tokens k > 0``).  A draft model
    (any ``make_decode_fns`` contract sharing the target's geometry;
    ``draft_fns=None`` means self-draft — the target drafts for itself,
    the trivial 100%%-acceptance case) runs ``k`` chained steps on its
    own mirrored arena, then the target scores all ``k`` fed positions
    (current token + the first k-1 proposals) in ONE bucketed program
    (``fns.verify`` when the contract exports it, e.g. ``models/t5.py``;
    otherwise ``k`` fused ``fns.step`` launches — same math) and
    the engine emits the accepted prefix plus the target's own token at
    the first mismatch — every emitted token is either verified equal to
    the target's greedy choice or IS the target's greedy choice, so a
    wrong draft costs speed, never correctness.  Rejected tail KV needs
    no rollback: position validity masks it at exact zero weight and
    later writes overwrite it.  Acceptance counters join the
    ``serving_decode_*`` family (``serving_decode_spec_accept_*``).

Metrics (``serving_decode_*``, labeled per replica; catalog in
docs/SERVING.md): steps/s, tokens/s, batch occupancy, cache pages in
use, active/queued sequences + outstanding tokens, per-token latency
histogram, evictions, step-time EWMA (what the router reads), prefix
cache hits/misses/resident pages, speculative proposals/acceptances.
"""

from __future__ import annotations

import collections
import contextlib
import hashlib
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from tpu_pipelines.serving.batching import (
    bucket_sizes,
    token_deadline_s,
    validate_generation_params,
)

log = logging.getLogger("tpu_pipelines.serving")


class EngineOverloaded(RuntimeError):
    """Token-level admission control refused the sequence: outstanding
    decode work (live + queued tokens) already exceeds the configured
    bound.  Maps to HTTP 429 + Retry-After, like ``ServerOverloaded`` —
    shed at the door, counted, never dropped mid-decode."""

    retry_after_s = 1


class GenerationEvicted(RuntimeError):
    """The sequence was evicted before finishing — its per-token SLO
    deadline passed under ``hard_deadline=True``, or the engine closed.
    Maps to a retriable 503: the server is healthy, this generation lost
    its latency race."""


class DecodeSessionLost(RuntimeError):
    """A replica died with generations in flight.  Raised by the
    supervised fleet's decode path instead of the raw worker-death
    exception, carrying each sequence's progress (the tokens the engine
    had already committed) so the fleet can re-prefill prompt + accepted
    tokens onto a surviving replica and continue the streams — greedy
    decode is deterministic, so the recovered stream is bitwise
    identical to an uninterrupted one."""

    def __init__(self, cause, partial_tokens=None, unfinished=0):
        super().__init__(
            f"decode session lost: {type(cause).__name__}: {cause}"
        )
        self.cause = cause
        self.partial_tokens = list(partial_tokens or [])
        self.unfinished = int(unfinished)


@dataclass
class _Sequence:
    """Host-side bookkeeping for one generation (the engine's unit of
    scheduling).  ``tokens`` mirrors the device state: its length IS the
    sequence's next decode position."""

    inputs: np.ndarray              # [max_input_len] padded token ids
    input_mask: np.ndarray          # [max_input_len] 1/0 validity
    max_new_tokens: int
    arrival_s: float
    deadline_s: Optional[float]
    tokens: List[int] = field(default_factory=list)
    _done: threading.Event = field(default_factory=threading.Event)
    result: Optional[np.ndarray] = None
    error: Optional[BaseException] = None
    # Request-trace context (None = untraced): rides the sequence across
    # the client->worker thread boundary so decode-step slot events land
    # on the originating request's trace.
    ctx: Any = None
    arrival_wall_s: float = 0.0
    # Prefix-cache entry this live sequence holds a reader reference on
    # (None = admitted without the cache, or reference already released).
    prefix_entry: Any = None

    def finish(self, error: Optional[BaseException] = None) -> None:
        if self._done.is_set():
            return
        if error is not None:
            self.error = error
        else:
            self.result = np.asarray(self.tokens, np.int32)
        self._done.set()

    def wait(self, timeout_s: float) -> np.ndarray:
        if not self._done.wait(timeout_s):
            raise TimeoutError("generation did not complete in time")
        if self.error is not None:
            raise self.error
        return self.result


def kv_bucket_sizes(max_decode_len: int, page_size: int) -> List[int]:
    """KV-cache length buckets: page, 2*page, 4*page, ... capped at the
    full cache.  ``page_size <= 0`` means one bucket — the whole cache —
    which is also the bitwise-exact mode (see module docstring)."""
    max_decode_len = int(max_decode_len)
    if max_decode_len <= 0:
        raise ValueError(
            f"max_decode_len must be positive, got {max_decode_len}"
        )
    if page_size <= 0 or page_size >= max_decode_len:
        return [max_decode_len]
    out = []
    k = int(page_size)
    while k < max_decode_len:
        out.append(k)
        k *= 2
    out.append(max_decode_len)
    return sorted(set(out))


def _is_enc_leaf(path) -> bool:
    """Cross-attention K/V leaves keep the ENCODER length on axis 1 (not
    the decode cache length) and are never written by a decode step."""
    return any("cached_enc" in str(getattr(p, "key", p)) for p in path)


class _PrefixEntry:
    """One cached prompt prefix: the device-resident prefill result plus
    refcount/LRU bookkeeping.  ``pages`` is the prompt's page-granular
    block count — the unit ``serving_decode_prefix_pages_in_use``
    reports and admission credits are charged in."""

    __slots__ = (
        "key", "pages", "readers", "tok0", "cache", "encoded",
        "draft_cache", "draft_encoded", "tick",
    )

    def __init__(self, key, pages, tok0, cache, encoded,
                 draft_cache=None, draft_encoded=None):
        self.key = key
        self.pages = int(pages)
        self.readers = 0
        self.tok0 = int(tok0)
        self.cache = cache
        self.encoded = encoded
        self.draft_cache = draft_cache
        self.draft_encoded = draft_encoded
        self.tick = 0


class PrefixCache:
    """Refcounted cache of prefill results keyed by page-granular block
    hashes of the prompt.

    The key is a CHAIN of block hashes — block ``i``'s digest folds the
    previous block's digest with ``page`` positions of (masked-inputs,
    mask) — so two prompts collide only when every block matches, i.e.
    the model-visible prompt is identical (masked positions are zeroed
    before hashing: their values never reach a logit — padding K/V is
    masked at exact zero weight — so they must not split the key).

    Refcounting is the page-lifetime contract: every live sequence
    admitted from an entry holds a reader reference, ``trim`` may evict
    only entries with ZERO readers (LRU among those), and an over-
    capacity entry is therefore freed exactly when its last reader
    retires.  Single-threaded by design: the engine's worker thread owns
    every lookup/insert/acquire, and release happens on the worker or
    after it has been joined (``close``)."""

    def __init__(self, capacity: int, page: int):
        self.capacity = max(1, int(capacity))
        self.page = max(1, int(page))
        self._entries: Dict[bytes, _PrefixEntry] = {}
        self._tick = 0
        self.hits = 0
        self.misses = 0

    @staticmethod
    def key_of(
        inputs: np.ndarray, input_mask: np.ndarray, page: int
    ) -> Tuple[bytes, int]:
        """(chain-tip digest, valid-prefix page count) for one padded
        prompt.  Hashing covers the full padded width so mask structure
        (including interior zeros, which shift relative positions) is
        part of the identity; the page count covers only valid tokens —
        the prefill work a hit actually skips."""
        page = max(1, int(page))
        mask = (np.asarray(input_mask) > 0)
        toks = np.asarray(inputs, np.int64) * mask
        m8 = mask.astype(np.int8)
        n_valid = int(mask.sum())
        pages = max(1, -(-n_valid // page))
        h = b""
        for i in range(0, max(toks.size, 1), page):
            h = hashlib.blake2b(
                h + toks[i:i + page].tobytes() + m8[i:i + page].tobytes(),
                digest_size=16,
            ).digest()
        return h, pages

    def __len__(self) -> int:
        return len(self._entries)

    def peek(self, key: bytes) -> Optional[_PrefixEntry]:
        return self._entries.get(key)

    def touch(self, entry: _PrefixEntry) -> None:
        self._tick += 1
        entry.tick = self._tick

    def insert(
        self, key, pages, tok0, cache, encoded,
        draft_cache=None, draft_encoded=None,
    ) -> _PrefixEntry:
        entry = self._entries.get(key)
        if entry is None:
            entry = _PrefixEntry(
                key, pages, tok0, cache, encoded, draft_cache, draft_encoded
            )
            self._entries[key] = entry
        self.touch(entry)
        self.trim()
        return entry

    def acquire(self, entry: _PrefixEntry) -> None:
        entry.readers += 1

    def release(self, entry: _PrefixEntry) -> None:
        entry.readers = max(0, entry.readers - 1)
        self.trim()

    def trim(self) -> None:
        """Evict LRU zero-reader entries down to capacity.  Entries with
        live readers are PINNED — the cache may run over capacity while
        readers hold pages, and shrinks the moment the last one lets go.
        The most-recently-touched entry is never the victim: without that
        rule a fresh insert into a cache whose capacity is held by pinned
        entries would evict ITSELF (it is the only zero-reader), killing
        the hot prompt's residency exactly when sharing is highest."""
        while len(self._entries) > self.capacity:
            newest = max(self._entries.values(), key=lambda e: e.tick)
            victims = [
                e for e in self._entries.values()
                if e.readers == 0 and e is not newest
            ]
            if not victims:
                return
            victim = min(victims, key=lambda e: e.tick)
            del self._entries[victim.key]

    def pages_in_use(self) -> int:
        return sum(e.pages for e in self._entries.values())


class GenerativeEngine:
    """One continuous-batching decode engine over one (model, params).

    ``fns`` is the duck-typed decode contract (see
    ``models/t5.py make_continuous_decode_fns``): ``prefill``/``step``
    plus geometry constants.  The engine owns a single worker thread; all
    device work — prefill, bucketed steps, arena scatters — happens
    there, so the jit-compiled programs never race.  ``submit`` blocks
    like ``RequestBatcher.submit``; ``submit_nowait`` returns a handle
    the fleet uses to run one request's rows concurrently.
    """

    # EWMA smoothing for the observed decode-step wall time (the router's
    # cost signal); same constant family as RequestBatcher.
    STEP_EWMA_ALPHA = 0.25

    def __init__(
        self,
        fns,
        params,
        *,
        max_batch_size: int = 8,
        page_size: int = 0,
        max_queue_tokens: int = 0,
        slo_ms_per_token: float = 0.0,
        hard_deadline: bool = False,
        prefix_cache_entries: int = 0,
        prefill_chunk_pages: int = 0,
        spec_tokens: int = 0,
        draft_fns: Any = None,
        draft_params: Any = None,
        device: Any = None,
        telemetry: Optional["DecodeTelemetry"] = None,
        registry=None,
        replica: str = "0",
        fault_hook: Any = None,
    ):
        # Supervision seam: called once per worker-loop round while work
        # is live; an exception here kills the worker exactly like a
        # device fault (the fleet's injected-kill path for decode).
        self._fault_hook = fault_hook
        self.fns = fns
        self.params = params
        self.max_decode_len = int(fns.max_decode_len)
        self.eos_id = int(fns.eos_id)
        self.pad_id = int(fns.pad_id)
        self.max_input_len = int(getattr(fns, "max_input_len", 64))
        self.max_batch_size = max(1, int(max_batch_size))
        self.page_size = int(page_size)
        self.max_queue_tokens = max(0, int(max_queue_tokens))
        self.slo_ms_per_token = max(0.0, float(slo_ms_per_token))
        self.hard_deadline = bool(hard_deadline)
        self.device = device
        self.batch_buckets = bucket_sizes(self.max_batch_size)
        self.kv_buckets = kv_bucket_sizes(self.max_decode_len, self.page_size)
        self._page = (
            self.page_size if 0 < self.page_size < self.max_decode_len
            else self.max_decode_len
        )
        # Prompt-side page unit (prefix hashing + admission credits):
        # the configured page size, or the whole prompt when unpaged.
        self._ppage = (
            self.page_size if self.page_size > 0 else self.max_input_len
        )
        self.prefix_cache_entries = max(0, int(prefix_cache_entries))
        self._prefix = (
            PrefixCache(self.prefix_cache_entries, self._ppage)
            if self.prefix_cache_entries > 0 else None
        )
        self.prefill_chunk_pages = max(0, int(prefill_chunk_pages))
        self._admit_credits = 0
        self.spec_tokens = max(0, int(spec_tokens))
        self._spec = self.spec_tokens > 0
        if self._spec:
            # draft_fns=None = self-draft: the target proposes for itself
            # on a mirrored arena — zero speedup, 100% acceptance, the
            # machinery's trivial correctness case.
            self.draft_fns = draft_fns if draft_fns is not None else fns
            self.draft_params = (
                draft_params if draft_params is not None else params
            )
            d = self.draft_fns
            if (
                int(d.max_decode_len) != self.max_decode_len
                or int(d.eos_id) != self.eos_id
                or int(d.pad_id) != self.pad_id
                or int(getattr(d, "max_input_len", self.max_input_len))
                != self.max_input_len
            ):
                raise ValueError(
                    "draft decode contract must share the target's "
                    "geometry (max_decode_len/eos_id/pad_id/max_input_len)"
                )
        else:
            self.draft_fns = None
            self.draft_params = None
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.telemetry = telemetry or DecodeTelemetry(registry, replica)

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: "collections.deque[_Sequence]" = collections.deque()
        self._slots: List[Optional[_Sequence]] = (
            [None] * self.max_batch_size
        )
        self._n_live = 0
        self._closed = False
        # Worker died (device fault / injected kill): reject new submits
        # immediately instead of queueing work nothing will ever serve.
        self._dead = False
        self._arena = None
        self._warmed = False
        self.compiles_after_warm = 0
        self.steps_run = 0
        self.step_ewma_s: Optional[float] = None

        self._step_fns: Dict[Tuple[int, int], Any] = {}
        self._jit_prefill = None
        self._jit_insert = None
        self._jit_move = None
        self._jit_clear = None
        self._jit_accept = None
        # Draft lane (speculative decoding): a second arena mirroring
        # every slot, stepped by the draft contract's own programs.
        self._d_arena = None
        self._d_step_fns: Dict[Tuple[int, int], Any] = {}
        self._verify_fns: Dict[Tuple[int, int], Any] = {}
        self._d_jit_prefill = None
        self._d_jit_insert = None
        self._d_jit_move = None
        self._d_jit_clear = None

        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    # ---------------------------------------------------------- device ctx

    def _dev(self):
        if self.device is None:
            return contextlib.nullcontext()
        import jax

        return jax.default_device(self.device)

    # ------------------------------------------------------- compiled fns

    def _lane_jits(self, fns) -> Tuple[Any, Any, Any, Any]:
        """(prefill, insert, move, clear) jits for one decode contract —
        the target lane always, plus the draft lane when speculative."""
        import jax
        import jax.numpy as jnp

        def prefill(params, inputs, input_mask):
            cache, encoded, logits = fns.prefill(params, inputs, input_mask)
            return cache, encoded, jnp.argmax(logits[0], -1).astype(jnp.int32)

        def insert(state, pcache, encoded, enc_mask, tok0, slot):
            cache, tok, pos, live, enc, mask = state
            cache = jax.tree_util.tree_map(
                lambda a, p: a.at[slot].set(p[0].astype(a.dtype)),
                cache, pcache,
            )
            return (
                cache,
                tok.at[slot].set(tok0),
                pos.at[slot].set(1),
                live.at[slot].set(True),
                enc.at[slot].set(encoded[0].astype(enc.dtype)),
                mask.at[slot].set(jnp.asarray(enc_mask[0], mask.dtype)),
            )

        def move(state, src, dst):
            return tuple(
                jax.tree_util.tree_map(lambda a: a.at[dst].set(a[src]), part)
                for part in state
            )

        def clear(state, slot):
            cache, tok, pos, live, enc, mask = state
            return (
                cache,
                tok.at[slot].set(self.pad_id),
                pos.at[slot].set(0),
                live.at[slot].set(False),
                enc,
                mask,
            )

        return (
            jax.jit(prefill), jax.jit(insert), jax.jit(move), jax.jit(clear)
        )

    def _build_jits(self) -> None:
        import jax

        (
            self._jit_prefill, self._jit_insert,
            self._jit_move, self._jit_clear,
        ) = self._lane_jits(self.fns)
        if self._spec:
            (
                self._d_jit_prefill, self._d_jit_insert,
                self._d_jit_move, self._d_jit_clear,
            ) = self._lane_jits(self.draft_fns)

        import jax.numpy as jnp

        def accept(state, new_tok, new_pos):
            # Speculative accept / step-sync: replace the whole tok/pos
            # vectors with host-composed values (dead rows carry
            # pad_id/0, matching clear's convention), and SCRUB cache
            # positions >= new_pos to exact zero.  Attention already
            # masks those positions, so for a masked contract this is a
            # value-level no-op (kept entries multiply by 1) — but it
            # makes "rejected speculative KV never reaches a logit" an
            # enforced invariant of the arena rather than a property
            # each decode contract must supply.
            cache, tok, pos, live, enc, mask = state

            def scrub(path, a):
                if _is_enc_leaf(path):
                    return a
                valid = jnp.arange(a.shape[1]) < new_pos[:, None]
                v = valid.reshape(valid.shape + (1,) * (a.ndim - 2))
                return a * v.astype(a.dtype)

            cache = jax.tree_util.tree_map_with_path(scrub, cache)
            return (cache, new_tok, new_pos, live, enc, mask)

        self._jit_accept = jax.jit(accept)

    def _build_step(self, b: int, kv: int, fns):
        import jax
        import jax.numpy as jnp

        pad = self.pad_id

        def run(params, state):
            cache, tok, pos, live, encoded, enc_mask = state
            sub = jax.tree_util.tree_map_with_path(
                lambda p, x: x[:b] if _is_enc_leaf(p) else x[:b, :kv], cache
            )
            new_sub, logits = fns.step(
                params, sub, tok[:b], pos[:b], encoded[:b], enc_mask[:b], kv
            )
            nxt = jnp.where(
                live[:b], jnp.argmax(logits, -1).astype(jnp.int32), pad
            )
            cache = jax.tree_util.tree_map_with_path(
                lambda p, a, n: a if _is_enc_leaf(p) else a.at[:b, :kv].set(n),
                cache, new_sub,
            )
            tok = tok.at[:b].set(nxt)
            pos = pos.at[:b].set(pos[:b] + live[:b].astype(jnp.int32))
            return (cache, tok, pos, live, encoded, enc_mask), nxt

        return jax.jit(run)

    def _build_verify(self, b: int, kv: int):
        """One bucketed target-verify program: score ``k = spec_tokens``
        candidate positions in ONE device step via the contract's
        ``verify`` (or ``k`` fused single-steps when the contract lacks
        it — same math, k launches).  Returns the updated cache plus
        greedy picks ``g[b, k]`` where ``g[:, j]`` is the target's choice
        at position ``pos + j`` given the fed tokens."""
        import jax
        import jax.numpy as jnp

        fns = self.fns
        k = self.spec_tokens
        verify = getattr(fns, "verify", None)

        def run(params, state, toks):
            # toks[b, k]: column 0 is each row's current last emitted
            # token, columns 1..k-1 the draft's first k-1 proposals.
            cache, tok, pos, live, encoded, enc_mask = state
            sub = jax.tree_util.tree_map_with_path(
                lambda p, x: x[:b] if _is_enc_leaf(p) else x[:b, :kv], cache
            )
            if verify is not None:
                new_sub, logits = verify(
                    params, sub, toks[:b], pos[:b],
                    encoded[:b], enc_mask[:b], kv,
                )
            else:
                outs = []
                new_sub = sub
                for j in range(k):
                    new_sub, lg = fns.step(
                        params, new_sub, toks[:b, j], pos[:b] + j,
                        encoded[:b], enc_mask[:b], kv,
                    )
                    outs.append(lg)
                logits = jnp.stack(outs, axis=1)
            g = jnp.argmax(logits, -1).astype(jnp.int32)  # [b, k]
            cache = jax.tree_util.tree_map_with_path(
                lambda p, a, n: a if _is_enc_leaf(p) else a.at[:b, :kv].set(n),
                cache, new_sub,
            )
            return (cache, tok, pos, live, encoded, enc_mask), g

        return jax.jit(run)

    def _program_for(self, cache, build, kind, b: int, kv: int):
        fn = cache.get((b, kv))
        if fn is None:
            if self._warmed:
                # The warmup contract: every (batch, kv) bucket program is
                # compiled before traffic.  A post-warm build means a
                # bucket the warmup missed — counted, loud, and the
                # warmup-contract test's assertion.
                self.compiles_after_warm += 1
                self.telemetry.on_compile_after_warm()
                log.warning(
                    "generative engine: compiling %s (%d, %d) AFTER "
                    "warmup — bucket missed by warm()", kind, b, kv,
                )
            fn = build(b, kv)
            cache[(b, kv)] = fn
        return fn

    def _step_for(self, b: int, kv: int):
        return self._program_for(
            self._step_fns, lambda b, kv: self._build_step(b, kv, self.fns),
            "step", b, kv,
        )

    def _d_step_for(self, b: int, kv: int):
        return self._program_for(
            self._d_step_fns,
            lambda b, kv: self._build_step(b, kv, self.draft_fns),
            "draft step", b, kv,
        )

    def _verify_for(self, b: int, kv: int):
        return self._program_for(
            self._verify_fns, self._build_verify, "verify", b, kv,
        )

    # ------------------------------------------------------------- arena

    def _ensure_arena(self) -> None:
        if self._arena is not None:
            return
        import jax
        import jax.numpy as jnp

        if self._jit_prefill is None:
            self._build_jits()
        with self._dev():
            # Commit params AND the arena to one device up front.  The
            # jit program cache keys on each argument's placement, not
            # just its shape: an exported payload's params arrive
            # COMMITTED (orbax restore), so step outputs — the next
            # step's arena — are committed too, and a warmup that ran on
            # an uncommitted pristine arena would silently recompile
            # every bucket program on its first real-traffic step (~1 s
            # stalls that defeat the whole warm() contract).  One
            # explicit placement makes warm and traffic byte-identical
            # cache keys — the warmup-contract test pins this.
            dev = self.device
            if dev is None:
                dev = jax.local_devices()[0]
            self.params = jax.device_put(self.params, dev)
            zin = jnp.full((1, self.max_input_len), self.pad_id, jnp.int32)
            zmask = jnp.zeros((1, self.max_input_len), jnp.int32)
            B = self.max_batch_size

            def blank_arena(prefill_jit, params):
                cache1, encoded1, _ = prefill_jit(params, zin, zmask)
                cache = jax.tree_util.tree_map(
                    lambda x: jnp.zeros((B,) + x.shape[1:], x.dtype), cache1
                )
                # Free rows keep an all-ONES encoder mask: cross-attention
                # over their zero K/V then averages zeros instead of
                # softmaxing an all-masked row into NaN.  Live rows
                # overwrite it on insert.
                return jax.device_put((
                    cache,
                    jnp.full((B,), self.pad_id, jnp.int32),
                    jnp.zeros((B,), jnp.int32),
                    jnp.zeros((B,), bool),
                    jnp.zeros((B,) + encoded1.shape[1:], encoded1.dtype),
                    jnp.ones((B, self.max_input_len), jnp.int32),
                ), dev)

            self._arena = blank_arena(self._jit_prefill, self.params)
            if self._spec:
                self.draft_params = jax.device_put(self.draft_params, dev)
                self._d_arena = blank_arena(
                    self._d_jit_prefill, self.draft_params
                )

    def warm(self) -> None:
        """Pre-compile every program traffic can pose: prefill, insert /
        move / clear, and one step per ``(batch_bucket, kv_bucket)``.
        The fleet's canary gate runs this BEFORE a version becomes
        eligible — the decode analog of the predict-bucket warmup — so a
        hot-swap never pays an XLA compile mid-traffic.  Results are
        discarded; the arena is untouched (jax arrays are immutable).
        Arguments mirror the traffic paths exactly — host numpy inputs,
        the committed arena — so every call lands on the SAME program
        cache key traffic will use (see _ensure_arena on placement)."""
        with self._dev():
            self._ensure_arena()
            zin = np.full((1, self.max_input_len), self.pad_id, np.int32)
            zmask = np.zeros((1, self.max_input_len), np.int32)
            cache1, encoded1, tok0 = self._jit_prefill(
                self.params, zin, zmask
            )
            # tok0 goes to insert as a HOST int32: the prefix-cache hit
            # path has only the entry's host token, and warm/miss/hit
            # must all land on the same insert program cache key.
            self._jit_insert(
                self._arena, cache1, encoded1, zmask,
                np.int32(int(tok0)), np.int32(0),
            )
            self._jit_move(self._arena, np.int32(0), np.int32(0))
            self._jit_clear(self._arena, np.int32(0))
            for b in self.batch_buckets:
                for kv in self.kv_buckets:
                    self._step_for(b, kv)(self.params, self._arena)
            B = self.max_batch_size
            ztok = np.full((B,), self.pad_id, np.int32)
            zpos = np.zeros((B,), np.int32)
            self._jit_accept(self._arena, ztok, zpos)
            if self._spec:
                dc1, de1, dt0 = self._d_jit_prefill(
                    self.draft_params, zin, zmask
                )
                self._d_jit_insert(
                    self._d_arena, dc1, de1, zmask,
                    np.int32(int(dt0)), np.int32(0),
                )
                self._d_jit_move(self._d_arena, np.int32(0), np.int32(0))
                self._d_jit_clear(self._d_arena, np.int32(0))
                self._jit_accept(self._d_arena, ztok, zpos)
                zk = np.full(
                    (B, self.spec_tokens), self.pad_id, np.int32
                )
                for b in self.batch_buckets:
                    for kv in self.kv_buckets:
                        self._d_step_for(b, kv)(
                            self.draft_params, self._d_arena
                        )
                        self._verify_for(b, kv)(
                            self.params, self._arena, zk
                        )
        self._warmed = True

    # ------------------------------------------------------------- client

    def outstanding_tokens(self) -> int:
        """Decode work still owed: remaining tokens of live sequences plus
        every queued sequence's full budget — the admission-control and
        routing unit."""
        with self._lock:
            live = sum(
                max(0, s.max_new_tokens - len(s.tokens))
                for s in self._slots[: self._n_live] if s is not None
            )
            queued = sum(s.max_new_tokens for s in self._queue)
        return live + queued

    def active_sequences(self) -> int:
        with self._lock:
            return self._n_live + len(self._queue)

    def idle(self) -> bool:
        with self._lock:
            return self._n_live == 0 and not self._queue

    def submit_nowait(
        self,
        inputs,
        *,
        max_new_tokens: Optional[int] = None,
        input_mask=None,
        ctx=None,
    ) -> _Sequence:
        params = validate_generation_params(
            {} if max_new_tokens is None
            else {"max_new_tokens": max_new_tokens},
            max_decode_len=self.max_decode_len,
        )
        m = params["max_new_tokens"]
        inputs = np.asarray(inputs, np.int32).reshape(-1)
        if inputs.size == 0 or inputs.size > self.max_input_len:
            raise ValueError(
                f"input length must be in [1, {self.max_input_len}], "
                f"got {inputs.size}"
            )
        if input_mask is None:
            mask = np.ones(inputs.shape, np.int32)
        else:
            mask = np.asarray(input_mask, np.int32).reshape(-1)
        pad = self.max_input_len - inputs.size
        inputs = np.pad(inputs, (0, pad), constant_values=self.pad_id)
        mask = np.pad(mask, (0, pad))
        if self.max_queue_tokens > 0:
            owed = self.outstanding_tokens()
            if owed + m > self.max_queue_tokens:
                self.telemetry.on_shed()
                raise EngineOverloaded(
                    f"outstanding decode tokens {owed} + {m} exceed the "
                    f"bound {self.max_queue_tokens}"
                )
        now = time.monotonic()
        seq = _Sequence(
            inputs=inputs,
            input_mask=mask,
            max_new_tokens=m,
            arrival_s=now,
            deadline_s=token_deadline_s(now, m, self.slo_ms_per_token),
            ctx=ctx,
            arrival_wall_s=time.time(),
        )
        with self._cond:
            if self._closed:
                raise RuntimeError("engine is closed")
            if self._dead:
                raise RuntimeError("engine worker died")
            self._queue.append(seq)
            self.telemetry.on_queue(self.outstanding_tokens_locked())
            self._cond.notify_all()
        return seq

    def outstanding_tokens_locked(self) -> int:
        # Caller holds self._lock (the condition's underlying lock).
        live = sum(
            max(0, s.max_new_tokens - len(s.tokens))
            for s in self._slots[: self._n_live] if s is not None
        )
        return live + sum(s.max_new_tokens for s in self._queue)

    def submit(
        self,
        inputs,
        *,
        max_new_tokens: Optional[int] = None,
        input_mask=None,
        timeout_s: float = 300.0,
    ) -> np.ndarray:
        """Blocking generate for one sequence; returns the emitted token
        ids (EOS included when hit within budget)."""
        return self.submit_nowait(
            inputs, max_new_tokens=max_new_tokens, input_mask=input_mask
        ).wait(timeout_s)

    def close(self, timeout_s: float = 5.0, *, final_error=None) -> None:
        """Reject new submits and fail everything unfinished.  Sequences
        mid-decode get ``GenerationEvicted`` (the zero-drop contract is
        the fleet's: it only closes engines after the drain) —
        ``final_error`` overrides that verdict, which the supervised
        rebuild uses so racing waiters recover instead of surfacing a
        503."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._worker.join(timeout=timeout_s)
        with self._lock:
            pending = list(self._queue) + [
                s for s in self._slots[: self._n_live] if s is not None
            ]
            self._queue.clear()
            self._n_live = 0
            self._slots = [None] * self.max_batch_size
        for seq in pending:
            self._release_prefix(seq)
            self._trace_end(seq, "evicted")
            seq.finish(final_error or GenerationEvicted("engine closed"))

    # ------------------------------------------------------------- worker

    def _run(self) -> None:
        try:
            while True:
                with self._cond:
                    while (
                        not self._closed
                        and not self._queue
                        and self._n_live == 0
                    ):
                        self._cond.wait()
                    if self._closed:
                        return
                if self._fault_hook is not None:
                    self._fault_hook()
                self._admit()
                if self._n_live:
                    self._decode_round()
                    if self.prefill_chunk_pages > 0:
                        # Each decode round EARNS admission credits
                        # (chunked prefill's meter), capped at one full
                        # prompt so idle decode can't bank a stall-sized
                        # prefill burst.
                        cap = max(
                            self.prefill_chunk_pages,
                            -(-self.max_input_len // self._ppage),
                        )
                        self._admit_credits = min(
                            cap,
                            self._admit_credits + self.prefill_chunk_pages,
                        )
        except Exception as e:  # noqa: BLE001 — device fault: fail loudly
            log.exception("generative engine worker died")
            with self._lock:
                self._dead = True
                pending = list(self._queue) + [
                    s for s in self._slots[: self._n_live] if s is not None
                ]
                self._queue.clear()
                self._n_live = 0
            for seq in pending:
                self._release_prefix(seq)
                self._trace_end(seq, "error")
                seq.finish(e)

    def _decode_round(self) -> None:
        """One scheduling round: a speculative draft/verify round when
        enabled and every live position has ``spec_tokens`` of cache
        headroom, else one fused single-token step."""
        if self._spec:
            n = self._n_live
            deepest = max(
                len(s.tokens) for s in self._slots[:n] if s is not None
            )
            if deepest + self.spec_tokens <= self.max_decode_len:
                self._spec_round()
                return
        self._step_once()

    def _prompt_pages(self, seq: _Sequence) -> int:
        n_valid = int((seq.input_mask > 0).sum())
        return max(1, -(-n_valid // self._ppage))

    def _admit(self) -> None:
        """Iteration-level admission: fill free slots from the queue NOW —
        between two decode steps — instead of waiting for the batch to
        drain.  One prefill (encoder + step-0 decode, the greedy math)
        per admitted sequence — or an arena scatter alone when the
        prefix cache already holds this prompt — metered by chunked-
        prefill credits when live sequences could starve."""
        while True:
            with self._lock:
                if not self._queue or self._n_live >= self.max_batch_size:
                    return
                seq = self._queue[0]
                entry = key = None
                if self._prefix is not None:
                    key, pages = PrefixCache.key_of(
                        seq.inputs, seq.input_mask, self._ppage
                    )
                    entry = self._prefix.peek(key)
                else:
                    pages = self._prompt_pages(seq)
                cost = 1 if entry is not None else pages
                if (
                    self.prefill_chunk_pages > 0
                    and self._n_live > 0
                    and cost > self._admit_credits
                ):
                    # Not enough credits between steps: leave the head
                    # queued, decode earns more, admission resumes next
                    # round — a long prompt never skips a live
                    # sequence's token deadline.
                    return
                self._queue.popleft()
                if self.prefill_chunk_pages > 0 and self._n_live > 0:
                    self._admit_credits -= cost
            with self._dev():
                self._ensure_arena()
                d_cache1 = d_enc1 = None
                if entry is not None:
                    self._prefix.hits += 1
                    self._prefix.touch(entry)
                    self.telemetry.on_prefix_hit(entry.pages)
                    cache1, enc1 = entry.cache, entry.encoded
                    d_cache1, d_enc1 = entry.draft_cache, entry.draft_encoded
                    t0 = entry.tok0
                else:
                    cache1, enc1, tok0 = self._jit_prefill(
                        self.params, seq.inputs[None], seq.input_mask[None]
                    )
                    t0 = int(tok0)
                    if self._spec:
                        d_cache1, d_enc1, _ = self._d_jit_prefill(
                            self.draft_params,
                            seq.inputs[None], seq.input_mask[None],
                        )
                    if self._prefix is not None:
                        self._prefix.misses += 1
                        self.telemetry.on_prefix_miss()
                        entry = self._prefix.insert(
                            key, pages, t0, cache1, enc1, d_cache1, d_enc1
                        )
                seq.tokens.append(t0)
                if t0 == self.eos_id or seq.max_new_tokens <= 1:
                    if self._prefix is not None:
                        self.telemetry.on_prefix_pages(
                            self._prefix.pages_in_use()
                        )
                    self._complete(seq)
                    continue
                if entry is not None:
                    self._prefix.acquire(entry)
                    seq.prefix_entry = entry
                    self.telemetry.on_prefix_pages(
                        self._prefix.pages_in_use()
                    )
                slot = self._n_live
                self._arena = self._jit_insert(
                    self._arena, cache1, enc1, seq.input_mask[None],
                    np.int32(t0), np.int32(slot),
                )
                if self._spec:
                    # The draft lane mirrors the slot: its own prefill
                    # cache, but the TARGET's first token — the draft
                    # always consumes the verified stream.
                    self._d_arena = self._d_jit_insert(
                        self._d_arena, d_cache1, d_enc1,
                        seq.input_mask[None], np.int32(t0), np.int32(slot),
                    )
            if seq.ctx is not None:
                # Slot event: the sequence joined the continuous batch —
                # the wait it paid in the queue is arrival -> now.
                seq.ctx.span_from_mono(
                    "decode.join", seq.arrival_s,
                    slot=slot, budget_tokens=seq.max_new_tokens,
                    prefix_hit=seq.prefix_entry is not None,
                )
            with self._lock:
                self._slots[slot] = seq
                self._n_live += 1

    def _spec_round(self) -> None:
        """One speculative round: ``k`` chained draft steps propose,
        ONE bucketed target program scores all ``k`` fed positions
        (``_build_verify``), and each row emits the accepted draft
        prefix plus the target's own token at the first mismatch —
        1..k verified-greedy tokens per target step.  The k-th draft
        proposal is never judged (the verify window is full): its step
        runs anyway so the draft cache covers every position the round
        can emit — without it the draft lane keeps a permanent KV hole
        at the last emitted position and acceptance collapses.
        Rejected-tail KV in both arenas is scrubbed to exact zero by
        the accept program (see ``_build_jits``)."""
        n = self._n_live
        k = self.spec_tokens
        b = next(bk for bk in self.batch_buckets if bk >= n)
        deepest = max(
            len(s.tokens) for s in self._slots[:n] if s is not None
        )
        kv = next(kb for kb in self.kv_buckets if kb >= deepest + k)
        B = self.max_batch_size
        toks = np.full((B, k), self.pad_id, np.int32)
        for i in range(n):
            s = self._slots[i]
            if s is not None:
                toks[i, 0] = s.tokens[-1]
        t_start = time.perf_counter()
        with self._dev():
            d_fn = self._d_step_for(b, kv)
            for j in range(1, k + 1):
                self._d_arena, nxt = d_fn(self.draft_params, self._d_arena)
                if j < k:
                    toks[:b, j] = np.asarray(nxt)
            self._arena, g = self._verify_for(b, kv)(
                self.params, self._arena, toks
            )
            gh = np.asarray(g)  # [b, k] — the device->host sync
        dt = time.perf_counter() - t_start
        if self.step_ewma_s is None:
            self.step_ewma_s = dt
        else:
            a_ = self.STEP_EWMA_ALPHA
            self.step_ewma_s = (1 - a_) * self.step_ewma_s + a_ * dt
        self.steps_run += 1
        now = time.monotonic()
        proposed = accepted = 0
        new_tok = np.full((B,), self.pad_id, np.int32)
        new_pos = np.zeros((B,), np.int32)
        for i in range(n):
            seq = self._slots[i]
            a = 0
            while a < k - 1 and toks[i, a + 1] == gh[i, a]:
                a += 1
            proposed += k - 1
            accepted += a
            emitted = 0
            for j in range(a + 1):
                t = int(gh[i, j])
                seq.tokens.append(t)
                emitted += 1
                self.telemetry.on_token()
                if (
                    t == self.eos_id
                    or len(seq.tokens) >= seq.max_new_tokens
                ):
                    break
            new_tok[i] = seq.tokens[-1]
            new_pos[i] = len(seq.tokens)
            if seq.ctx is not None:
                seq.ctx.instant(
                    "decode.spec", slot=i, token=len(seq.tokens),
                    accepted=a, emitted=emitted,
                    batch_bucket=b, kv_bucket=kv, live=n,
                    step_s=round(dt, 6),
                )
        self.spec_proposed += proposed
        self.spec_accepted += accepted
        self.telemetry.on_spec(proposed, accepted)
        pages = sum(
            -(-(len(s.tokens) + 1) // self._page)
            for s in self._slots[:n] if s is not None
        )
        self.telemetry.on_step(dt, self.step_ewma_s, n, b, pages, int(n))
        with self._dev():
            # Wholesale tok/pos sync of BOTH lanes to the emitted stream
            # (rows past n carry pad/0, clear's convention).
            self._arena = self._jit_accept(self._arena, new_tok, new_pos)
            self._d_arena = self._jit_accept(
                self._d_arena, new_tok, new_pos
            )
        for slot in range(n - 1, -1, -1):
            seq = self._slots[slot]
            t = seq.tokens[-1]
            done = (
                t == self.eos_id or len(seq.tokens) >= seq.max_new_tokens
            )
            if done:
                if seq.ctx is not None and t == self.eos_id:
                    seq.ctx.instant(
                        "decode.eos", slot=slot, tokens=len(seq.tokens)
                    )
                self._retire(slot)
                self._complete(seq)
            elif (
                self.hard_deadline
                and seq.deadline_s is not None
                and now > seq.deadline_s
            ):
                self.telemetry.on_evicted()
                self._retire(slot)
                self._evict_seq(
                    seq, slot,
                    f"per-token SLO deadline exceeded after "
                    f"{len(seq.tokens)}/{seq.max_new_tokens} tokens",
                )

    def _step_once(self) -> None:
        n = self._n_live
        b = next(bk for bk in self.batch_buckets if bk >= n)
        deepest = max(
            len(s.tokens) for s in self._slots[:n] if s is not None
        )
        kv = next(k for k in self.kv_buckets if k >= deepest + 1)
        fn = self._step_for(b, kv)
        t0 = time.perf_counter()
        with self._dev():
            self._arena, nxt = fn(self.params, self._arena)
            if self._spec:
                # Keep the draft lane's KV stream gap-free even on the
                # single-step fallback path (headroom near the cache
                # end): the draft consumes the same tok/pos mirror, its
                # own next-token guess is then overwritten by the
                # accept-sync below.
                self._d_arena, _ = self._d_step_for(b, kv)(
                    self.draft_params, self._d_arena
                )
            toks = np.asarray(nxt)  # the one device->host sync per step
        if self._spec:
            new_tok = np.full((self.max_batch_size,), self.pad_id, np.int32)
            new_pos = np.zeros((self.max_batch_size,), np.int32)
            for i in range(n):
                s = self._slots[i]
                if s is not None:
                    new_tok[i] = int(toks[i])
                    new_pos[i] = len(s.tokens) + 1
            with self._dev():
                self._d_arena = self._jit_accept(
                    self._d_arena, new_tok, new_pos
                )
        dt = time.perf_counter() - t0
        if self.step_ewma_s is None:
            self.step_ewma_s = dt
        else:
            a = self.STEP_EWMA_ALPHA
            self.step_ewma_s = (1 - a) * self.step_ewma_s + a * dt
        self.steps_run += 1
        pages = sum(
            -(-(len(s.tokens) + 1) // self._page)
            for s in self._slots[:n] if s is not None
        )
        self.telemetry.on_step(dt, self.step_ewma_s, n, b, pages, int(n))
        now = time.monotonic()
        for slot in range(n - 1, -1, -1):
            seq = self._slots[slot]
            t = int(toks[slot])
            seq.tokens.append(t)
            self.telemetry.on_token()
            if seq.ctx is not None:
                # Per decode-step slot event: which step, which program
                # bucket pair — the trace shows exactly which steps this
                # sequence rode and with how much co-batched company.
                seq.ctx.instant(
                    "decode.step", slot=slot, token=len(seq.tokens),
                    batch_bucket=b, kv_bucket=kv, live=n,
                    step_s=round(dt, 6),
                )
            done = (
                t == self.eos_id or len(seq.tokens) >= seq.max_new_tokens
            )
            # Retire the slot BEFORE waking the waiter: the client thread
            # resumes to consistent accounting (outstanding_tokens of a
            # finished sequence is already 0, its slot already free).
            if done:
                if seq.ctx is not None and t == self.eos_id:
                    seq.ctx.instant(
                        "decode.eos", slot=slot, tokens=len(seq.tokens)
                    )
                self._retire(slot)
                self._complete(seq)
            elif (
                self.hard_deadline
                and seq.deadline_s is not None
                and now > seq.deadline_s
            ):
                self.telemetry.on_evicted()
                self._retire(slot)
                self._evict_seq(
                    seq, slot,
                    f"per-token SLO deadline exceeded after "
                    f"{len(seq.tokens)}/{seq.max_new_tokens} tokens",
                )

    def _retire(self, slot: int) -> None:
        with self._dev():
            last = self._n_live - 1
            if slot != last:
                self._arena = self._jit_move(
                    self._arena, np.int32(last), np.int32(slot)
                )
                if self._spec:
                    self._d_arena = self._d_jit_move(
                        self._d_arena, np.int32(last), np.int32(slot)
                    )
            self._arena = self._jit_clear(self._arena, np.int32(last))
            if self._spec:
                self._d_arena = self._d_jit_clear(
                    self._d_arena, np.int32(last)
                )
        with self._lock:
            if slot != self._n_live - 1:
                self._slots[slot] = self._slots[self._n_live - 1]
            self._slots[self._n_live - 1] = None
            self._n_live -= 1

    def _release_prefix(self, seq: _Sequence) -> None:
        """Drop this sequence's reader reference on its prefix-cache
        entry (no-op when it holds none).  The LAST reader's release is
        what makes an over-capacity entry evictable — the refcount
        contract the accounting test pins."""
        entry = seq.prefix_entry
        if entry is None or self._prefix is None:
            return
        seq.prefix_entry = None
        self._prefix.release(entry)
        self.telemetry.on_prefix_pages(self._prefix.pages_in_use())

    def _complete(self, seq: _Sequence) -> None:
        self._release_prefix(seq)
        latency = time.monotonic() - seq.arrival_s
        self.telemetry.on_done(latency, len(seq.tokens))
        self._trace_end(seq, "complete")
        seq.finish()

    def _evict_seq(self, seq: _Sequence, slot: int, reason: str) -> None:
        self._release_prefix(seq)
        if seq.ctx is not None:
            seq.ctx.instant(
                "decode.evict", slot=slot, tokens=len(seq.tokens),
                reason=reason,
            )
        self._trace_end(seq, "evicted")
        seq.finish(GenerationEvicted(reason))

    def _trace_end(self, seq: _Sequence, status: str) -> None:
        """The whole-lifetime ``decode`` span (arrival -> end): emitted
        for EVERY terminal edge — EOS, budget, eviction, engine death —
        so a stream's trace always covers its full decode lifetime."""
        if seq.ctx is None:
            return
        seq.ctx.complete_span(
            "decode", seq.arrival_wall_s, seq.arrival_s,
            time.monotonic() - seq.arrival_s,
            status=status, tokens=len(seq.tokens),
            budget_tokens=seq.max_new_tokens,
        )


class DecodeTelemetry:
    """The ``serving_decode_*`` family, shared by every engine of one
    replica (one label set per replica, however many versions are
    resident mid-drain).  All methods are no-ops without a registry."""

    def __init__(self, registry=None, replica: str = "0"):
        self.replica = str(replica)
        self._steps = self._tokens = self._seqs = self._evicted = None
        self._shed = self._occ = self._pages = self._active = None
        self._queue_tokens = self._step_s = self._per_token = None
        self._compiles = None
        self._prefix_hits = self._prefix_misses = None
        self._prefix_hit_pages = self._prefix_pages = None
        self._spec_proposed = self._spec_accept = None
        self._spec_ratio = None
        if registry is None:
            return
        from tpu_pipelines.observability.metrics import fine_latency_buckets

        lab = ("replica",)
        self._steps = registry.counter(
            "serving_decode_steps_total",
            "Continuous-batch decode steps executed.", labels=lab,
        ).labels(self.replica)
        self._tokens = registry.counter(
            "serving_decode_tokens_total",
            "Tokens emitted by the continuous-batch engine.", labels=lab,
        ).labels(self.replica)
        self._seqs = registry.counter(
            "serving_decode_sequences_total",
            "Generations completed (EOS or max_new_tokens).", labels=lab,
        ).labels(self.replica)
        self._evicted = registry.counter(
            "serving_decode_evicted_total",
            "Sequences evicted before finishing (per-token SLO deadline "
            "or engine shutdown).", labels=lab,
        ).labels(self.replica)
        self._shed = registry.counter(
            "serving_decode_shed_total",
            "Sequences refused by token-level admission control.",
            labels=lab,
        ).labels(self.replica)
        self._occ = registry.gauge(
            "serving_decode_batch_occupancy",
            "Live sequences / batch bucket of the most recent step.",
            labels=lab,
        ).labels(self.replica)
        self._pages = registry.gauge(
            "serving_decode_cache_pages_in_use",
            "KV-cache pages covering every live sequence's positions.",
            labels=lab,
        ).labels(self.replica)
        self._active = registry.gauge(
            "serving_decode_sequences_active",
            "Sequences live in the decode arena.", labels=lab,
        ).labels(self.replica)
        self._queue_tokens = registry.gauge(
            "serving_decode_queue_tokens",
            "Outstanding decode tokens (live remainder + queued budgets).",
            labels=lab,
        ).labels(self.replica)
        self._step_s = registry.gauge(
            "serving_decode_step_seconds",
            "EWMA wall time of one continuous-batch decode step.",
            labels=lab,
        ).labels(self.replica)
        # Fine sqrt(2) ladder (metrics.fine_latency_buckets): a decode
        # step runs in the tens-to-hundreds of µs, BELOW the default x2
        # ladder's 100µs floor — on the default ladder every per-token
        # observation piled into the first two buckets and a scraped
        # quantile was meaningless.
        self._per_token = registry.histogram(
            "serving_decode_per_token_latency_seconds",
            "Completed-generation latency divided by tokens emitted — "
            "the per-token SLO judge (fine sqrt(2) buckets).",
            labels=lab, buckets=fine_latency_buckets(),
        ).labels(self.replica)
        self._compiles = registry.counter(
            "serving_decode_compiles_after_warm_total",
            "Decode-step programs compiled AFTER warm() — each one is a "
            "broken warmup contract (an XLA compile paid mid-traffic); "
            "the SLO monitor treats any increase as a breach.",
            labels=lab,
        ).labels(self.replica)
        self._prefix_hits = registry.counter(
            "serving_decode_prefix_hit_total",
            "Admissions served from the prefix cache (prefill skipped).",
            labels=lab,
        ).labels(self.replica)
        self._prefix_misses = registry.counter(
            "serving_decode_prefix_miss_total",
            "Admissions that ran a full prefill with the prefix cache "
            "enabled.", labels=lab,
        ).labels(self.replica)
        self._prefix_hit_pages = registry.counter(
            "serving_decode_prefix_hit_pages_total",
            "Prompt pages whose prefill was skipped via prefix-cache "
            "hits — the work the cache saved.", labels=lab,
        ).labels(self.replica)
        self._prefix_pages = registry.gauge(
            "serving_decode_prefix_pages_in_use",
            "Prompt pages resident in the prefix cache (readers pin "
            "entries past capacity until the last one retires).",
            labels=lab,
        ).labels(self.replica)
        self._spec_proposed = registry.counter(
            "serving_decode_spec_proposed_total",
            "Draft tokens proposed by speculative decoding.", labels=lab,
        ).labels(self.replica)
        self._spec_accept = registry.counter(
            "serving_decode_spec_accept_total",
            "Draft tokens the target verified and accepted.", labels=lab,
        ).labels(self.replica)
        self._spec_ratio = registry.gauge(
            "serving_decode_spec_accept_ratio",
            "Lifetime speculative acceptance rate (accepted / proposed).",
            labels=lab,
        ).labels(self.replica)

    def on_step(self, dt, ewma, live, bucket, pages, active) -> None:
        if self._steps is None:
            return
        self._steps.inc()
        self._occ.set(live / max(1, bucket))
        self._pages.set(pages)
        self._active.set(active)
        self._step_s.set(ewma)

    def on_token(self) -> None:
        if self._tokens is not None:
            self._tokens.inc()

    def on_done(self, latency_s: float, n_tokens: int) -> None:
        if self._seqs is None:
            return
        self._seqs.inc()
        self._per_token.observe(latency_s / max(1, n_tokens))

    def on_evicted(self) -> None:
        if self._evicted is not None:
            self._evicted.inc()

    def on_shed(self) -> None:
        if self._shed is not None:
            self._shed.inc()

    def on_queue(self, outstanding_tokens: int) -> None:
        if self._queue_tokens is not None:
            self._queue_tokens.set(outstanding_tokens)

    def on_compile_after_warm(self) -> None:
        if self._compiles is not None:
            self._compiles.inc()

    def on_prefix_hit(self, pages: int) -> None:
        if self._prefix_hits is not None:
            self._prefix_hits.inc()
            self._prefix_hit_pages.inc(pages)

    def on_prefix_miss(self) -> None:
        if self._prefix_misses is not None:
            self._prefix_misses.inc()

    def on_prefix_pages(self, pages: int) -> None:
        if self._prefix_pages is not None:
            self._prefix_pages.set(pages)

    def on_spec(self, proposed: int, accepted: int) -> None:
        if self._spec_proposed is None:
            return
        if proposed:
            self._spec_proposed.inc(proposed)
        if accepted:
            self._spec_accept.inc(accepted)
        p = self._spec_proposed.get()
        if p:
            self._spec_ratio.set(self._spec_accept.get() / p)
