"""SavedModel export: interop with real TF Serving deployments.

SURVEY.md §3.5 / §7 hard part 2: the reference's Pusher ships SavedModels to
TensorFlow Serving.  This exporter converts the payload's single jitted
device computation (numeric transform fused with the model forward pass)
through jax2tf into a SavedModel with a ``serving_default`` signature, with
a symbolic batch dimension so the server can batch freely.

The host string stage (tokenization, vocab lookup — numpy) is NOT inside the
SavedModel; it runs in the client/ingestion tier, exactly as the framework's
own server does (``LoadedModel.host_preprocess``).  For fully self-contained
serving of raw strings, use the framework ModelServer instead.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

import numpy as np

from tpu_pipelines.trainer.export import load_exported_model

log = logging.getLogger("tpu_pipelines.serving")


def export_saved_model(
    model_uri: str,
    out_dir: str,
    example_batch: Dict[str, np.ndarray],
    *,
    polymorphic_batch: bool = True,
) -> str:
    """Convert an exported payload to a SavedModel; returns ``out_dir``.

    ``example_batch``: raw features (any batch size) used to derive the
    device-side input signature through the payload's own host stage.
    """
    import tensorflow as tf
    from jax.experimental import jax2tf

    loaded = load_exported_model(model_uri)
    iface = {
        k: np.asarray(v) for k, v in loaded.host_preprocess(example_batch).items()
    }

    if polymorphic_batch:
        shapes = {
            k: "(b, " + ", ".join(str(d) for d in v.shape[1:]) + ")"
            if v.ndim > 1 else "(b,)"
            for k, v in iface.items()
        }
        tf_fn = jax2tf.convert(
            loaded.device_predict, polymorphic_shapes=[shapes],
            with_gradient=False,
        )
        specs = {
            k: tf.TensorSpec([None, *v.shape[1:]], v.dtype, name=k)
            for k, v in iface.items()
        }
    else:
        tf_fn = jax2tf.convert(loaded.device_predict, with_gradient=False)
        specs = {
            k: tf.TensorSpec(v.shape, v.dtype, name=k) for k, v in iface.items()
        }

    module = tf.Module()
    module.fn = tf.function(tf_fn, input_signature=[specs])
    tf.saved_model.save(
        module, out_dir,
        signatures={"serving_default": module.fn.get_concrete_function(specs)},
    )
    log.info("SavedModel written to %s (inputs: %s)", out_dir, sorted(specs))
    return out_dir
