"""Orchestration: local DAG runner, cluster spec emitter, multi-host bootstrap.

TPU-native equivalent of TFX's L4 orchestration layer plus the Kubeflow/Argo
substrate interface (SURVEY.md §1, §3.1, §3.2).
"""

from tpu_pipelines.orchestration.local_runner import (  # noqa: F401
    LocalDagRunner,
    NodeResult,
    PipelineRunError,
    RunResult,
)
from tpu_pipelines.orchestration.cluster_runner import (  # noqa: F401
    TPUJobRunner,
    TPUJobRunnerConfig,
)
