"""Local DAG runner: concurrent ready-set scheduling with caching, retry,
partial runs.

Equivalent of TFX's ``LocalDagRunner`` + launcher stack (SURVEY.md §3.1),
with Kubeflow/Argo's DAG-level parallelism (SURVEY.md §3.1): independent
branches run concurrently instead of serializing in topo order.

    run(pipeline)
    └─ compile DSL → IR
    └─ ready-set scheduler (worker pool of ``max_parallel_nodes``):
       a node is dispatched once every upstream has PUBLISHED; at most one
       "tpu" resource-class node (Trainer/Tuner/Transform/Evaluator/
       BulkInferrer) holds the chip at a time while "host" nodes overlap
       freely.  Per dispatched node:
       ├─ DRIVER: resolve input artifacts; compute content cache key;
       │          cache hit ⇒ publish CACHED execution reusing outputs.
       │          Runs in the scheduler thread, so execution ids (and the
       │          output URIs embedding them) are assigned in deterministic
       │          dispatch order.
       ├─ LAUNCHER: allocate output artifact dirs; invoke executor in a
       │            worker thread (with per-node retry — the Argo
       │            retryStrategy equivalent)
       └─ PUBLISHER: fingerprint outputs, mark LIVE, record execution +
                     lineage events + contexts — every store write funnels
                     through one run-level publish lock, preserving the
                     store's single-writer discipline and lineage ordering.

``max_parallel_nodes`` defaults to the DAG's root count (env-overridable via
``TPP_MAX_PARALLEL_NODES``); at 1 — and always under ``spmd_sync``, whose
collectives require every process to take the same branches in the same
order — the runner takes the classic sequential topo loop, whose metadata
trace the 1-worker scheduler reproduces exactly (tests/test_concurrent_runner).

Crash safety (docs/RECOVERY.md):
  - ``run(..., resume_from="latest"|run_id)`` reconstructs a prior run from
    the metadata store: COMPLETE/CACHED executions are ADOPTED as-is (same
    execution ids, same artifact URIs, lineage preserved); executions still
    RUNNING at the crash are fenced (marked ABANDONED, their
    allocated-but-unpublished output dirs removed) and re-dispatched along
    with everything downstream.  A per-run DAG fingerprint recorded on the
    run context refuses resumption of a run whose compiled IR changed.
  - per-node ``execution_timeout_s`` (component override > pipeline default
    > env ``TPP_NODE_TIMEOUT_S``) is enforced by a watchdog in the
    scheduler thread: on expiry the node is published FAILED(timeout), its
    chip gate released, and the run drains — the worker's eventual result
    is fenced out, so a hung executor can never stall the pool or
    double-publish.
  - fault hooks (tpu_pipelines/testing/faults.py) thread through dispatch,
    the executor attempt, and both sides of the publisher — no-ops unless a
    test installs a plan.

The orchestrator is cold control plane; all hot work happens inside executors
(jitted train/transform steps).  Single-writer metadata discipline: only this
runner writes to the store during a run.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import os
import shutil
import tempfile
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Sequence

from tpu_pipelines.dsl.compiler import (
    Compiler,
    NodeIR,
    PipelineIR,
    resolve_property,
)
from tpu_pipelines.dsl.component import ExecutorContext
from tpu_pipelines.dsl.pipeline import Pipeline
from tpu_pipelines.metadata.store import MetadataStore, StoreUnavailableError
from tpu_pipelines.metadata.types import (
    Artifact,
    ArtifactState,
    Context,
    Execution,
    ExecutionState,
)
from tpu_pipelines.observability import trace as _trace
from tpu_pipelines.robustness import (
    TRANSIENT,
    RetryPolicy,
    classify_error,
    record_retry,
)
from tpu_pipelines.testing import faults as _faults
from tpu_pipelines.utils.fingerprint import execution_cache_key, fingerprint_dir
from tpu_pipelines.utils.span import has_span_pattern, resolve_span_pattern

log = logging.getLogger("tpu_pipelines.runner")


def _maybe_locked(lock: Optional[threading.Lock]):
    """The run-level publish lock when scheduling concurrently, a no-op
    context in the sequential path (where this thread is the only writer)."""
    import contextlib

    return lock if lock is not None else contextlib.nullcontext()


def _spmd_broadcast_int(value: int) -> int:
    """Broadcast a small int from process 0 to all processes (collective)."""
    import numpy as np
    from jax.experimental import multihost_utils

    return int(multihost_utils.broadcast_one_to_all(np.int32(value)))


def _spmd_broadcast_json(obj: Any) -> Any:
    """Broadcast a JSON-serializable value from process 0 (two collectives:
    length, then padded payload — workers don't know the size up front)."""
    import json as _json

    import numpy as np
    from jax.experimental import multihost_utils

    data = np.frombuffer(_json.dumps(obj).encode(), np.uint8)
    n = _spmd_broadcast_int(data.size)
    buf = np.zeros(n, np.uint8)
    buf[: min(n, data.size)] = data[:n]
    out = multihost_utils.broadcast_one_to_all(buf)
    return _json.loads(np.asarray(out).tobytes().decode())


def _spmd_sync_inputs(
    inputs: Dict[str, List[Artifact]],
) -> Dict[str, List[Artifact]]:
    """Replace every process's resolved inputs with process 0's.

    Input resolution reads the metadata store, and workers hold a
    point-in-time snapshot of it — a concurrent run publishing a newer
    upstream execution between the snapshot and process 0's read would
    otherwise feed different hosts different artifact URIs for the same
    training step (silently mixed datasets).
    """
    payload = {
        key: [
            {
                "type_name": a.type_name,
                "uri": a.uri,
                "id": a.id,
                "fingerprint": a.fingerprint,
                "properties": a.properties,
            }
            for a in arts
        ]
        for key, arts in inputs.items()
    }
    synced = _spmd_broadcast_json(payload)
    return {
        key: [
            Artifact(
                type_name=d["type_name"],
                uri=d["uri"],
                id=d["id"],
                state=ArtifactState.LIVE,
                properties=d["properties"],
                fingerprint=d["fingerprint"],
            )
            for d in arts
        ]
        for key, arts in synced.items()
    }


class PipelineRunError(RuntimeError):
    def __init__(self, message: str, result: "RunResult"):
        super().__init__(message)
        self.result = result


class _RunTelemetry:
    """Live run-progress telemetry for one pipeline run.

    Publishes nodes pending/running/done/failed gauges, per-node
    dispatch heartbeats, and a run info metric into the process metrics
    registry (in-memory — zero file/socket footprint), and optionally
    serves them: ``TPP_METRICS_PORT`` starts a background ``/metrics`` +
    ``/healthz`` HTTP server for the duration of the run — the opt-in
    scrape surface for long pipelines (matching the cluster runner's
    prometheus.io annotations).  Everything here is best-effort: a taken
    port logs a warning and the run proceeds unobserved.
    """

    def __init__(self, pipeline_name: str, run_id: str):
        from tpu_pipelines.observability.metrics import default_registry

        reg = default_registry()
        self._g_pending = reg.gauge(
            "pipeline_nodes_pending", "Nodes not yet dispatched.",
        )
        self._g_running = reg.gauge(
            "pipeline_nodes_running", "Nodes currently executing.",
        )
        self._g_done = reg.gauge(
            "pipeline_nodes_done",
            "Nodes settled successfully (COMPLETE/CACHED/skips).",
        )
        self._g_failed = reg.gauge(
            "pipeline_nodes_failed", "Nodes settled FAILED.",
        )
        self._g_heartbeat = reg.gauge(
            "pipeline_node_heartbeat_ts",
            "Wall-clock (epoch s) of the node's last scheduler event "
            "(dispatch or settle).",
            labels=("node",),
        )
        self._c_dispatch = reg.counter(
            "pipeline_node_dispatch_total",
            "Executor dispatches per node (retries re-count).",
            labels=("node",),
        )
        reg.gauge(
            "pipeline_run_info",
            "1 for the currently running pipeline run.",
            labels=("pipeline", "run_id"),
        ).labels(pipeline_name, run_id).set(1)
        self._failed = 0
        self._server = None
        self._info = {"pipeline": pipeline_name, "run_id": run_id}
        port = os.environ.get("TPP_METRICS_PORT", "").strip()
        if port and port != "0":
            from tpu_pipelines.observability.metrics import (
                start_http_server,
            )
            from tpu_pipelines.observability.federation import (
                FederatedRegistry,
                federation_dir,
            )

            # With TPP_FEDERATION_DIR set, the runner's port becomes the
            # ONE federated scrape: its own registry merged with every
            # spooled snapshot (fork-pool workers, per-host trainers,
            # fleet replicas), host/replica/tenant-labeled.  Without it,
            # the plain process registry is served — byte-identical to
            # the pre-federation behavior.
            serve_reg = (
                FederatedRegistry(reg) if federation_dir() else reg
            )
            try:
                self._server = start_http_server(
                    serve_reg, port=int(port), health_fn=self._health
                )
                log.info(
                    "metrics server on :%d (/metrics, /healthz)",
                    self._server.port,
                )
            except (OSError, ValueError) as e:
                log.warning(
                    "TPP_METRICS_PORT=%s: metrics server not started: %s",
                    port, e,
                )

    def _health(self) -> Dict[str, Any]:
        return {
            "healthy": self._failed == 0,
            **self._info,
            "nodes_failed": self._failed,
        }

    def progress(self, pending: int, running: int, result: "RunResult",
                 ) -> None:
        failed = sum(
            1 for nr in result.nodes.values() if nr.status == "FAILED"
        )
        self._failed = failed
        self._g_pending.set(pending)
        self._g_running.set(running)
        self._g_done.set(len(result.nodes) - failed)
        self._g_failed.set(failed)

    def heartbeat(self, node_id: str, dispatched: bool = False) -> None:
        self._g_heartbeat.labels(node_id).set(time.time())
        if dispatched:
            self._c_dispatch.labels(node_id).inc()

    def close(self) -> None:
        if self._server is not None:
            self._server.close()
            self._server = None


@dataclasses.dataclass
class NodeResult:
    node_id: str
    status: str   # COMPLETE | CACHED | FAILED | SKIPPED | COND_SKIPPED
    execution_id: int = 0
    outputs: Dict[str, List[Artifact]] = dataclasses.field(default_factory=dict)
    error: str = ""
    wall_clock_s: float = 0.0
    retries: int = 0
    # True when resume_from stitched this node in from a prior run's
    # published execution instead of executing it again.
    adopted: bool = False


@dataclasses.dataclass
class RunResult:
    pipeline_name: str
    run_id: str
    nodes: Dict[str, NodeResult] = dataclasses.field(default_factory=dict)
    # Effective scheduler pool size this run executed with (1 = sequential).
    max_parallel_nodes: int = 1

    @property
    def succeeded(self) -> bool:
        return all(
            n.status in ("COMPLETE", "CACHED", "SKIPPED", "COND_SKIPPED")
            for n in self.nodes.values()
        )

    def outputs_of(self, node_id: str, key: str) -> List[Artifact]:
        return self.nodes[node_id].outputs.get(key, [])


@dataclasses.dataclass
class _LaunchPlan:
    """Driver-phase output for a node that must execute: everything the
    worker-thread launcher/publisher phase needs.  The RUNNING execution is
    already registered (ids — and output URIs embedding them — are assigned
    in the scheduler thread, in deterministic dispatch order)."""

    node: NodeIR
    component: Any
    inputs: Dict[str, List[Artifact]]
    props: Dict[str, Any]
    external_fps: Dict[str, str]
    execution: Execution
    outputs: Dict[str, List[Artifact]]
    all_ctx: List[Context]
    t0: float
    # Deadline watchdog state (0 = no deadline).  ``cancel`` is handed to
    # the executor (extras["cancel_event"]) so cooperative long-runners can
    # abort; ``fenced`` is set by the scheduler when the deadline expires
    # (the worker must not publish afterwards); ``published`` is set by the
    # worker under the publish lock (the scheduler must not fence
    # afterwards) — together they make exactly one publish win.
    deadline_s: float = 0.0
    # Effective executor retry policy (node > pipeline > env > legacy
    # max_retries), resolved in the driver phase so the worker-thread
    # launcher loop never reads config.
    retry_policy: Optional[RetryPolicy] = None
    cancel: threading.Event = dataclasses.field(
        default_factory=threading.Event
    )
    fenced: threading.Event = dataclasses.field(
        default_factory=threading.Event
    )
    published: threading.Event = dataclasses.field(
        default_factory=threading.Event
    )


class LocalDagRunner:
    """In-process topological pipeline runner.

    Per-node retries follow the shared :class:`RetryPolicy` precedence
    (docs/RECOVERY.md): ``@component(retry_policy=...)`` >
    ``Pipeline(retry_policy=...)`` > env ``TPP_RETRY_*`` > the legacy
    ``max_retries`` constructor knob (mapped to ``RetryPolicy(
    max_attempts=max_retries+1, base_delay_s=0)`` — immediate retries, as
    before).  Only failures the shared taxonomy classifies TRANSIENT are
    retried; permanent failures (bad config, poisoned input) fail the node
    immediately.  Idempotence contract: executors write only under their
    output artifact uris and tmp dir, so a retry starts clean.

    ``max_parallel_nodes`` bounds the concurrent scheduler's worker pool:
    None = env ``TPP_MAX_PARALLEL_NODES`` if set, else the DAG's root count.
    1 means the classic sequential topo loop; "tpu" resource-class nodes are
    additionally serialized against each other regardless of pool size.
    """

    def __init__(
        self,
        max_retries: int = 0,
        spmd_sync: bool = False,
        max_parallel_nodes: Optional[int] = None,
    ):
        # Persistent XLA compile cache: the single biggest repeat-run cost
        # on TPU is recompiling unchanged programs (~45 s for the BERT
        # step, ~16 s warm-cached); enable before any executor compiles.
        from tpu_pipelines.utils.compile_cache import (
            maybe_enable_compile_cache,
        )

        maybe_enable_compile_cache()
        self.max_retries = max_retries
        self.max_parallel_nodes = max_parallel_nodes
        # Multi-host SPMD mode (run_node with a live coordination service):
        # workers execute against a point-in-time snapshot of the shared
        # metadata sqlite, so two store-derived decisions could diverge from
        # process 0's — the cache verdict, and the execution id embedded in
        # output URIs.  With spmd_sync, both are broadcast from process 0 so
        # every process takes the same branch and writes the same URIs
        # (orbax collective saves require a single shared directory).
        self.spmd_sync = spmd_sync
        if spmd_sync and max_retries:
            raise ValueError(
                "spmd_sync is incompatible with in-runner retries: process 0's"
                " clean-slate wipe would race workers still in the previous"
                " attempt; use substrate-level retries (Argo retryStrategy /"
                " JobSet backoff) instead"
            )

    def run(
        self,
        pipeline: Pipeline,
        runtime_parameters: Optional[Dict[str, Any]] = None,
        run_id: Optional[str] = None,
        from_nodes: Optional[Sequence[str]] = None,
        to_nodes: Optional[Sequence[str]] = None,
        raise_on_failure: bool = True,
        extras: Optional[Dict[str, Any]] = None,
        resume_from: Optional[str] = None,
        lint: Optional[str] = None,
    ) -> RunResult:
        """Execute the pipeline.

        ``lint`` opts into the static-analysis pre-flight gate
        (docs/ANALYSIS.md): "error" refuses to run on any ERROR finding,
        "warn" on any finding at all; env ``TPP_LINT`` is the fleet-wide
        default when the argument is None, and "off"/unset skips the
        analyzer entirely — zero behavior change, byte-identical metadata
        trace.  The gate runs BEFORE the metadata store is opened, so a
        refused run leaves no trace anywhere.

        ``from_nodes``/``to_nodes`` bound a partial run (TFX partial-run
        semantics): nodes outside the range are not executed; their outputs are
        resolved from the latest LIVE artifacts already in the metadata store.

        ``resume_from`` ("latest" or a prior run id) continues a run whose
        orchestrator died: published COMPLETE/CACHED executions are adopted
        as-is, RUNNING-at-crash executions are fenced (ABANDONED + orphan
        output dirs removed), and only unfinished nodes plus their
        descendants execute.  Refused when the compiled DAG's fingerprint no
        longer matches the one recorded for that run.
        """
        ir = Compiler().compile(pipeline)
        if self.spmd_sync:
            # Same hazard the constructor's max_retries check guards (and
            # the TPP108 lint rule catches at compile time): an in-runner
            # retry would wipe the shared output dirs while peer processes
            # are still inside the previous attempt's collectives.  Only
            # IR-carried policies are checked — the env rung is the
            # operator's fleet default for LOCAL runs, and cluster pods
            # (which strip IR policies in run_node) must not refuse over
            # an inherited environment.
            ir.spmd_sync = True
            retrying = sorted(
                n.id for n in ir.nodes
                if (
                    p := RetryPolicy.from_json(
                        getattr(n, "retry_policy", None)
                    ) or RetryPolicy.from_json(
                        getattr(ir, "default_retry_policy", None)
                    )
                ) is not None and p.max_attempts > 1 and not n.is_resolver
            )
            if retrying:
                raise ValueError(
                    f"spmd_sync is incompatible with in-runner retry "
                    f"policies (configured on {retrying}); use "
                    "substrate-level retries (Argo retryStrategy / JobSet "
                    "restarts) instead"
                )
        lint_level = None
        if not self.spmd_sync:
            # Under spmd_sync every process would lint (and potentially
            # load module files) redundantly; the cluster runner already
            # gated the IR at manifest-emission time.
            from tpu_pipelines.analysis import resolve_lint_level

            lint_level = resolve_lint_level(lint)
        if lint_level:
            from tpu_pipelines.analysis import analyze_pipeline, gate_or_raise

            findings = analyze_pipeline(pipeline, ir=ir)
            gate_or_raise(
                findings, lint_level, f"LocalDagRunner pre-flight "
                f"({pipeline.name})",
            )
        executors = {c.id: c for c in pipeline.components}
        from tpu_pipelines.metadata import open_store

        store = open_store(pipeline.metadata_path)
        dag_fp = ir.fingerprint()
        adopted: Dict[str, NodeResult] = {}
        try:
            if resume_from:
                if self.spmd_sync:
                    raise ValueError(
                        "resume_from is incompatible with spmd_sync: resume "
                        "decisions are store-derived and per-process; use "
                        "substrate-level restart (Argo retry) for multi-host "
                        "nodes"
                    )
                if from_nodes or to_nodes:
                    raise ValueError(
                        "resume_from is incompatible with from_nodes/"
                        "to_nodes: a resume re-runs exactly the unfinished "
                        "frontier of the prior run"
                    )
                if run_id:
                    raise ValueError(
                        "pass either resume_from (continues the prior run's "
                        "id) or run_id, not both"
                    )
                run_id, adopted = self._prepare_resume(
                    store, ir, pipeline.name, resume_from, dag_fp
                )
            run_id = run_id or f"{pipeline.name}-{int(time.time() * 1000)}"
            runtime_parameters = dict(runtime_parameters or {})

            pipeline_ctx = Context("pipeline", pipeline.name)
            run_ctx = Context(
                "pipeline_run", f"{pipeline.name}.{run_id}",
                # The DAG fingerprint recorded here is what a future
                # resume_from checks; put_context is insert-or-fetch, so a
                # resumed run keeps the original record.
                properties={"run_id": run_id, "dag_fingerprint": dag_fp},
            )
            store.put_context(pipeline_ctx)
            store.put_context(run_ctx)

            # RunTrace (observability/): run-scoped span log.  Off under
            # spmd_sync — every process would append to the same shared
            # file — and under TPP_TRACE=0 (nothing is even created).  A
            # resumed run reuses the prior run_id and so APPENDS to the
            # crashed run's event log.
            recorder = None
            if not self.spmd_sync:
                _trace.install_log_correlation()
                _trace.set_run_id(run_id)
                recorder = _trace.TraceRecorder.maybe_create(
                    _trace.run_trace_dir(ir.pipeline_root, run_id), run_id
                )

            selected = self._select_nodes(ir, from_nodes, to_nodes)
            if self.spmd_sync and len(selected) != 1:
                # Per-node collective counts must be identical on every
                # process; the failed-upstream skip path performs none, so a
                # multi-node run with divergent node outcomes would deadlock
                # peers at the next node's broadcast.  Cluster mode runs one
                # node per pod.
                raise ValueError(
                    "spmd_sync requires a single-node partial run "
                    f"(from_nodes=to_nodes=[node]); selected {sorted(selected)}"
                )
            result = RunResult(pipeline_name=pipeline.name, run_id=run_id)
            # node_id -> {output_key: [Artifact]} for input resolution.
            produced: Dict[str, Dict[str, List[Artifact]]] = {}
            failed_upstream: set = set()
            cond_skipped: set = set()
            # Adopted nodes settle before scheduling starts: downstream
            # input resolution sees their original artifacts, and both
            # loops skip anything already in result.nodes.
            for node in ir.nodes:
                if node.id in adopted:
                    self._settle(
                        adopted[node.id], produced, failed_upstream,
                        cond_skipped, result,
                    )
                    if recorder:
                        nr = adopted[node.id]
                        recorder.instant(
                            "resume_adopt", cat="run", node=node.id,
                            args={
                                "status": nr.status,
                                "execution_id": nr.execution_id,
                            },
                        )

            max_parallel = self._effective_parallelism(ir)
            result.max_parallel_nodes = max_parallel
            # Live telemetry (observability/metrics.py): run-progress
            # gauges + per-node heartbeats, plus the opt-in
            # TPP_METRICS_PORT scrape server.  Under spmd_sync each k8s
            # pod owns its network namespace so every process may bind;
            # same-host peers lose the bind race and log a warning (the
            # constructor's OSError guard), never fail the run.
            telemetry = _RunTelemetry(pipeline.name, run_id)
            shared = dict(
                store=store, ir=ir, executors=executors, selected=selected,
                produced=produced, failed_upstream=failed_upstream,
                cond_skipped=cond_skipped, result=result,
                runtime_parameters=runtime_parameters,
                pipeline_ctx=pipeline_ctx, run_ctx=run_ctx,
                extras=extras, enable_cache=pipeline.enable_cache,
                telemetry=telemetry,
            )
            # Deadline enforcement needs the executor in a worker thread the
            # watchdog can outlive, so any configured deadline routes the run
            # through the concurrent scheduler even at pool size 1.
            has_deadlines = any(
                self._node_timeout_s(n, ir) > 0 for n in ir.nodes
            )
            # TPP_FORCE_SCHEDULER=1 routes even a 1-worker run through the
            # concurrent scheduler — the test hook proving its trace matches
            # the sequential loop byte for byte (tests/test_concurrent_runner
            # .py).  spmd_sync always stays sequential: its collectives
            # require every process to take identical branches in identical
            # order.
            if recorder:
                recorder.instant(
                    "run_start", cat="run",
                    args={
                        "pipeline": pipeline.name,
                        "max_parallel_nodes": max_parallel,
                        "resume_from": resume_from or "",
                        "adopted": sorted(adopted),
                        "dag_fingerprint": dag_fp,
                    },
                )
            try:
                with _trace.activate(recorder):
                    if not self.spmd_sync and (
                        max_parallel > 1
                        or has_deadlines
                        or os.environ.get("TPP_FORCE_SCHEDULER") == "1"
                    ):
                        self._run_nodes_concurrent(
                            max_workers=max_parallel, **shared
                        )
                    else:
                        if has_deadlines and self.spmd_sync:
                            log.warning(
                                "execution_timeout_s is not enforced under"
                                " spmd_sync (the schedule must stay"
                                " collective-deterministic); rely on the"
                                " substrate deadline"
                                " (activeDeadlineSeconds)"
                            )
                        self._run_nodes_sequential(**shared)
                if recorder:
                    recorder.instant(
                        "run_end", cat="run",
                        args={"succeeded": result.succeeded},
                    )
            finally:
                telemetry.close()
                if recorder:
                    recorder.close()
        finally:
            store.close()
        if raise_on_failure and not result.succeeded:
            bad = [n for n in result.nodes.values() if n.status == "FAILED"]
            raise PipelineRunError(
                f"Pipeline {pipeline.name!r} run {run_id} failed at: "
                + ", ".join(f"{n.node_id} ({n.error.splitlines()[-1] if n.error else ''})" for n in bad),
                result,
            )
        return result

    # ------------------------------------------------------------ internals

    def _effective_parallelism(self, ir: PipelineIR) -> int:
        """Resolve the scheduler pool size: explicit arg > env > DAG roots.

        spmd_sync always forces 1: the per-node collective counts must be
        identical on every process, so the schedule (one node, sequential)
        must never depend on local timing."""
        if self.spmd_sync:
            return 1
        if self.max_parallel_nodes is not None:
            return max(1, int(self.max_parallel_nodes))
        env = os.environ.get("TPP_MAX_PARALLEL_NODES", "")
        if env:
            return max(1, int(env))
        return max(1, ir.n_roots())

    @staticmethod
    def _node_timeout_s(node: NodeIR, ir: PipelineIR) -> float:
        """Effective execution deadline for a node (0 = none).

        Precedence: component-level override (NodeIR.execution_timeout_s) >
        pipeline default (Pipeline(node_timeout_s=...)) > env
        ``TPP_NODE_TIMEOUT_S`` as the fleet-wide outermost fallback.
        """
        if node.execution_timeout_s and node.execution_timeout_s > 0:
            return float(node.execution_timeout_s)
        if ir.default_node_timeout_s and ir.default_node_timeout_s > 0:
            return float(ir.default_node_timeout_s)
        env = os.environ.get("TPP_NODE_TIMEOUT_S", "")
        if env:
            try:
                return max(0.0, float(env))
            except ValueError:
                log.warning("ignoring non-numeric TPP_NODE_TIMEOUT_S=%r", env)
        return 0.0

    def _node_retry_policy(
        self, node: NodeIR, ir: PipelineIR
    ) -> Optional[RetryPolicy]:
        """Effective executor retry policy for a node (None = single
        attempt).

        Precedence (docs/RECOVERY.md "Retry policies & error taxonomy"):
        component override (NodeIR.retry_policy) > pipeline default
        (Pipeline(retry_policy=...)) > env ``TPP_RETRY_*`` > the legacy
        ``LocalDagRunner(max_retries=N)`` constructor knob, which maps to
        ``RetryPolicy(max_attempts=N+1, base_delay_s=0)`` — its historical
        retry-immediately semantics, now with classification (a
        PermanentError never burns the budget).  Resolver nodes never
        retry: they answer from the store, and their failures are store
        failures the scheduler already contains.
        """
        if node.is_resolver:
            return None
        if self.spmd_sync:
            # run() refused IR-carried policies already; the env rung is
            # also ignored here so a fleet-wide TPP_RETRY_* default can
            # never arm an in-runner retry across SPMD processes.
            return None
        policy = RetryPolicy.from_json(getattr(node, "retry_policy", None))
        if policy is None:
            policy = RetryPolicy.from_json(
                getattr(ir, "default_retry_policy", None)
            )
        if policy is None:
            policy = RetryPolicy.from_env()
        if policy is None and self.max_retries:
            policy = RetryPolicy(
                max_attempts=self.max_retries + 1,
                base_delay_s=0.0,
                jitter=False,
            )
        return policy

    # -------------------------------------------------------------- resume

    def _prepare_resume(
        self,
        store: MetadataStore,
        ir: PipelineIR,
        pipeline_name: str,
        resume_from: str,
        dag_fp: str,
    ):
        """Reconstruct a crashed run's state from the metadata store.

        Returns ``(run_id, adopted)`` where ``adopted`` maps node ids to
        ready-made NodeResults for every node whose prior execution can be
        trusted: COMPLETE/CACHED with all output artifacts still LIVE (and
        every upstream itself adopted), or a Cond CANCELED skip record.
        Before adoption, the stale-execution sweep fences everything still
        RUNNING at the crash: marks it ABANDONED in the store and removes
        its allocated-but-unpublished output dirs, so the re-dispatch starts
        from a clean slate and a half-written payload can never be read.
        """
        prefix = f"{pipeline_name}."
        candidates = [
            c for c in store.get_contexts("pipeline_run")
            if c.name.startswith(prefix)
        ]
        if resume_from != "latest":
            candidates = [
                c for c in candidates
                if c.properties.get("run_id") == resume_from
                or c.name == prefix + resume_from
            ]
        if not candidates:
            raise ValueError(
                f"resume_from={resume_from!r}: no prior run of pipeline "
                f"{pipeline_name!r} in {store.db_path!r}"
            )
        run_ctx = max(candidates, key=lambda c: c.id)
        prior_fp = run_ctx.properties.get("dag_fingerprint", "")
        if prior_fp != dag_fp:
            detail = (
                "was recorded before DAG fingerprinting existed"
                if not prior_fp
                else "was compiled from a different DAG (nodes, wiring, "
                     "exec-properties, or executor code changed)"
            )
            raise ValueError(
                f"resume refused: run {run_ctx.name!r} {detail}; start a "
                "fresh run instead (the execution cache still reuses "
                "any node whose inputs and code are unchanged)"
            )
        run_id = run_ctx.properties.get("run_id") or run_ctx.name[len(prefix):]

        by_id = {n.id: n for n in ir.nodes}
        fenced = store.sweep_stale_executions(run_ctx.id)
        for ex in fenced:
            node = by_id.get(ex.node_id)
            if node is None:
                continue
            for key in node.outputs:
                stale = os.path.join(
                    ir.pipeline_root, node.id, key, str(ex.id)
                )
                if os.path.isdir(stale):
                    shutil.rmtree(stale)

        # Newest decisive execution per node within the crashed run.
        decisive: Dict[str, Execution] = {}
        for ex in store.get_executions_by_context(run_ctx.id):  # id order
            if ex.state in (
                ExecutionState.COMPLETE,
                ExecutionState.CACHED,
                ExecutionState.FAILED,
                ExecutionState.ABANDONED,
            ):
                decisive[ex.node_id] = ex
            elif (
                ex.state == ExecutionState.CANCELED
                and ex.properties.get("cond_skipped")
            ):
                decisive[ex.node_id] = ex

        adopted: Dict[str, NodeResult] = {}
        for node in ir.nodes:  # topo order: upstream adoption settles first
            ex = decisive.get(node.id)
            if ex is None:
                continue
            if any(u not in adopted for u in node.upstream):
                # An upstream re-runs, so this node's recorded outputs may
                # not match what the re-run produces — re-run it too (the
                # execution cache still short-circuits identical work).
                continue
            if ex.state in (ExecutionState.COMPLETE, ExecutionState.CACHED):
                outputs = self._outputs_of_execution(store, node, ex)
                if outputs is None:
                    continue  # an output artifact went non-LIVE: re-run
                adopted[node.id] = NodeResult(
                    node_id=node.id,
                    status=(
                        "COMPLETE"
                        if ex.state == ExecutionState.COMPLETE else "CACHED"
                    ),
                    execution_id=ex.id,
                    outputs=outputs,
                    adopted=True,
                )
            elif ex.state == ExecutionState.CANCELED:
                adopted[node.id] = NodeResult(
                    node_id=node.id, status="COND_SKIPPED", adopted=True
                )
            # FAILED / ABANDONED: fall through to re-dispatch.
        rerun = sorted(n.id for n in ir.nodes if n.id not in adopted)
        log.info(
            "resume %s: adopting %d node(s), fenced %d stale execution(s), "
            "re-running %s",
            run_id, len(adopted), len(fenced), rerun or "nothing",
        )
        return run_id, adopted

    @staticmethod
    def _outputs_of_execution(
        store: MetadataStore, node: NodeIR, ex: Execution
    ) -> Optional[Dict[str, List[Artifact]]]:
        """A specific execution's outputs in event-index order, or None when
        any output artifact is no longer LIVE (adoption must be refused)."""
        from tpu_pipelines.metadata.types import EventType

        candidate: Dict[str, List[tuple]] = {}
        for ev in store.get_events_by_execution(ex.id):
            if ev.type != EventType.OUTPUT:
                continue
            art = store.get_artifact(ev.artifact_id)
            if art is None or art.state != ArtifactState.LIVE:
                return None
            candidate.setdefault(ev.path, []).append((ev.index, art))
        if not candidate and node.outputs and not node.is_resolver:
            # A COMPLETE execution with declared outputs but no OUTPUT
            # events is corrupt state (interrupted legacy publish) — same
            # rule as the cache lookup.
            return None
        outputs: Dict[str, List[Artifact]] = (
            {key: [] for key in node.outputs} if node.is_resolver else {}
        )
        outputs.update({
            path: [a for _, a in sorted(pairs, key=lambda p: p[0])]
            for path, pairs in candidate.items()
        })
        return outputs

    def _control_outcome(
        self,
        store: MetadataStore,
        node: NodeIR,
        selected: set,
        produced: Dict[str, Dict[str, List[Artifact]]],
        failed_upstream: set,
        cond_skipped: set,
        runtime_parameters: Dict[str, Any],
        pipeline_ctx: Context,
        run_ctx: Context,
    ) -> Optional[NodeResult]:
        """Control-plane verdict for a node whose upstreams are all settled:
        a NodeResult for nodes that must NOT execute (partial-run skip,
        upstream failure, condition skip/error), or None when the node should
        be dispatched.  Store writes here (the CANCELED cond-skip record)
        happen in the calling scheduler thread, never in workers."""
        if node.id not in selected:
            # A node whose NEWEST execution was a condition-skip — whether
            # directly gated or cascade-skipped (both publish the CANCELED
            # cond_skipped record) — replays as condition-skipped, not as
            # its older, condition-rejected outputs.
            replay_skip = self._latest_is_cond_skip(store, node)
            if self.spmd_sync:
                # Store-derived; broadcast like every control decision.
                replay_skip = bool(
                    _spmd_broadcast_int(1 if replay_skip else 0)
                )
            if replay_skip:
                return NodeResult(node_id=node.id, status="COND_SKIPPED")
            outputs = self._resolve_prior_outputs(store, node)
            return NodeResult(
                node_id=node.id, status="SKIPPED", outputs=outputs
            )
        if any(u in failed_upstream for u in node.upstream):
            return NodeResult(
                node_id=node.id, status="FAILED", error="upstream failure",
            )
        # Cond semantics (dsl/cond.py): a node whose predicate fails — or
        # whose upstream was condition-skipped — is COND_SKIPPED, which is
        # NOT a failure: the run still succeeds without it.  The verdict is
        # recorded as a CANCELED execution so partial runs and cluster pods
        # replay the latest decision.
        unmet: List[Any] = []
        cond_error: Any = None
        cascade = any(u in cond_skipped for u in node.upstream)
        if node.conditions and not cascade:
            from tpu_pipelines.dsl.cond import (
                ConditionUnresolvedError,
                evaluate_condition,
            )

            try:
                unmet = [
                    c for c in node.conditions
                    if not evaluate_condition(
                        c, produced, runtime_parameters or {}
                    )
                ]
            except ConditionUnresolvedError as e:
                # Producer never published anything (e.g. a partial run
                # excluding it with no prior history): a configuration
                # mistake, surfaced as a node FAILURE — never silently
                # COND_SKIPPED (round-4 advisor finding).
                cond_error = str(e)
        skip = cascade or bool(unmet)
        if self.spmd_sync and (node.conditions or cascade):
            # Store-derived decision: process 0's verdict is authoritative,
            # or divergent snapshots would leave some processes inside the
            # executor's collectives while others skipped (same hazard as
            # the cache-verdict broadcast).
            verdict = 2 if cond_error else (1 if skip else 0)
            verdict = _spmd_broadcast_int(verdict)
            skip = verdict == 1
            if verdict == 2 and cond_error is None:
                cond_error = (
                    "condition unresolved on primary process "
                    "(producer has no published outputs)"
                )
            elif verdict != 2:
                cond_error = None
        if cond_error:
            return NodeResult(
                node_id=node.id, status="FAILED", error=cond_error,
            )
        if skip:
            log.info(
                "node %s: condition not met%s; skipping",
                node.id,
                "" if cascade else f" ({unmet})",
            )
            primary = True
            if self.spmd_sync:
                import jax

                primary = jax.process_index() == 0
            if primary:
                ex = Execution(
                    type_name=node.component_type,
                    node_id=node.id,
                    state=ExecutionState.CANCELED,
                    properties={
                        "cond_skipped": True,
                        "unmet_conditions": unmet,
                    },
                )
                store.publish_execution(ex, {}, {}, [pipeline_ctx, run_ctx])
            return NodeResult(node_id=node.id, status="COND_SKIPPED")
        return None

    @staticmethod
    def _settle(
        node_result: NodeResult,
        produced: Dict[str, Dict[str, List[Artifact]]],
        failed_upstream: set,
        cond_skipped: set,
        result: RunResult,
    ) -> None:
        """Record a node's final verdict and update the downstream-visible
        state (scheduler thread only — ``produced`` feeds input resolution)."""
        nid = node_result.node_id
        result.nodes[nid] = node_result
        if node_result.status in ("COMPLETE", "CACHED", "SKIPPED"):
            produced[nid] = node_result.outputs
        elif node_result.status == "COND_SKIPPED":
            cond_skipped.add(nid)
            produced[nid] = {}
        else:  # FAILED
            failed_upstream.add(nid)

    def _run_nodes_sequential(
        self, *, store, ir, executors, selected, produced, failed_upstream,
        cond_skipped, result, runtime_parameters, pipeline_ctx, run_ctx,
        extras, enable_cache, telemetry,
    ) -> None:
        """The classic strict-topo-order loop (spmd_sync and pool size 1)."""
        rec = _trace.active_recorder()
        remaining = sum(1 for n in ir.nodes if n.id not in result.nodes)
        for node in ir.nodes:
            if node.id in result.nodes:
                continue  # adopted by resume_from before scheduling began
            telemetry.progress(remaining - 1, 1, result)
            telemetry.heartbeat(node.id, dispatched=True)
            t0_wall, t0_mono = time.time(), time.monotonic()
            try:
                node_result = self._control_outcome(
                    store, node, selected, produced, failed_upstream,
                    cond_skipped, runtime_parameters, pipeline_ctx, run_ctx,
                )
                if node_result is None:
                    node_result = self._run_node(
                        store, ir, node, executors[node.id], produced,
                        runtime_parameters, [pipeline_ctx, run_ctx],
                        extras=dict(extras or {}),
                        enable_cache=enable_cache,
                    )
            except StoreUnavailableError as e:
                # Store backend died under a driver-phase write: record a
                # node failure (descendants fail fast) instead of crashing
                # the run.
                node_result = NodeResult(
                    node_id=node.id, status="FAILED",
                    error=f"metadata store unavailable: {e}",
                )
            self._settle(
                node_result, produced, failed_upstream, cond_skipped, result
            )
            remaining -= 1
            telemetry.progress(remaining, 0, result)
            telemetry.heartbeat(node.id)
            if rec:
                rec.complete(
                    "node", "scheduler", node.id, t0_wall, t0_mono,
                    time.monotonic() - t0_mono,
                    args={
                        "status": node_result.status,
                        "execution_id": node_result.execution_id,
                        "retries": node_result.retries,
                        "queue_wait_s": 0.0,
                        "gate_wait_s": 0.0,
                        "upstream": list(node.upstream),
                    },
                )

    def _run_nodes_concurrent(
        self, *, store, ir, executors, selected, produced, failed_upstream,
        cond_skipped, result, runtime_parameters, pipeline_ctx, run_ctx,
        extras, enable_cache, telemetry, max_workers: int,
    ) -> None:
        """Ready-set scheduler: dispatch any node whose upstreams have all
        published, lowest topo index first; executors run in a worker pool
        while driver/launch (and so execution-id/URI assignment) stays in
        this thread.  At most one "tpu" resource-class node is in flight at
        a time; "host" nodes overlap freely.  A failing node marks its
        descendants FAILED without cancelling in-flight or independent work
        (same fail-fast semantics as the sequential loop — downstream nodes
        of a failure are never started, in-flight branches drain and
        publish)."""
        import queue as queue_mod
        from concurrent.futures import ThreadPoolExecutor

        publish_lock = threading.Lock()
        # Adopted (resume_from) nodes are already settled in result.nodes.
        unprocessed = [
            n.id for n in ir.nodes if n.id not in result.nodes
        ]  # stays in topo order
        by_id = {n.id: n for n in ir.nodes}
        settled: set = set(result.nodes)
        in_flight: set = set()
        in_flight_plans: Dict[str, _LaunchPlan] = {}
        rec = _trace.active_recorder()
        # Trace bookkeeping: when a node became READY (all upstreams
        # settled), when it first blocked on the tpu chip gate, and when
        # it was actually dispatched — queue wait and gate wait are the
        # differences, the per-node span runs dispatch -> settle.
        ready_at: Dict[str, tuple] = {}        # nid -> (wall, mono)
        gate_blocked_at: Dict[str, float] = {}  # nid -> mono
        dispatch_info: Dict[str, tuple] = {}    # nid -> (wall, mono, qw, gw)

        def emit_node(nr: NodeResult, t0: tuple, queue_wait: float,
                      gate_wait: float) -> None:
            telemetry.heartbeat(nr.node_id)  # settle heartbeat
            if rec is None:
                return
            wall0, mono0 = t0
            rec.complete(
                "node", "scheduler", nr.node_id, wall0, mono0,
                time.monotonic() - mono0,
                args={
                    "status": nr.status,
                    "execution_id": nr.execution_id,
                    "retries": nr.retries,
                    "queue_wait_s": round(queue_wait, 6),
                    "gate_wait_s": round(gate_wait, 6),
                    "upstream": list(by_id[nr.node_id].upstream),
                },
            )
        # node_id -> absolute monotonic deadline for in-flight timed nodes.
        deadlines: Dict[str, float] = {}
        # Nodes settled FAILED(timeout) by the watchdog whose worker thread
        # has not returned yet: their eventual done_q result is discarded.
        zombies: set = set()
        tpu_in_flight: Optional[str] = None
        done_q: "queue_mod.Queue" = queue_mod.Queue()

        def worker(plan: _LaunchPlan, node_extras: Dict[str, Any]) -> None:
            try:
                # Worker threads have fresh contextvar contexts: stamp the
                # run/node ids so this thread's log records are attributable.
                with _trace.node_log_context(
                    plan.node.id, rec.run_id if rec else ""
                ):
                    nr = self._execute_and_publish(
                        store, plan, node_extras, publish_lock
                    )
            except _faults.SimulatedCrash as crash:
                # Forward the injected orchestrator death to the scheduler
                # thread, which re-raises it (the whole process "dies").
                done_q.put(crash)
                return
            except Exception:
                # Runner-internal failure: settle the node as FAILED instead
                # of deadlocking the scheduler on a completion that never
                # arrives.
                nr = NodeResult(
                    node_id=plan.node.id, status="FAILED",
                    error=traceback.format_exc(),
                )
            done_q.put(nr)

        pool = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="tpp-node"
        )
        try:
            while unprocessed or in_flight:
                telemetry.progress(len(unprocessed), len(in_flight), result)
                progressed = False
                # With a single worker, hold back later nodes until the
                # in-flight one settles: control-plane publishes (cond-skip
                # CANCELED records) must interleave exactly as the
                # sequential loop's would.
                scan = (
                    [] if (max_workers == 1 and in_flight)
                    else list(unprocessed)
                )
                for nid in scan:
                    node = by_id[nid]
                    if any(u not in settled for u in node.upstream):
                        continue
                    if nid not in ready_at:
                        ready_at[nid] = (time.time(), time.monotonic())
                    try:
                        nr = self._control_outcome(
                            store, node, selected, produced, failed_upstream,
                            cond_skipped, runtime_parameters, pipeline_ctx,
                            run_ctx,
                        )
                    except StoreUnavailableError as e:
                        nr = NodeResult(
                            node_id=nid, status="FAILED",
                            error=f"metadata store unavailable: {e}",
                        )
                    if nr is not None:
                        self._settle(
                            nr, produced, failed_upstream, cond_skipped,
                            result,
                        )
                        unprocessed.remove(nid)
                        settled.add(nid)
                        progressed = True
                        emit_node(nr, ready_at[nid], 0.0, 0.0)
                        continue
                    if len(in_flight) >= max_workers:
                        continue  # no slot; later control-only nodes may settle
                    if node.resource_class == "tpu" and tpu_in_flight:
                        # chip busy; host nodes may still dispatch
                        gate_blocked_at.setdefault(nid, time.monotonic())
                        continue
                    dispatch_wall, dispatch_mono = (
                        time.time(), time.monotonic()
                    )
                    queue_wait = dispatch_mono - ready_at[nid][1]
                    gate_wait = (
                        dispatch_mono - gate_blocked_at.pop(nid)
                        if nid in gate_blocked_at else 0.0
                    )
                    try:
                        prepared = self._prepare_node(
                            store, ir, node, executors[nid], produced,
                            runtime_parameters, [pipeline_ctx, run_ctx],
                            enable_cache, publish_lock,
                        )
                    except StoreUnavailableError as e:
                        # Driver-phase store write failed (cache publish,
                        # RUNNING registration): a node failure, not a
                        # run crash.
                        prepared = NodeResult(
                            node_id=nid, status="FAILED",
                            error=f"metadata store unavailable: {e}",
                        )
                    unprocessed.remove(nid)
                    progressed = True
                    if isinstance(prepared, NodeResult):
                        # Resolver, cache hit, or driver failure: finished
                        # without an executor.
                        self._settle(
                            prepared, produced, failed_upstream,
                            cond_skipped, result,
                        )
                        settled.add(nid)
                        emit_node(
                            prepared, (dispatch_wall, dispatch_mono),
                            queue_wait, gate_wait,
                        )
                        continue
                    in_flight.add(nid)
                    in_flight_plans[nid] = prepared
                    dispatch_info[nid] = (
                        dispatch_wall, dispatch_mono, queue_wait, gate_wait
                    )
                    if prepared.deadline_s > 0:
                        deadlines[nid] = (
                            time.monotonic() + prepared.deadline_s
                        )
                    if node.resource_class == "tpu":
                        tpu_in_flight = nid
                    node_extras = dict(extras or {})
                    # Cooperative cancellation handle: set on deadline
                    # expiry and at drain, so well-behaved long-runners
                    # (and the fault harness's injected hangs) can abort.
                    node_extras["cancel_event"] = prepared.cancel
                    telemetry.heartbeat(nid, dispatched=True)
                    pool.submit(worker, prepared, node_extras)
                if progressed:
                    continue
                if not in_flight:
                    # Nothing runnable, nothing running: an IR bug (cycle),
                    # not a state this acyclic-compiled DAG can reach.
                    raise RuntimeError(
                        f"scheduler stalled with pending nodes {unprocessed}"
                    )
                # Watchdog wait: block until a completion arrives or the
                # nearest in-flight deadline expires.
                wait_s = None
                if deadlines:
                    wait_s = max(
                        0.0, min(deadlines.values()) - time.monotonic()
                    )
                try:
                    item = done_q.get(timeout=wait_s)
                except queue_mod.Empty:
                    now = time.monotonic()
                    for nid in [
                        n for n, d in deadlines.items() if d <= now
                    ]:
                        expired = self._expire_deadline(
                            store, in_flight_plans[nid], publish_lock
                        )
                        deadlines.pop(nid)
                        if expired is None:
                            continue  # published concurrently: result coming
                        in_flight.discard(nid)
                        in_flight_plans.pop(nid)
                        zombies.add(nid)
                        if tpu_in_flight == nid:
                            tpu_in_flight = None  # release the chip gate
                        self._settle(
                            expired, produced, failed_upstream,
                            cond_skipped, result,
                        )
                        settled.add(nid)
                        dw, dm, qw, gw = dispatch_info.pop(nid)
                        emit_node(expired, (dw, dm), qw, gw)
                    continue
                if isinstance(item, BaseException):
                    raise item  # forwarded SimulatedCrash
                nr = item
                if nr.node_id in zombies:
                    # The timed-out worker finally returned (its publish was
                    # fenced); the node is already settled FAILED(timeout).
                    zombies.discard(nr.node_id)
                    continue
                in_flight.discard(nr.node_id)
                in_flight_plans.pop(nr.node_id, None)
                deadlines.pop(nr.node_id, None)
                if tpu_in_flight == nr.node_id:
                    tpu_in_flight = None
                self._settle(
                    nr, produced, failed_upstream, cond_skipped, result
                )
                settled.add(nr.node_id)
                dw, dm, qw, gw = dispatch_info.pop(nr.node_id)
                emit_node(nr, (dw, dm), qw, gw)
            telemetry.progress(len(unprocessed), len(in_flight), result)
        finally:
            # Release every cooperative hang, give timed-out workers a short
            # grace to drain, then shut down — without blocking forever on a
            # genuinely wedged thread (it holds no locks and its publish is
            # fenced, so abandoning it is safe).
            for plan in in_flight_plans.values():
                plan.cancel.set()
            deadline = time.monotonic() + 2.0
            while zombies and time.monotonic() < deadline:
                try:
                    item = done_q.get(timeout=0.1)
                except queue_mod.Empty:
                    continue
                if isinstance(item, NodeResult):
                    zombies.discard(item.node_id)
            pool.shutdown(wait=not zombies)

    def _expire_deadline(
        self,
        store: MetadataStore,
        plan: _LaunchPlan,
        publish_lock: threading.Lock,
    ) -> Optional[NodeResult]:
        """Watchdog expiry for one in-flight node: fence the worker's future
        publish, record the FAILED(timeout) execution, and release the
        (cooperative) executor via the cancel event.  Returns None when the
        worker's publish already won the race (its completion is in flight
        on the done queue), else the timeout NodeResult to settle.

        A deadline expiry is terminal: the hung attempt cannot be reaped, so
        a clean-slate retry would race its writes — the timeout consumes
        whatever retry budget the node had left.
        """
        node, ex = plan.node, plan.execution
        with publish_lock:
            if plan.published.is_set():
                return None
            plan.fenced.set()
            plan.cancel.set()
            wall = time.time() - plan.t0
            error = (
                f"execution timeout: node {node.id!r} exceeded its "
                f"{plan.deadline_s:g}s deadline"
            )
            ex.state = ExecutionState.FAILED
            ex.properties.update({
                "wall_clock_s": round(wall, 4),
                "timeout": True,
                "error": error,
            })
            try:
                # Outputs publish as ABANDONED at their allocated URIs; the
                # wedged executor may still be writing under them, which is
                # why they are never adopted or cached.
                store.publish_execution(
                    ex, plan.inputs, plan.outputs, plan.all_ctx
                )
            except StoreUnavailableError as e:
                log.error(
                    "node %s: metadata store unavailable while recording "
                    "timeout: %s", node.id, e,
                )
        log.warning("node %s: %s", node.id, error)
        _trace.instant(
            "deadline_expired", cat="scheduler", node=node.id,
            args={"deadline_s": plan.deadline_s, "execution_id": ex.id},
        )
        return NodeResult(
            node_id=node.id, status="FAILED", execution_id=ex.id,
            error=error, wall_clock_s=wall,
        )

    @staticmethod
    def _select_nodes(
        ir: PipelineIR,
        from_nodes: Optional[Sequence[str]],
        to_nodes: Optional[Sequence[str]],
    ) -> set:
        all_ids = {n.id for n in ir.nodes}
        for nid in list(from_nodes or []) + list(to_nodes or []):
            if nid not in all_ids:
                raise ValueError(f"Unknown node in partial-run bounds: {nid!r}")
        selected = set(all_ids)
        if from_nodes:
            # keep only nodes downstream-of-or-equal-to any from_node
            keep = set(from_nodes)
            changed = True
            while changed:
                changed = False
                for n in ir.nodes:
                    if n.id not in keep and any(u in keep for u in n.upstream):
                        keep.add(n.id)
                        changed = True
            selected &= keep
        if to_nodes:
            # keep only nodes upstream-of-or-equal-to any to_node
            by_id = {n.id: n for n in ir.nodes}
            keep = set()
            stack = list(to_nodes)
            while stack:
                nid = stack.pop()
                if nid in keep:
                    continue
                keep.add(nid)
                stack.extend(by_id[nid].upstream)
            selected &= keep
        return selected

    @staticmethod
    def _latest_is_cond_skip(store: MetadataStore, node: NodeIR) -> bool:
        """True when the node's newest decisive execution (COMPLETE, CACHED,
        or a Cond CANCELED record) was a condition-skip."""
        for ex in reversed(store.get_executions(node_id=node.id)):
            if ex.state in (ExecutionState.COMPLETE, ExecutionState.CACHED):
                return False
            if (
                ex.state == ExecutionState.CANCELED
                and ex.properties.get("cond_skipped")
            ):
                return True
        return False

    @staticmethod
    def _resolve_prior_outputs(
        store: MetadataStore, node: NodeIR
    ) -> Dict[str, List[Artifact]]:
        """Latest LIVE outputs of a node from prior runs (partial-run reuse)."""
        outputs: Dict[str, List[Artifact]] = {}
        for ex in reversed(store.get_executions(node_id=node.id)):
            if ex.state not in (ExecutionState.COMPLETE, ExecutionState.CACHED):
                continue
            from tpu_pipelines.metadata.types import EventType

            candidate: Dict[str, List[tuple]] = {}
            live = True
            for ev in store.get_events_by_execution(ex.id):
                if ev.type != EventType.OUTPUT:
                    continue
                art = store.get_artifact(ev.artifact_id)
                if art is None or art.state != ArtifactState.LIVE:
                    live = False
                    break
                candidate.setdefault(ev.path, []).append((ev.index, art))
            if node.is_resolver:
                # The NEWEST resolver execution is authoritative, full stop:
                # resolved-empty is a valid state, and a resolved artifact
                # that has since gone non-LIVE means empty NOW — falling
                # through to an older execution in either case would
                # resurrect a baseline the latest resolution rejected.
                outputs = {key: [] for key in node.outputs}
                if live:
                    outputs.update({
                        path: [
                            a for _, a in sorted(pairs, key=lambda p: p[0])
                        ]
                        for path, pairs in candidate.items()
                    })
                break
            if live and candidate:
                # Same event-index ordering as the cache path, so a SKIPPED
                # node hands downstream the identical artifact order.
                outputs = {
                    path: [a for _, a in sorted(pairs, key=lambda p: p[0])]
                    for path, pairs in candidate.items()
                }
                break
        return outputs

    def _run_node(
        self,
        store: MetadataStore,
        ir: PipelineIR,
        node: NodeIR,
        component,
        produced: Dict[str, Dict[str, List[Artifact]]],
        runtime_parameters: Dict[str, Any],
        contexts: List[Context],
        extras: Dict[str, Any],
        enable_cache: bool,
    ) -> NodeResult:
        """Sequential-path node execution: driver + launcher + publisher
        inline, in this thread (the concurrent scheduler calls the two
        phases separately — driver here, launcher/publisher in a worker)."""
        prepared = self._prepare_node(
            store, ir, node, component, produced, runtime_parameters,
            contexts, enable_cache, publish_lock=None,
        )
        if isinstance(prepared, NodeResult):
            return prepared
        with _trace.node_log_context(node.id):
            return self._execute_and_publish(
                store, prepared, extras, publish_lock=None
            )

    def _prepare_node(
        self,
        store: MetadataStore,
        ir: PipelineIR,
        node: NodeIR,
        component,
        produced: Dict[str, Dict[str, List[Artifact]]],
        runtime_parameters: Dict[str, Any],
        contexts: List[Context],
        enable_cache: bool,
        publish_lock: Optional[threading.Lock],
    ):
        """DRIVER phase: input resolution, cache check, and — on a cache
        miss — RUNNING-execution registration + output allocation.  Returns
        a NodeResult for nodes finished without an executor (resolver, cache
        hit, driver failure), else a _LaunchPlan for _execute_and_publish.
        Always runs in the scheduling thread, so execution ids (and the
        output URIs embedding them) are assigned in dispatch order."""
        t0 = time.time()
        # Fault hook: kill-orchestrator-at-node-N fires here, in the
        # scheduler thread, before any state for this node is registered.
        _faults.at_dispatch(node.id)
        with contextlib.ExitStack() as stack:
            stack.enter_context(_trace.node_log_context(node.id))
            stack.enter_context(
                _trace.span("driver", cat="scheduler", node=node.id)
            )
            return self._prepare_node_inner(
                store, ir, node, component, produced, runtime_parameters,
                contexts, enable_cache, publish_lock, t0,
            )

    def _prepare_node_inner(
        self, store, ir, node, component, produced, runtime_parameters,
        contexts, enable_cache, publish_lock, t0,
    ):
        node_ctx = Context("node", f"{ir.name}.{node.id}")
        with _maybe_locked(publish_lock):
            store.put_context(node_ctx)
        all_ctx = contexts + [node_ctx]

        if node.is_resolver:
            with _maybe_locked(publish_lock):
                return self._run_resolver_node(
                    store, ir, node, all_ctx, t0, runtime_parameters
                )

        # ---- DRIVER: resolve inputs + cache check
        resolve_error = ""
        try:
            inputs = self._resolve_inputs(node, produced)
        except KeyError as e:
            inputs = {}
            resolve_error = f"input resolution failed: {e}"
        if self.spmd_sync:
            # Process 0's resolution is authoritative: a worker that failed
            # (or resolved differently) against its store snapshot adopts
            # process 0's artifacts; if process 0 failed, everyone fails.
            if _spmd_broadcast_int(0 if resolve_error else 1):
                inputs = _spmd_sync_inputs(inputs)
                resolve_error = ""
            elif not resolve_error:
                resolve_error = "input resolution failed on process 0"
        if resolve_error:
            return NodeResult(
                node_id=node.id, status="FAILED", error=resolve_error,
            )
        props = {
            k: resolve_property(v, runtime_parameters)
            for k, v in node.exec_properties.items()
        }
        input_fps = {
            key: [a.fingerprint or f"artifact:{a.id}" for a in arts]
            for key, arts in inputs.items()
        }
        external_fps: Dict[str, str] = {}
        # External data named by path-valued exec-properties participates by
        # content, so editing a source file invalidates the cache even though
        # the path string is unchanged.  {SPAN}/{VERSION} patterns resolve to
        # the concrete (newest or pinned) directory FIRST, so a new span
        # arriving at an unchanged pattern string also invalidates.
        for param in node.external_input_parameters:
            path = props.get(param)
            if isinstance(path, str) and has_span_pattern(path):
                try:
                    path, r_span, r_version = resolve_span_pattern(
                        path, props.get("span"), props.get("version"),
                    )
                except FileNotFoundError:
                    path = None  # executor will raise with the real error
                else:
                    # The delivery's identity joins the cache key alongside
                    # its content: fingerprint_dir hashes root-RELATIVE
                    # names + bytes, so a byte-identical re-delivery under
                    # a new {VERSION} would otherwise cache-hit and keep
                    # serving the stale version-stamped artifact — the
                    # continuous watcher treats a re-delivery as a changed
                    # span, and the cache must agree.
                    input_fps[f"__span__:{param}"] = [
                        f"span={r_span}:version={r_version}"
                    ]
            if isinstance(path, str) and os.path.exists(path):
                fp = fingerprint_dir(path)
                input_fps[f"__external__:{param}"] = [fp]
                # Memo for the publisher: an executor that re-points an
                # output at this same external path (Importer) reuses the
                # driver's hash instead of re-reading the whole payload.
                external_fps[os.path.abspath(path)] = fp
        cache_key = execution_cache_key(
            node.id, node.executor_version, props, input_fps
        )

        cached = store.get_cached_outputs(cache_key) if enable_cache else None
        if self.spmd_sync:
            # Collective: every process learns process 0's cache verdict so
            # none executes (and blocks in jit collectives) while process 0
            # takes the cached shortcut.  A worker's snapshot is a subset of
            # the live store, so worker-hit ⇒ process-0-hit; the reverse gap
            # (process 0 sees an entry published after the snapshot) is the
            # case handled here.
            hit = _spmd_broadcast_int(1 if cached is not None else 0)
            if hit and cached is None:
                log.info(
                    "node %s: process 0 reported a cache hit not in this "
                    "worker's snapshot; skipping execution", node.id,
                )
                return NodeResult(
                    node_id=node.id,
                    status="CACHED",
                    wall_clock_s=time.time() - t0,
                )
            if not hit:
                cached = None
        if cached is not None:
            ex = Execution(
                type_name=node.component_type,
                node_id=node.id,
                state=ExecutionState.CACHED,
                properties={"cache_hit": True},
                cache_key=cache_key,
            )
            with _maybe_locked(publish_lock):
                store.publish_execution(ex, inputs, cached, all_ctx)
            log.info("node %s: cache hit (execution %d)", node.id, ex.id)
            _trace.instant(
                "cache_hit", cat="scheduler", node=node.id,
                args={"execution_id": ex.id},
            )
            return NodeResult(
                node_id=node.id,
                status="CACHED",
                execution_id=ex.id,
                outputs=cached,
                wall_clock_s=time.time() - t0,
            )

        # ---- LAUNCHER: register execution, allocate outputs, run executor
        if enable_cache:
            _trace.instant("cache_miss", cat="scheduler", node=node.id)
        ex = Execution(
            type_name=node.component_type,
            node_id=node.id,
            state=ExecutionState.RUNNING,
            properties={},
            cache_key=cache_key,
        )
        with _maybe_locked(publish_lock):
            store.put_execution(ex)
            # Associate the RUNNING record with its contexts NOW, not only
            # at publish: if the orchestrator dies mid-execution, the
            # resume's stale-execution sweep finds the orphan by run
            # context.  publish_execution re-associates (INSERT OR IGNORE),
            # so the final row set is unchanged.
            for ctx in all_ctx:
                store.associate(ctx.id, ex.id)

        # Output URIs embed the execution id; under spmd_sync process 0's id
        # is authoritative so all processes write one shared directory tree.
        # Process 0 wipes any stale dir BEFORE the broadcast barrier releases
        # the workers — afterwards nobody may delete under the shared URIs.
        if self.spmd_sync:
            import jax

            if jax.process_index() == 0:
                for key in node.outputs:
                    stale = os.path.join(
                        ir.pipeline_root, node.id, key, str(ex.id)
                    )
                    if os.path.isdir(stale):
                        shutil.rmtree(stale)
            uri_ex_id = _spmd_broadcast_int(ex.id)
        else:
            uri_ex_id = ex.id
        outputs: Dict[str, List[Artifact]] = {}
        for key, type_name in node.outputs.items():
            uri = os.path.join(ir.pipeline_root, node.id, key, str(uri_ex_id))
            outputs[key] = [Artifact(type_name=type_name, uri=uri)]
        return _LaunchPlan(
            node=node, component=component, inputs=inputs, props=props,
            external_fps=external_fps, execution=ex, outputs=outputs,
            all_ctx=all_ctx, t0=t0,
            deadline_s=self._node_timeout_s(node, ir),
            retry_policy=self._node_retry_policy(node, ir),
        )

    def _execute_and_publish(
        self,
        store: MetadataStore,
        plan: _LaunchPlan,
        extras: Dict[str, Any],
        publish_lock: Optional[threading.Lock],
    ) -> NodeResult:
        """LAUNCHER + PUBLISHER phases: run the executor (with per-node
        retries), then fingerprint and publish.  Under the concurrent
        scheduler this runs in a worker thread; every store write goes
        through the run-level publish lock."""
        node, ex = plan.node, plan.execution
        inputs, props, outputs = plan.inputs, plan.props, plan.outputs
        external_fps, all_ctx, t0 = plan.external_fps, plan.all_ctx, plan.t0
        extras = dict(extras)
        # Cooperative cancellation: the watchdog (and drain) set this event;
        # long-running executors may poll it to abort early.
        extras.setdefault("cancel_event", plan.cancel)

        error = ""
        extra_props: Dict[str, Any] = {}
        attempts = 1
        executor = plan.component.EXECUTOR
        # The runner-allocated output locations.  Executors may REASSIGN an
        # artifact's uri (Importer points it at external source data); every
        # retry must reset to — and clean — the ALLOCATED path, never the
        # executor-assigned one (rmtree of a reassigned uri would delete the
        # user's source data).
        allocated_uris = {
            id(a): a.uri for arts in outputs.values() for a in arts
        }
        # Classified retry loop (docs/RECOVERY.md): only transient
        # failures consume the policy's backoff budget; a permanent
        # verdict (bad config, poisoned input) fails the node on the
        # spot.  The node deadline (plan.deadline_s, enforced by the
        # scheduler watchdog) still covers ALL attempts and sleeps.
        policy = plan.retry_policy or RetryPolicy(
            max_attempts=1, base_delay_s=0.0, jitter=False
        )
        retry_t0 = time.monotonic()
        if executor is None:
            error = f"component {node.id} has no executor"
        else:
            while True:
                tmp = tempfile.mkdtemp(prefix=f"tpp-{node.id}-")
                try:
                    for arts in outputs.values():
                        for a in arts:
                            a.uri = allocated_uris[id(a)]
                            # spmd_sync: shared dirs were wiped pre-barrier;
                            # deleting here would race other processes.
                            if not self.spmd_sync and os.path.isdir(a.uri):
                                shutil.rmtree(a.uri)  # clean slate on retry
                            os.makedirs(a.uri, exist_ok=True)
                    ctx = ExecutorContext(
                        node_id=node.id,
                        inputs=inputs,
                        outputs=outputs,
                        exec_properties=props,
                        tmp_dir=tmp,
                        extras=extras,
                    )
                    with _trace.span(
                        "executor", cat="executor", node=node.id,
                        args={"attempt": attempts},
                    ) as tsp:
                        # Fault hook: raise-in-executor / cooperative hang.
                        _faults.in_executor(node.id, plan.cancel)
                        ret = executor(ctx)
                        tsp["ok"] = True
                    extra_props = dict(ret or {})
                    error = ""
                    break
                except Exception as exc:
                    error = traceback.format_exc()
                    verdict = classify_error(exc)
                    log.warning(
                        "node %s attempt %d/%d failed (%s):\n%s",
                        node.id, attempts, policy.max_attempts, verdict,
                        error,
                    )
                    if attempts >= policy.max_attempts:
                        break
                    if verdict != TRANSIENT:
                        log.info(
                            "node %s: %s failure is permanent; not "
                            "retrying (%d attempt(s) left unspent)",
                            node.id, type(exc).__name__,
                            policy.max_attempts - attempts,
                        )
                        break
                    delay = policy.backoff_s(attempts)
                    if policy.deadline_s > 0:
                        remaining = policy.deadline_s - (
                            time.monotonic() - retry_t0
                        )
                        if remaining <= 0:
                            log.warning(
                                "node %s: retry budget (%gs) spent after "
                                "%d attempt(s)", node.id,
                                policy.deadline_s, attempts,
                            )
                            break
                        delay = min(delay, remaining)
                    if plan.cancel.is_set():
                        break  # watchdog expiry / drain: stop retrying
                    record_retry(f"node:{node.id}")
                    _trace.instant(
                        "retry", cat="executor", node=node.id,
                        args={
                            "attempt": attempts,
                            "backoff_s": round(delay, 4),
                            "error_kind": type(exc).__name__,
                        },
                    )
                    # Backoff waits on the cancel event so a draining run
                    # (or the deadline watchdog) wakes it immediately.
                    if delay > 0 and plan.cancel.wait(delay):
                        break
                    attempts += 1
                finally:
                    shutil.rmtree(tmp, ignore_errors=True)

        if self.spmd_sync:
            # Collective status exchange, which is also the barrier ensuring
            # all executor-side writes land before process 0 fingerprints the
            # shared output dirs.  Any process's failure fails the node
            # everywhere — otherwise process 0 would publish COMPLETE (and a
            # cache entry) over an output a worker never finished, and the
            # substrate's retry would then hit that poisoned cache forever.
            import jax
            import numpy as np
            from jax.experimental import multihost_utils

            failures = multihost_utils.process_allgather(
                np.int32(1 if error else 0)
            )
            failed_on = [int(i) for i in np.flatnonzero(np.asarray(failures))]
            if failed_on and not error:
                error = f"executor failed on process(es) {failed_on}"
            if jax.process_index() != 0:
                # Workers' store writes are scratch-discarded; skip the
                # (potentially expensive) fingerprint + publish entirely.
                return NodeResult(
                    node_id=node.id,
                    status="FAILED" if error else "COMPLETE",
                    error=error,
                    wall_clock_s=time.time() - t0,
                    retries=attempts - 1,
                )

        # ---- PUBLISHER
        wall = time.time() - t0
        ex.properties.update(extra_props)
        ex.properties.update(
            {"wall_clock_s": round(wall, 4), "retries": attempts - 1}
        )
        if error:
            # A failed attempt may have left an executor-reassigned uri on
            # an output (Importer); the ABANDONED record must point at the
            # ALLOCATED location, never at the user's external source data.
            for arts in outputs.values():
                for a in arts:
                    a.uri = allocated_uris[id(a)]
            ex.state = ExecutionState.FAILED
            ex.properties["error"] = error.splitlines()[-1] if error else ""
            publish_err = self._publish_fenced(store, plan, publish_lock)
            if publish_err:
                error = f"{error}\n{publish_err}"
            return NodeResult(
                node_id=node.id, status="FAILED", execution_id=ex.id,
                error=error, wall_clock_s=wall, retries=attempts - 1,
            )
        # Fault hook: crash-after-success-before-publish (the state a resume
        # must fence: RUNNING execution + written payload dirs, no events).
        _faults.before_publish(node.id)
        with _trace.span("fingerprint", cat="executor", node=node.id):
            for arts in outputs.values():
                for a in arts:
                    a.fingerprint = (
                        external_fps.get(os.path.abspath(a.uri))
                        or fingerprint_dir(a.uri)
                    )
        ex.state = ExecutionState.COMPLETE
        publish_err = self._publish_fenced(store, plan, publish_lock)
        if publish_err is not None:
            # Store backend died under the publish: the run must record a
            # node failure, not crash (the payload is on disk but without a
            # COMPLETE record it is invisible — a resume re-runs the node).
            return NodeResult(
                node_id=node.id, status="FAILED", execution_id=ex.id,
                error=publish_err, wall_clock_s=wall, retries=attempts - 1,
            )
        if plan.fenced.is_set():
            # The watchdog expired this node while the executor was
            # finishing: the scheduler already settled FAILED(timeout) and
            # published; this result is discarded as a zombie.
            return NodeResult(
                node_id=node.id, status="FAILED", execution_id=ex.id,
                error="fenced by deadline watchdog", wall_clock_s=wall,
            )
        # Fault hook: crash-right-after-publish (the state a resume adopts).
        _faults.after_publish(node.id)
        log.info(
            "node %s: COMPLETE in %.2fs (execution %d)", node.id, wall, ex.id
        )
        return NodeResult(
            node_id=node.id, status="COMPLETE", execution_id=ex.id,
            outputs=outputs, wall_clock_s=wall, retries=attempts - 1,
        )

    @staticmethod
    def _publish_fenced(
        store: MetadataStore,
        plan: _LaunchPlan,
        publish_lock: Optional[threading.Lock],
    ) -> Optional[str]:
        """Publish the plan's execution unless the deadline watchdog fenced
        it first.  The fenced/published handshake runs under the publish
        lock, so exactly one of {worker publish, watchdog FAILED(timeout)
        publish} reaches the store.  Returns an error string when the store
        backend is unavailable (the caller records a node failure), else
        None."""
        try:
            with _trace.span(
                "publish", cat="executor", node=plan.node.id,
                args={"state": plan.execution.state.value},
            ), _maybe_locked(publish_lock):
                if plan.fenced.is_set():
                    return None  # watchdog already published FAILED(timeout)
                plan.published.set()
                store.publish_execution(
                    plan.execution, plan.inputs, plan.outputs, plan.all_ctx
                )
        except StoreUnavailableError as e:
            log.error(
                "node %s: metadata store unavailable during publish: %s",
                plan.node.id, e,
            )
            return f"metadata store unavailable during publish: {e}"
        return None

    def _run_resolver_node(
        self,
        store: MetadataStore,
        ir: PipelineIR,
        node: NodeIR,
        all_ctx: List[Context],
        t0: float,
        runtime_parameters: Dict[str, Any],
    ) -> NodeResult:
        """Driver-level Resolver execution (TFX Resolver semantics): query
        the metadata store per the configured strategy, publish an execution
        whose OUTPUT events reference the EXISTING artifacts (same ids — the
        lineage graph records reuse), and never cache: the strategy's answer
        changes as runs accumulate, so every run must re-query."""
        from tpu_pipelines.components.resolver import resolve_artifacts

        error = ""
        outputs: Dict[str, List[Artifact]] = {}
        props = {
            k: resolve_property(v, runtime_parameters)
            for k, v in node.exec_properties.items()
        }
        try:
            outputs = resolve_artifacts(
                store,
                strategy=props.get("strategy", "latest_blessed_model"),
                pipeline_name=ir.name,
                within_pipeline=bool(props.get("within_pipeline", True)),
                # Strategy-specific knobs (rolling_window's span count and
                # producer filters) ride the exec properties verbatim.
                extra=props,
            )
        except Exception:
            error = traceback.format_exc()
        if self.spmd_sync:
            # Process 0's store view is authoritative (same hazard as
            # _spmd_sync_inputs: snapshot skew across hosts).
            if _spmd_broadcast_int(0 if error else 1):
                outputs = _spmd_sync_inputs(outputs)
                error = ""
            elif not error:
                error = "resolver failed on process 0"
        if error:
            return NodeResult(node_id=node.id, status="FAILED", error=error)

        resolved_ids = sorted(
            a.id for arts in outputs.values() for a in arts
        )
        wall = time.time() - t0
        ex = Execution(
            type_name=node.component_type,
            node_id=node.id,
            state=ExecutionState.COMPLETE,
            properties={
                "strategy": props.get("strategy"),
                "resolved_artifact_ids": resolved_ids,
                "wall_clock_s": round(wall, 4),
            },
        )
        primary = True
        if self.spmd_sync:
            import jax

            primary = jax.process_index() == 0
        if primary:
            store.publish_execution(ex, {}, outputs, all_ctx)
        ex_id = ex.id
        if self.spmd_sync:
            # Only process 0 publishes; its id is the one that exists in the
            # shared store, so every process's NodeResult must carry IT —
            # a non-primary ex.id of 0 would reference a nonexistent
            # execution (round-4 advisor finding).
            ex_id = _spmd_broadcast_int(ex_id)
        log.info(
            "node %s: RESOLVED %s (execution %d)",
            node.id, resolved_ids or "nothing", ex_id,
        )
        return NodeResult(
            node_id=node.id, status="COMPLETE", execution_id=ex_id,
            outputs=outputs, wall_clock_s=wall,
        )

    @staticmethod
    def _resolve_inputs(
        node: NodeIR, produced: Dict[str, Dict[str, List[Artifact]]]
    ) -> Dict[str, List[Artifact]]:
        inputs: Dict[str, List[Artifact]] = {}
        for key, refs in node.inputs.items():
            arts: List[Artifact] = []
            for ref in refs:
                if not ref.producer:
                    # Producer-less channels have no resolution mechanism yet;
                    # ingestion goes through EXTERNAL_INPUT_PARAMETERS or an
                    # Importer-style component.  Fail at driver time instead
                    # of letting the executor crash (and retry) on a
                    # configuration error.
                    raise KeyError(
                        f"{node.id}: input {key!r} is wired to a channel with "
                        "no producer component; external data must enter via "
                        "an ingestion component (e.g. ExampleGen path param)"
                    )
                up = produced.get(ref.producer)
                if up is None:
                    raise KeyError(
                        f"{node.id}: upstream {ref.producer} produced nothing"
                    )
                got = up.get(ref.output_key)
                if not got:
                    # A Resolver that found nothing publishes an EMPTY output
                    # list; an optional downstream input then resolves to an
                    # empty list — the key stays PRESENT, so the executor can
                    # distinguish wired-but-empty (resolver bootstrap) from
                    # never-wired (a configuration gap).  Anything else fails.
                    if key in node.optional_inputs:
                        continue
                    raise KeyError(
                        f"{node.id}: upstream {ref.producer} has no output "
                        f"{ref.output_key!r}"
                    )
                arts.extend(got)
            inputs[key] = arts
        return inputs
