"""TPUJobRunner: compile a pipeline to TPU cluster manifests (no execution).

Equivalent of ``KubeflowDagRunner().run(pipeline)`` (SURVEY.md §3.2), which
only COMPILES — it emits Argo workflow YAML and the operator substrate runs
it.  Here the BASELINE north-star applies: instead of GPU ``TFJob``s the
runner renders **TPU JobSet** specs (jobset.x-k8s.io, the k8s API Cloud TPU
multi-host training uses) plus an Argo ``Workflow`` expressing the component
DAG.  Everything after submission is substrate, not framework.

Emitted per run directory:
  - ``pipeline_ir.json``  — compiled IR (golden-testable)
  - ``workflow.yaml``     — Argo Workflow: one DAG task per component.
    Single-host nodes are container templates invoking
    ``python -m tpu_pipelines.run_node`` in the user image; distributed
    nodes (Trainer/Tuner with ``num_hosts`` > 1) are Argo ``resource``
    templates that CREATE the node's JobSet and await its completion, so
    multi-host training runs inside the DAG with its dependencies honored.
  - ``jobset_<node>.yaml`` — the same JobSet standalone (num_hosts workers,
    TPU nodeSelectors, TPP_* bootstrap env consumed by
    parallel/distributed.py), for manual submission/debugging.

Multi-host wiring: worker 0's headless-service DNS name is the coordination
service address; each worker derives its process id from the JobSet
completion index.  This replaces TF_CONFIG + TFJob operator (SURVEY.md §2b).
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Any, Dict, List, Optional


def _yaml():
    # Lazy: pyyaml is only needed on the compile-to-manifests path, so the
    # local runner (and run_node, the container entrypoint) must not require
    # it at import time.
    import yaml

    return yaml

from tpu_pipelines.dsl.compiler import Compiler, PipelineIR, is_runtime_param
from tpu_pipelines.dsl.pipeline import Pipeline
from tpu_pipelines.parallel.distributed import (
    DEFAULT_PORT,
    ENV_COORDINATOR,
    ENV_NUM_PROCESSES,
)

# Components that train and therefore get a JobSet when num_hosts > 1.
DISTRIBUTED_COMPONENT_TYPES = ("Trainer", "Tuner")

# Fallback TPU classification for IR emitted before NodeIR.resource_class
# existed (SURVEY.md §2a TPU-equiv column); current IR carries the class.
_LEGACY_TPU_COMPONENT_TYPES = (
    "Trainer", "Tuner", "Evaluator", "BulkInferrer", "Transform",
)


def k8s_name(s: str) -> str:
    """DNS-1123 subdomain: lowercase alphanumerics and '-', edge-trimmed."""
    out = re.sub(r"[^a-z0-9-]+", "-", s.lower()).strip("-")
    if not out:
        raise ValueError(f"cannot derive a k8s name from {s!r}")
    return out[:253]


@dataclasses.dataclass
class TPUJobRunnerConfig:
    image: str                              # container image with user code
    pipeline_module: str                    # path inside image defining create_pipeline()
    output_dir: str
    # TPU slice geometry (GKE labels; v5e-8 single host by default).
    tpu_accelerator: str = "tpu-v5-lite-podslice"
    tpu_topology: str = "2x4"
    num_hosts: int = 1
    chips_per_host: int = 8
    namespace: str = "default"
    service_account: str = ""
    workflow_name: str = ""                 # defaults to pipeline name
    # Workflow-wide cap on concurrently running DAG tasks (Argo
    # spec.parallelism) — the cluster mirror of the local runner's
    # ``max_parallel_nodes`` pool.  0 = unlimited (Argo's default: every
    # ready branch schedules).  Independent of the TPU mutex below, which
    # serializes chip-holding nodes regardless of this cap.
    max_parallel_nodes: int = 0
    # Serialize TPU resource-class nodes behind one Argo mutex (the cluster
    # equivalent of the local scheduler's single-chip gate).  Disable on
    # multi-slice clusters where concurrent training pods land on distinct
    # slices.  Tuner trial-shard pods are exempt: their fan-out exists to
    # use many slices at once.
    tpu_mutex: bool = True
    # Shared storage for pipeline_root + the metadata sqlite.  Cross-pod
    # semantics (artifact URIs, run_node's shared-store precondition, orbax
    # collective saves) require every pod to see one filesystem: set
    # ``shared_volume_claim`` to a ReadWriteMany PVC name (NFS/Filestore) and
    # it is mounted at ``shared_mount_path`` in every container; leave it
    # empty only when the image itself provides shared storage at the
    # pipeline's paths (e.g. a GCS FUSE sidecar or bucket mount).
    shared_volume_claim: str = ""
    shared_mount_path: str = "/pipeline"
    # Path to a prior run's RunTrace metrics.json (observability/export.py
    # or `python -m tpu_pipelines trace <run_id> --metrics ...`).  When
    # set, each node template carries the measured duration / queue wait
    # as annotations and the Workflow carries the measured critical path —
    # the profile operators read to size parallelism, deadlines, and
    # preemption budgets without re-running the pipeline.
    trace_metrics_path: str = ""
    # Live-telemetry scrape port (observability/metrics.py).  When > 0,
    # every node pod gets TPP_METRICS_PORT in its env (the local runner
    # then serves /metrics + /healthz on it for the duration of the node)
    # and the matching prometheus.io/scrape|port|path pod annotations, so
    # a cluster Prometheus with kubernetes_sd discovers the pods with no
    # per-pipeline scrape config.  0 = no server, no annotations.
    metrics_port: int = 0
    # Metric-federation spool (observability/federation.py).  When set,
    # every pod gets TPP_FEDERATION_DIR so trainers / fork-pool workers /
    # fleet replicas publish snapshot deltas there, and each pod's
    # /metrics port serves the MERGED host/replica/tenant-labeled scrape.
    # Must live on the shared volume (same precondition as
    # pipeline_root).  "" = federation off, zero footprint.
    federation_spool: str = ""
    # Tenant label stamped on every federated series (TPP_TENANT) — the
    # per-team quota-accounting seam (ROADMAP item 1).  "" = unlabeled.
    tenant: str = ""
    # Durable metrics history (observability/metrics_history.py).  True
    # sets TPP_METRICS_HISTORY=1 in every pod: trainers append scrape
    # snapshots to <pipeline_root>/.runs/_metrics/<run_id>/ for
    # `trace diff` and the continuous controller to read after the pods
    # are gone.
    metrics_history: bool = False
    # Static-analysis gate on the compiled IR (docs/ANALYSIS.md) before any
    # manifest is emitted: "error" (default) refuses on ERROR findings,
    # "warn" on any finding, "off" disables.  Graph rules (TPP1xx) only —
    # executor/module sources belong to the image, not this host, so the
    # Layer-2 code rules run in the pods via the local runner's TPP_LINT.
    lint: str = "error"


class TPUJobRunner:
    """Compile-only runner; returns the paths of the emitted manifests."""

    def __init__(self, config: TPUJobRunnerConfig):
        self.config = config

    def run(self, pipeline: Pipeline) -> Dict[str, str]:
        ir = Compiler().compile(pipeline)
        cfg = self.config
        if (cfg.lint or "").lower() in ("error", "warn"):
            # A workflow that cannot succeed must not reach the cluster:
            # YAML that fans out to N pods before the misconfiguration
            # surfaces wastes chips and poisons the shared store.
            from tpu_pipelines.analysis import analyze_ir, gate_or_raise

            gate_or_raise(
                analyze_ir(ir), cfg.lint.lower(),
                f"cluster compile ({pipeline.name})",
            )
        os.makedirs(cfg.output_dir, exist_ok=True)
        out: Dict[str, str] = {}

        ir_path = os.path.join(cfg.output_dir, "pipeline_ir.json")
        with open(ir_path, "w") as f:
            f.write(ir.to_json_str())
        out["pipeline_ir"] = ir_path

        wf_path = os.path.join(cfg.output_dir, "workflow.yaml")
        with open(wf_path, "w") as f:
            _yaml().safe_dump(self._workflow(ir), f, sort_keys=True)
        out["workflow"] = wf_path

        for node in ir.nodes:
            if self._is_distributed(node):
                js_path = os.path.join(
                    cfg.output_dir, f"jobset_{k8s_name(node.id)}.yaml"
                )
                with open(js_path, "w") as f:
                    _yaml().safe_dump(
                        self._jobset(ir, node.id), f, sort_keys=True
                    )
                out[f"jobset_{node.id}"] = js_path
        return out

    # ------------------------------------------------------------ manifests

    def _node_command(self, node_id: str) -> List[str]:
        return [
            "python", "-m", "tpu_pipelines.run_node",
            "--pipeline-module", self.config.pipeline_module,
            "--node-id", node_id,
        ]

    def _is_distributed(self, node) -> bool:
        return (
            node.component_type in DISTRIBUTED_COMPONENT_TYPES
            and self.config.num_hosts > 1
        )

    @staticmethod
    def _node_retry_strategy(ir: PipelineIR, node) -> Dict[str, Any]:
        """Argo ``retryStrategy`` for a node template — the cluster mirror
        of the local runner's classified retry loop (docs/RECOVERY.md).

        Precedence matches the deadline mapping: component retry policy >
        pipeline default; the env fallback (``TPP_RETRY_*``) is
        deliberately NOT read at compile time (the operator laptop's
        environment is not the cluster's).  With no policy anywhere the
        historical default stays: ``limit: 2`` immediate retries.  With a
        policy, ``limit``/``backoff`` carry its attempts and exponential
        schedule (Argo adds its own jitter server-side).
        """
        from tpu_pipelines.robustness import RetryPolicy

        policy = RetryPolicy.from_json(
            getattr(node, "retry_policy", None)
        ) or RetryPolicy.from_json(
            getattr(ir, "default_retry_policy", None)
        )
        if policy is None:
            return {"limit": 2}
        strategy: Dict[str, Any] = {"limit": policy.retries}
        if policy.base_delay_s > 0:
            strategy["backoff"] = {
                "duration": f"{policy.base_delay_s:g}s",
                "factor": 2,
                "maxDuration": f"{policy.max_delay_s:g}s",
            }
        return strategy

    @staticmethod
    def _node_deadline_s(ir: PipelineIR, node) -> int:
        """Effective execution deadline (whole seconds; 0 = none) — the
        cluster mirror of the local watchdog's precedence: component
        override > pipeline default.  The env fallback (TPP_NODE_TIMEOUT_S)
        is deliberately NOT read at compile time: the operator laptop's
        environment is not the cluster's; set the pipeline default instead.
        """
        t = float(getattr(node, "execution_timeout_s", 0.0) or 0.0)
        if t <= 0:
            t = float(getattr(ir, "default_node_timeout_s", 0.0) or 0.0)
        return int(-(-t // 1)) if t > 0 else 0

    # ------------------------------------------------- tuner trial fan-out

    @staticmethod
    def _tuner_shards(node) -> int:
        """Katib-style fan-out degree for a Tuner node (0 = no fan-out)."""
        if node.component_type != "Tuner":
            return 0
        v = node.exec_properties.get("trial_shards", 0)
        if is_runtime_param(v):
            v = v.get("default") or 0
        try:
            v = int(v)
        except (TypeError, ValueError):
            return 0
        if v > 1:
            algo = node.exec_properties.get("algorithm", "grid")
            # A literal adaptive algorithm can NEVER run with shard fan-out
            # (sequential-by-round; the Tuner rejects it at runtime) — fail
            # at compile time instead of in every emitted shard pod.  A
            # RuntimeParameter algorithm is deferred to the runtime check:
            # its launch-time value may be either way, so compile cannot
            # decide for it.
            if not is_runtime_param(algo) and algo not in ("grid", "random"):
                raise ValueError(
                    f"Tuner node {node.id!r}: trial_shards={v} requires an "
                    f"enumerable algorithm (grid/random), got {algo!r}"
                )
        return v if v > 1 else 0

    @staticmethod
    def _tuner_shard_dir(ir: PipelineIR, node_id: str) -> str:
        # Under pipeline_root: the one filesystem every pod shares.
        return "/".join((ir.pipeline_root.rstrip("/"), ".tuner_shards", node_id))

    def _tuner_trial_command(
        self, ir: PipelineIR, node_id: str, shard: int, num_shards: int
    ) -> List[str]:
        return [
            "python", "-m", "tpu_pipelines.components.tuner_trial", "shard",
            "--pipeline-module", self.config.pipeline_module,
            "--node-id", node_id,
            "--shard", f"{shard}/{num_shards}",
            "--shard-dir", self._tuner_shard_dir(ir, node_id),
        ]

    def _metrics_annotations(self) -> Dict[str, str]:
        """prometheus.io discovery annotations matching the node's live
        /metrics server ({} when metrics_port is unset)."""
        port = self.config.metrics_port
        if port <= 0:
            return {}
        return {
            "prometheus.io/scrape": "true",
            "prometheus.io/port": str(port),
            "prometheus.io/path": "/metrics",
        }

    def _metrics_env(self) -> List[Dict[str, str]]:
        cfg = self.config
        env: List[Dict[str, str]] = []
        if cfg.metrics_port > 0:
            env.append(
                {"name": "TPP_METRICS_PORT", "value": str(cfg.metrics_port)}
            )
        if cfg.federation_spool:
            env.append({
                "name": "TPP_FEDERATION_DIR",
                "value": cfg.federation_spool,
            })
        if cfg.tenant:
            env.append({"name": "TPP_TENANT", "value": cfg.tenant})
        if cfg.metrics_history:
            env.append({"name": "TPP_METRICS_HISTORY", "value": "1"})
        return env

    def _load_trace_metrics(self) -> Dict[str, Any]:
        """Prior-run RunTrace metrics, {} when not configured.

        A configured-but-unreadable path is a compile-time error: silently
        emitting un-annotated manifests would defeat the reason the
        operator pointed at a profile."""
        path = self.config.trace_metrics_path
        if not path:
            return {}
        with open(path, "r", encoding="utf-8") as f:
            metrics = json.load(f)
        if not isinstance(metrics, dict):
            raise ValueError(
                f"trace_metrics_path {path!r} is not a metrics.json object"
            )
        return metrics

    def _workflow(self, ir: PipelineIR) -> Dict[str, Any]:
        cfg = self.config
        name = k8s_name(cfg.workflow_name or ir.name)
        trace_metrics = self._load_trace_metrics()
        trace_per_node = trace_metrics.get("per_node", {})
        tasks = []
        for node in ir.nodes:
            task: Dict[str, Any] = {
                "name": k8s_name(node.id),
                "template": k8s_name(node.id),
            }
            deps = sorted(k8s_name(u) for u in node.upstream)
            shards = self._tuner_shards(node)
            if shards:
                # Katib-style fan-out: one pod per trial shard between the
                # tuner's upstreams and the (merging) tuner node itself.
                trial_names = [
                    k8s_name(f"{node.id}-trial-{i}") for i in range(shards)
                ]
                for tn in trial_names:
                    t: Dict[str, Any] = {"name": tn, "template": tn}
                    if deps:
                        t["dependencies"] = deps
                    tasks.append(t)
                # `depends`, not `dependencies`: upstreams must succeed, but
                # trial pods only need to FINISH — the merge re-runs any
                # shard's missing trials locally (load_shard_results +
                # incremental shard writes), so a preempted shard degrades
                # to local re-runs instead of failing the workflow.
                task["depends"] = " && ".join(
                    [f"{d}.Succeeded" for d in deps]
                    + [
                        f"({t}.Succeeded || {t}.Failed || {t}.Errored)"
                        for t in trial_names
                    ]
                )
            elif deps:
                task["dependencies"] = deps
            tasks.append(task)
        if any("depends" in t for t in tasks):
            # Argo rejects DAG templates that mix `depends` and
            # `dependencies`; when any task needs a `depends` expression
            # (tuner fan-out above), rewrite the plain lists into their
            # equivalent expression so the whole DAG uses one form.
            for t in tasks:
                deps = t.pop("dependencies", None)
                if deps:
                    t["depends"] = " && ".join(
                        f"{d}.Succeeded" for d in deps
                    )
        templates: List[Dict[str, Any]] = [
            {"name": "pipeline-dag", "dag": {"tasks": tasks}}
        ]
        for node in ir.nodes:
            shards = self._tuner_shards(node)
            # The local watchdog's deadline, as Argo's template-level
            # activeDeadlineSeconds: a hung pod is killed by the substrate
            # and the failure counts against retryStrategy — the same
            # "timeouts consume the retry budget" semantics as the local
            # runner (docs/RECOVERY.md precedence table).
            deadline_s = self._node_deadline_s(ir, node)
            retry_strategy = self._node_retry_strategy(ir, node)
            for i in range(shards):
                trial_tpl: Dict[str, Any] = {
                    "name": k8s_name(f"{node.id}-trial-{i}"),
                    "retryStrategy": dict(retry_strategy),
                    "container": {
                        "image": cfg.image,
                        "command": self._tuner_trial_command(
                            ir, node.id, i, shards
                        ),
                        "resources": self._node_resources(node),
                    },
                    "nodeSelector": self._tpu_node_selector(),
                }
                if deadline_s:
                    trial_tpl["activeDeadlineSeconds"] = deadline_s
                if cfg.shared_volume_claim:
                    trial_tpl["container"]["volumeMounts"] = (
                        self._volume_mounts()
                    )
                templates.append(trial_tpl)
            tpl: Dict[str, Any] = {
                "name": k8s_name(node.id),
                "retryStrategy": dict(retry_strategy),
            }
            if deadline_s:
                tpl["activeDeadlineSeconds"] = deadline_s
            if self._is_distributed(node):
                # Create the node's JobSet and await it: multi-host training
                # runs inside the DAG, dependencies intact.
                jobset = self._jobset(ir, node.id)
                tpl["resource"] = {
                    "action": "create",
                    "setOwnerReference": True,
                    "successCondition": "status.terminalState == Completed",
                    "failureCondition": "status.terminalState == Failed",
                    "manifest": _yaml().safe_dump(jobset, sort_keys=True),
                }
            else:
                tpl["container"] = {
                    "image": cfg.image,
                    "command": self._node_command(node.id),
                    "resources": self._node_resources(node),
                }
                if shards:
                    # The tuner node merges the shard pods' scores and is the
                    # single execution MLMD records for the fan-out.
                    tpl["container"]["env"] = [{
                        "name": "TPP_TUNER_SHARD_DIR",
                        "value": self._tuner_shard_dir(ir, node.id),
                    }]
                if cfg.shared_volume_claim:
                    tpl["container"]["volumeMounts"] = self._volume_mounts()
                if self._is_tpu_node(node):
                    tpl["nodeSelector"] = self._tpu_node_selector()
            if cfg.tpu_mutex and self._is_tpu_node(node):
                # One chip-holding node at a time — the Argo equivalent of
                # the local scheduler's TPU resource-class gate.  Trial-shard
                # pods stay exempt (their fan-out targets many slices).
                tpl["synchronization"] = {
                    "mutex": {"name": f"{name}-tpu"}
                }
            info = trace_per_node.get(node.id)
            if info:
                # Measured profile from the prior run's trace: what this
                # node actually cost, on the template the operator reads.
                tpl.setdefault("metadata", {}).setdefault(
                    "annotations", {}
                ).update({
                    "tpu-pipelines/measured-duration-s":
                        str(info.get("wall_s", "")),
                    "tpu-pipelines/measured-queue-wait-s":
                        str(info.get("queue_wait_s", "")),
                })
            if cfg.metrics_port > 0:
                # Live telemetry: the pod serves /metrics + /healthz on
                # TPP_METRICS_PORT (local_runner) and the annotations let
                # a kubernetes_sd Prometheus discover it automatically.
                tpl.setdefault("metadata", {}).setdefault(
                    "annotations", {}
                ).update(self._metrics_annotations())
            metrics_env = self._metrics_env()
            if metrics_env and "container" in tpl:
                # Federation/history knobs flow even without a scrape
                # port — the spool and the snapshot ring are file-based.
                tpl["container"].setdefault("env", []).extend(metrics_env)
            templates.append(tpl)
        spec: Dict[str, Any] = {
            "entrypoint": "pipeline-dag",
            "templates": templates,
        }
        if cfg.max_parallel_nodes > 0:
            spec["parallelism"] = cfg.max_parallel_nodes
        if cfg.shared_volume_claim:
            spec["volumes"] = self._volumes()
        if cfg.service_account:
            spec["serviceAccountName"] = cfg.service_account
        return {
            "apiVersion": "argoproj.io/v1alpha1",
            "kind": "Workflow",
            "metadata": {
                "generateName": f"{name}-",
                "namespace": cfg.namespace,
                "labels": {"tpu-pipelines/pipeline": name},
                # The compiler's topo stage groups: nodes within one group
                # share no data dependency, so Argo schedules them
                # concurrently — the same parallelism the local concurrent
                # scheduler realizes dynamically from its ready set.
                "annotations": {
                    "tpu-pipelines/stage-groups": json.dumps(
                        ir.topo_levels()
                    ),
                    **(
                        {
                            "tpu-pipelines/trace-critical-path": json.dumps({
                                "nodes": trace_metrics.get(
                                    "critical_path_nodes", []
                                ),
                                "seconds": trace_metrics.get(
                                    "critical_path_measured_s", 0.0
                                ),
                            }),
                        }
                        if trace_metrics else {}
                    ),
                },
            },
            "spec": spec,
        }

    def _jobset(self, ir: PipelineIR, node_id: str) -> Dict[str, Any]:
        """Multi-host TPU JobSet for one training node (replaces TFJob)."""
        cfg = self.config
        name = k8s_name(f"{ir.name}-{node_id}")
        coordinator = (
            f"{name}-workers-0-0.{name}:{DEFAULT_PORT}"
        )
        env = [
            {"name": ENV_COORDINATOR, "value": coordinator},
            {"name": ENV_NUM_PROCESSES, "value": str(cfg.num_hosts)},
            # process id comes from the completion index injected by the Job
            # controller; parallel/distributed.py reads it as the fallback.
        ]
        if self._tuner_shards(ir.node(node_id)):
            env.append({
                "name": "TPP_TUNER_SHARD_DIR",
                "value": self._tuner_shard_dir(ir, node_id),
            })
        env.extend(self._metrics_env())
        if cfg.federation_spool:
            # Each training host publishes under its own replica label;
            # the pod name (unique per completion index) is the natural
            # host-stable identity.
            env.append({
                "name": "TPP_FED_REPLICA",
                "valueFrom": {"fieldRef": {"fieldPath": "metadata.name"}},
            })
        container = {
            "name": "worker",
            "image": cfg.image,
            "command": self._node_command(node_id),
            "env": env,
            "resources": {
                "requests": {"google.com/tpu": cfg.chips_per_host},
                "limits": {"google.com/tpu": cfg.chips_per_host},
            },
            "ports": [{"containerPort": DEFAULT_PORT}],
        }
        if cfg.shared_volume_claim:
            container["volumeMounts"] = self._volume_mounts()
        pod_spec: Dict[str, Any] = {
            "subdomain": name,
            "restartPolicy": "Never",
            "nodeSelector": self._tpu_node_selector(),
            "containers": [container],
        }
        if cfg.shared_volume_claim:
            pod_spec["volumes"] = self._volumes()
        pod_template: Dict[str, Any] = {"spec": pod_spec}
        metrics_ann = self._metrics_annotations()
        if metrics_ann:
            # On the POD template (not the JobSet object): kubernetes_sd
            # Prometheus discovers pods, and each worker pod serves its
            # own /metrics.
            pod_template["metadata"] = {"annotations": metrics_ann}
        job_spec: Dict[str, Any] = {
            "parallelism": cfg.num_hosts,
            "completions": cfg.num_hosts,
            "completionMode": "Indexed",
            "backoffLimit": 0,
            "template": pod_template,
        }
        deadline_s = self._node_deadline_s(ir, ir.node(node_id))
        if deadline_s:
            # Enforced by the Job controller itself, so a hung multi-host
            # step dies even when submitted standalone (outside the Argo
            # template whose activeDeadlineSeconds mirrors it).
            job_spec["activeDeadlineSeconds"] = deadline_s
        spec: Dict[str, Any] = {
            "replicatedJobs": [{
                "name": "workers",
                "replicas": 1,
                "template": {"spec": job_spec},
            }],
        }
        from tpu_pipelines.robustness import RetryPolicy

        policy = RetryPolicy.from_json(
            getattr(ir.node(node_id), "retry_policy", None)
        ) or RetryPolicy.from_json(getattr(ir, "default_retry_policy", None))
        if policy is not None and policy.retries > 0:
            # The JobSet-level restart (every worker together) is the only
            # correct retry unit for a collective step: per-pod backoff
            # (Job backoffLimit, pinned 0 above) would restart one worker
            # into its peers' half-dead collectives.  This is why the
            # local runner refuses in-runner retries under spmd_sync
            # (and lint rule TPP108 flags them at compile time): the
            # substrate, not the runner, owns multi-host retry.
            spec["failurePolicy"] = {"maxRestarts": policy.retries}
        return {
            "apiVersion": "jobset.x-k8s.io/v1alpha2",
            "kind": "JobSet",
            "metadata": {
                "name": name,
                "namespace": cfg.namespace,
                "labels": {
                    "tpu-pipelines/pipeline": k8s_name(ir.name),
                    "tpu-pipelines/node": k8s_name(node_id),
                },
            },
            "spec": spec,
        }

    # -------------------------------------------------------- serving

    def emit_serving_manifests(
        self,
        model_name: str,
        model_base_dir: str,
        *,
        replicas: int = 1,
        port: int = 8501,
        grpc_port: int = 8500,
        batching: bool = True,
        on_tpu: bool = False,
    ) -> str:
        """Deployment + Service for the standalone model server — the
        workshop's TF-Serving/KFServing deployment YAML equivalent (SURVEY.md
        §2d, §3.5).  ``model_base_dir`` is the Pusher destination (versioned
        layout) on the shared volume; the server's ``--poll-seconds`` watcher
        hot-swaps each newly pushed version, so pushing IS deploying.
        ``grpc_port`` exposes the gRPC predict surface alongside REST (TF
        Serving's 8500/8501 convention; pass -1 for REST only).  ``on_tpu``
        schedules serving pods onto TPU nodes for jitted on-chip inference;
        default is CPU serving (the usual canary/low-QPS shape).
        """
        cfg = self.config
        name = k8s_name(f"{model_name}-serving")
        labels = {"tpu-pipelines/serving": k8s_name(model_name)}
        command = [
            "python", "-m", "tpu_pipelines.serving",
            "--model-name", model_name,
            "--base-dir", model_base_dir,
            "--port", str(port),
        ]
        if grpc_port >= 0:
            command += ["--grpc-port", str(grpc_port)]
        if batching:
            command.append("--batching")
        ports = [{"containerPort": port, "name": "http"}]
        if grpc_port >= 0:
            ports.append({"containerPort": grpc_port, "name": "grpc"})
        container: Dict[str, Any] = {
            "name": "model-server",
            "image": cfg.image,
            "command": command,
            "ports": ports,
            "readinessProbe": {
                "httpGet": {"path": f"/v1/models/{model_name}", "port": port},
                "initialDelaySeconds": 5,
                "periodSeconds": 10,
            },
            "resources": (
                self._tpu_resources() if on_tpu
                else {"requests": {"cpu": "2", "memory": "4Gi"}}
            ),
        }
        if cfg.shared_volume_claim:
            container["volumeMounts"] = self._volume_mounts()
        pod_spec: Dict[str, Any] = {"containers": [container]}
        if cfg.shared_volume_claim:
            pod_spec["volumes"] = self._volumes()
        if on_tpu:
            pod_spec["nodeSelector"] = self._tpu_node_selector()
        deployment = {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": name, "namespace": cfg.namespace,
                         "labels": labels},
            "spec": {
                "replicas": replicas,
                "selector": {"matchLabels": labels},
                "template": {
                    "metadata": {"labels": labels},
                    "spec": pod_spec,
                },
            },
        }
        service = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": name, "namespace": cfg.namespace,
                         "labels": labels},
            "spec": {
                "selector": labels,
                "ports": (
                    [{"name": "http", "port": port, "targetPort": port}]
                    + (
                        [{"name": "grpc", "port": grpc_port,
                          "targetPort": grpc_port}]
                        if grpc_port >= 0 else []
                    )
                ),
            },
        }
        os.makedirs(cfg.output_dir, exist_ok=True)
        path = os.path.join(cfg.output_dir, f"serving_{k8s_name(model_name)}.yaml")
        with open(path, "w") as f:
            _yaml().safe_dump_all([deployment, service], f, sort_keys=True)
        return path

    def _volumes(self) -> List[Dict[str, Any]]:
        return [{
            "name": "pipeline-shared",
            "persistentVolumeClaim": {
                "claimName": self.config.shared_volume_claim,
            },
        }]

    def _volume_mounts(self) -> List[Dict[str, str]]:
        return [{
            "name": "pipeline-shared",
            "mountPath": self.config.shared_mount_path,
        }]

    def _tpu_node_selector(self) -> Dict[str, str]:
        return {
            "cloud.google.com/gke-tpu-accelerator": self.config.tpu_accelerator,
            "cloud.google.com/gke-tpu-topology": self.config.tpu_topology,
        }

    def _is_tpu_node(self, node) -> bool:
        # Nodes that run jitted on-chip work schedule onto TPU node pools;
        # data/metadata-plane components stay on CPU nodes.  The IR's
        # resource_class (compiled from Component.RESOURCE_CLASS — the same
        # classification the local concurrent scheduler gates the chip on)
        # is authoritative; the legacy type list covers pre-resource-class IR.
        rc = getattr(node, "resource_class", "")
        if rc:
            return rc == "tpu"
        return node.component_type in _LEGACY_TPU_COMPONENT_TYPES

    def _tpu_resources(self) -> Dict[str, Any]:
        return {
            "requests": {"google.com/tpu": self.config.chips_per_host},
            "limits": {"google.com/tpu": self.config.chips_per_host},
        }

    def _node_resources(self, node) -> Dict[str, Any]:
        if self._is_tpu_node(node):
            return self._tpu_resources()
        return {"requests": {"cpu": "2", "memory": "4Gi"}}
