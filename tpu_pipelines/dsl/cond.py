"""Conditional execution: TFX ``tfx.dsl.Cond`` equivalent.

Components constructed inside a ``with Cond(predicate):`` block only
execute when the predicate holds at runtime; otherwise the runner marks
them ``COND_SKIPPED`` (not failed — the run still succeeds) and every
downstream consumer of their outputs cascade-skips the same way.

Predicates are declarative and compile into the IR (no Python callbacks at
runtime — the cluster runner's per-pod execution evaluates the same JSON):

::

    from tpu_pipelines.dsl.cond import Cond, artifact_property, runtime_parameter

    # Deploy-gated push: only when the run was started with deploy=true.
    with Cond(runtime_parameter("deploy", default=False) == True):  # noqa: E712
        pusher = Pusher(model=..., blessing=...)

    # Property-gated: push only high-accuracy models (beyond the blessing).
    with Cond(artifact_property(
        evaluator.outputs["evaluation"], "overall_metrics.accuracy") >= 0.9):
        pusher = Pusher(...)

``artifact_property`` references an upstream output channel; the producer
becomes a dependency of every conditional node, so the property exists by
the time the predicate is evaluated.  Dotted property paths traverse
nested dicts.  Conditions nest (inner blocks AND with outer ones).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

_OPS = {
    "eq": lambda a, b: a == b,
    "ne": lambda a, b: a != b,
    "gt": lambda a, b: a is not None and a > b,
    "ge": lambda a, b: a is not None and a >= b,
    "lt": lambda a, b: a is not None and a < b,
    "le": lambda a, b: a is not None and a <= b,
}


@dataclasses.dataclass
class Predicate:
    """One comparison; ``kind`` is "artifact_property" (channel + dotted
    property path) or "runtime_parameter" (name + default)."""

    kind: str
    op: str
    value: Any
    # artifact_property:
    channel: Any = None          # dsl Channel (compile-time only)
    prop: str = ""
    # runtime_parameter:
    param: str = ""
    default: Any = None

    def to_json(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"kind": self.kind, "op": self.op,
                             "value": self.value}
        if self.kind == "artifact_property":
            d["producer"] = self.channel.producer.id
            d["output_key"] = self.channel.output_key
            d["prop"] = self.prop
        else:
            d["param"] = self.param
            d["default"] = self.default
        return d

    def __bool__(self) -> bool:
        # Chained comparisons (`0.5 <= ref <= 0.9`) would silently AND
        # through truthiness and drop the first predicate; make the misuse
        # loud instead (the SQLAlchemy/numpy comparison-builder guard).
        raise TypeError(
            "a Cond predicate has no truth value; chained comparisons like "
            "`lo <= artifact_property(...) <= hi` are not supported — nest "
            "two Cond blocks (or two predicates) instead"
        )


class _Comparable:
    """Builder half of a predicate; comparison operators finish it."""

    def _make(self, op: str, value: Any) -> Predicate:
        raise NotImplementedError

    def __eq__(self, other):  # noqa: D105 — intentional predicate builder
        return self._make("eq", other)

    def __ne__(self, other):
        return self._make("ne", other)

    def __gt__(self, other):
        return self._make("gt", other)

    def __ge__(self, other):
        return self._make("ge", other)

    def __lt__(self, other):
        return self._make("lt", other)

    def __le__(self, other):
        return self._make("le", other)

    __hash__ = None  # comparisons build predicates; not a hashable value


class _PropertyRef(_Comparable):
    def __init__(self, channel, prop: str):
        self.channel = channel
        self.prop = prop

    def _make(self, op: str, value: Any) -> Predicate:
        return Predicate(
            kind="artifact_property", op=op, value=value,
            channel=self.channel, prop=self.prop,
        )


class _ParamRef(_Comparable):
    def __init__(self, param: str, default: Any = None):
        self.param = param
        self.default = default

    def _make(self, op: str, value: Any) -> Predicate:
        return Predicate(
            kind="runtime_parameter", op=op, value=value,
            param=self.param, default=self.default,
        )


def artifact_property(channel, prop: str) -> _PropertyRef:
    """Reference an output artifact's property for a Cond predicate;
    ``prop`` may be a dotted path into nested dict properties.  The channel
    must have a producer component — the predicate is evaluated against the
    producer's published outputs."""
    if getattr(channel, "producer", None) is None:
        raise ValueError(
            "artifact_property requires a channel with a producer component "
            "(e.g. some_node.outputs['key']); a producer-less channel has no "
            "published properties to evaluate"
        )
    return _PropertyRef(channel, prop)


def runtime_parameter(name: str, default: Any = None) -> _ParamRef:
    """Reference a runtime parameter for a Cond predicate."""
    return _ParamRef(name, default)


_ACTIVE: List["Cond"] = []


class Cond:
    def __init__(self, predicate: Predicate):
        if not isinstance(predicate, Predicate):
            raise TypeError(
                "Cond expects a predicate built from artifact_property()/"
                f"runtime_parameter() comparisons, got {type(predicate).__name__}"
            )
        self.predicate = predicate

    def __enter__(self) -> "Cond":
        _ACTIVE.append(self)
        return self

    def __exit__(self, *exc) -> None:
        _ACTIVE.pop()


def active_predicates() -> List[Predicate]:
    """Predicates of every open Cond block (outermost first) — captured by
    Component.__init__ for nodes constructed inside the blocks."""
    return [c.predicate for c in _ACTIVE]


def _dotted(d: Any, path: str) -> Any:
    cur = d
    for part in path.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur


class ConditionUnresolvedError(RuntimeError):
    """The predicate's producer has no published outputs at all.

    Distinct from an unmet predicate (round-4 advisor finding): when the
    producer never executed — e.g. a partial run whose ``to_nodes`` range
    excludes it and no prior-run history exists — silently reporting the
    gated node as COND_SKIPPED would mask a configuration mistake as a
    legitimately unmet condition.  The runner surfaces this as a node
    FAILURE instead."""


def evaluate_condition(
    cond: Dict[str, Any],
    produced: Dict[str, Dict[str, List[Any]]],
    runtime_parameters: Dict[str, Any],
) -> bool:
    """Evaluate one serialized predicate against this run's state.

    Raises :class:`ConditionUnresolvedError` when the predicate reads an
    artifact property but the producer has no published outputs for the
    key — 'never ran' must not be conflated with 'ran and the property
    does not satisfy the predicate' (which returns False)."""
    op = _OPS[cond["op"]]
    if cond["kind"] == "runtime_parameter":
        actual = runtime_parameters.get(cond["param"], cond.get("default"))
        return bool(op(actual, cond["value"]))
    outputs = produced.get(cond["producer"])
    if not outputs or cond["output_key"] not in outputs:
        # The producer never published AT ALL (the output key is absent,
        # not merely empty): a configuration mistake, not an unmet
        # condition.
        raise ConditionUnresolvedError(
            f"condition on {cond['producer']}.{cond['output_key']}"
            f".{cond['prop']} cannot be evaluated: the producer has no "
            "published outputs in this run or any prior run. In a partial "
            "run, include the producer in the node selection (or run the "
            "full pipeline once first)."
        )
    arts = outputs[cond["output_key"]] or []
    if not arts:
        # The producer RAN and published an empty output list (a Resolver
        # that found nothing, e.g. no blessed model yet): a legitimately
        # unmet condition — skip, don't fail.
        return False
    actual = _dotted(arts[0].properties, cond["prop"])
    return bool(op(actual, cond["value"]))
