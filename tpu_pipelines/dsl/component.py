"""Component model: spec + executor, wired by typed channels.

A component is (1) a declarative spec — typed input/output channels and
exec-properties — and (2) an executor function invoked by a runner's launcher
with resolved artifacts.  This mirrors the TFX component = spec + driver +
executor split (SURVEY.md §2a); the driver half (input resolution, caching)
lives in the orchestrator.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Type

from tpu_pipelines.dsl.artifact_types import ARTIFACT_TYPES
from tpu_pipelines.metadata.types import Artifact


class Channel:
    """A typed edge: references a producer component's output key.

    Channels are how the Pipeline discovers the DAG — no explicit edge list;
    dependency = consuming another component's output channel, exactly like
    TFX's ``Channel``/artifact-query model.
    """

    def __init__(
        self,
        type_name: str,
        producer: Optional["Component"] = None,
        output_key: str = "",
    ):
        if type_name not in ARTIFACT_TYPES:
            raise ValueError(f"Unknown artifact type: {type_name!r}")
        self.type_name = type_name
        self.producer = producer
        self.output_key = output_key

    def __repr__(self) -> str:
        src = (
            f"{self.producer.id}.{self.output_key}" if self.producer else "<external>"
        )
        return f"Channel({self.type_name} from {src})"


@dataclasses.dataclass
class Parameter:
    """Declared exec-property: type-checked, defaultable."""

    type: type = object
    default: Any = None
    required: bool = False


class RuntimeParameter:
    """Deploy-time placeholder substituted by the runner at run start.

    Equivalent of TFX's ``RuntimeParameter`` (SURVEY.md §5 config system):
    the compiled IR stores the placeholder; ``Runner.run(...,
    runtime_parameters={name: value})`` substitutes it.
    """

    def __init__(self, name: str, default: Any = None):
        self.name = name
        self.default = default

    def __repr__(self) -> str:
        return f"RuntimeParameter({self.name!r}, default={self.default!r})"


@dataclasses.dataclass
class ComponentSpec:
    inputs: Dict[str, str] = dataclasses.field(default_factory=dict)    # key -> artifact type
    outputs: Dict[str, str] = dataclasses.field(default_factory=dict)   # key -> artifact type
    parameters: Dict[str, Parameter] = dataclasses.field(default_factory=dict)
    # Input keys that may be left unwired (e.g. Trainer without a Transform).
    optional_inputs: tuple = ()


@dataclasses.dataclass
class ExecutorContext:
    """Everything an executor sees: resolved artifacts + properties.

    ``inputs``/``outputs`` map spec keys to artifact lists; output artifact
    uris are pre-allocated directories the executor writes into.  ``extras``
    carries runner-provided handles (mesh config, metadata store for
    sub-lineage, tmp dir).
    """

    node_id: str
    inputs: Dict[str, List[Artifact]]
    outputs: Dict[str, List[Artifact]]
    exec_properties: Dict[str, Any]
    tmp_dir: str = ""
    extras: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def input(self, key: str) -> Artifact:
        arts = self.inputs.get(key) or []
        if not arts:
            raise KeyError(f"{self.node_id}: no input artifact for {key!r}")
        return arts[0]

    def output(self, key: str) -> Artifact:
        arts = self.outputs.get(key) or []
        if not arts:
            raise KeyError(f"{self.node_id}: no output artifact for {key!r}")
        return arts[0]


# Executor: a plain callable.  Returning a dict merges those entries into the
# execution's recorded properties (e.g. examples/sec from the Trainer).
ExecutorFn = Callable[[ExecutorContext], Optional[Dict[str, Any]]]


def _coerce_retry_policy(value, owner: str):
    """Normalize a RetryPolicy | dict | None into a RetryPolicy (or None).

    Lives here so the DSL accepts the ergonomic forms while the IR always
    carries one canonical shape; a bad value fails at authoring time, not
    minutes into a run.
    """
    if value is None:
        return None
    from tpu_pipelines.robustness import RetryPolicy

    if isinstance(value, RetryPolicy):
        return value
    if isinstance(value, dict):
        try:
            return RetryPolicy(**value) if value else None
        except (TypeError, ValueError) as e:
            raise ValueError(
                f"{owner}: invalid retry_policy {value!r}: {e}"
            ) from e
    raise TypeError(
        f"{owner}: retry_policy must be a RetryPolicy or dict, got "
        f"{type(value).__name__}"
    )


class Component:
    """Base class for pipeline nodes.

    Subclasses declare ``SPEC`` and ``EXECUTOR``; instances are constructed
    with channels for spec inputs and values for spec parameters::

        stats = StatisticsGen(examples=example_gen.outputs["examples"])

    Instances expose ``.outputs[key]`` channels for downstream wiring.
    """

    SPEC: ComponentSpec = ComponentSpec()
    EXECUTOR: Optional[ExecutorFn] = None
    # Bump or override to invalidate cached executions when semantics change
    # in ways source-hashing can't see (e.g. data format revision).
    CACHE_SALT: str = ""
    # Scheduler resource class: "host" components (data/metadata plane) may
    # overlap freely under the concurrent runner; "tpu" components run jitted
    # on-chip work, so at most one executes at a time (no device contention,
    # no compile-cache thrash).  The cluster runner uses the same class for
    # TPU node selection and the per-pipeline chip mutex.
    RESOURCE_CLASS: str = "host"
    # Exec-property keys whose values are *external* filesystem paths (data
    # the pipeline ingests but no upstream node produced).  The driver
    # fingerprints the referenced content into the cache key, so editing the
    # file invalidates the cache even though the path string is unchanged —
    # the equivalent of TFX ExampleGen's input-fingerprint/span mechanism.
    EXTERNAL_INPUT_PARAMETERS: tuple = ()
    # Execution deadline in seconds (0 = none).  The deadline covers the
    # node's whole launcher phase — all retry attempts included — so a hung
    # executor cannot stall the run forever.  Precedence: this component
    # override > Pipeline(node_timeout_s=...) > env TPP_NODE_TIMEOUT_S.
    # Locally a scheduler watchdog enforces it; on the cluster it maps to
    # activeDeadlineSeconds (Argo template / JobSet job).
    EXECUTION_TIMEOUT_S: float = 0.0
    # Declared side effect: the node's value is what it DOES (push a model,
    # gate a blessing, write external predictions), not the artifacts it
    # emits — so the TPP101 dead-end lint rule must not flag its unconsumed
    # outputs.  Pusher/validators/BulkInferrer/Evaluator set this.
    IS_SINK: bool = False
    # Lint rule ids suppressed for every instance of this component
    # (per-instance: .with_lint_suppressions("TPP103")).  Compiled into
    # NodeIR.lint_suppress; see docs/ANALYSIS.md.
    LINT_SUPPRESS: tuple = ()
    # Per-node retry policy (tpu_pipelines.robustness.RetryPolicy or its
    # dict form; None = fall back to the pipeline default, then env
    # TPP_RETRY_*).  Covers the node's executor attempts with classified
    # (transient-only) retries, exponential backoff + full jitter, and an
    # optional total budget.  Locally the runner's launcher loop enforces
    # it; on the cluster it maps to Argo retryStrategy / JobSet restarts.
    # Like deadlines, it is operational metadata: excluded from the DAG
    # fingerprint, so tuning retries never blocks resume_from.
    RETRY_POLICY = None
    # Module-file entry points the Layer-2 analyzer walks in addition to
    # EXECUTOR: names loaded from exec_properties["module_file"] at run
    # time (Trainer: run_fn; Transform: preprocessing_fn).
    LINT_MODULE_FNS: tuple = ()

    def __init__(self, instance_name: str = "", **kwargs: Any):
        cls = type(self)
        self.id = instance_name or cls.__name__
        self.input_channels: Dict[str, List[Channel]] = {}
        self.exec_properties: Dict[str, Any] = {}
        self.execution_timeout_s = float(cls.EXECUTION_TIMEOUT_S or 0.0)
        self.lint_suppress: List[str] = [str(r) for r in cls.LINT_SUPPRESS]
        self.retry_policy = _coerce_retry_policy(cls.RETRY_POLICY, self.id)

        for key, value in kwargs.items():
            # A key may name both an input and a parameter (e.g. Trainer's
            # `hyperparameters`: Tuner artifact OR literal dict); the value
            # type disambiguates.
            looks_like_channel = isinstance(value, Channel) or (
                isinstance(value, list)
                and value
                and all(isinstance(v, Channel) for v in value)
            )
            if key in self.SPEC.inputs and (
                looks_like_channel or key not in self.SPEC.parameters
            ):
                chans = value if isinstance(value, list) else [value]
                for ch in chans:
                    if not isinstance(ch, Channel):
                        raise TypeError(
                            f"{self.id}: input {key!r} must be a Channel, got "
                            f"{type(ch).__name__}"
                        )
                    expected = self.SPEC.inputs[key]
                    if ch.type_name != expected:
                        raise TypeError(
                            f"{self.id}: input {key!r} expects artifact type "
                            f"{expected}, got {ch.type_name}"
                        )
                self.input_channels[key] = chans
            elif key in self.SPEC.parameters:
                self.exec_properties[key] = value
            else:
                raise TypeError(f"{self.id}: unknown argument {key!r}")

        for key, param in self.SPEC.parameters.items():
            if key not in self.exec_properties:
                if param.required:
                    raise TypeError(f"{self.id}: missing required parameter {key!r}")
                self.exec_properties[key] = param.default

        missing = [
            k for k in self.SPEC.inputs
            if k not in self.input_channels and k not in self.SPEC.optional_inputs
        ]
        if missing:
            raise TypeError(f"{self.id}: missing required inputs {missing}")

        self.outputs: Dict[str, Channel] = {
            key: Channel(type_name, producer=self, output_key=key)
            for key, type_name in self.SPEC.outputs.items()
        }

        # Conditions from enclosing `with Cond(...)` blocks (dsl/cond.py):
        # the runner only executes this node when every predicate holds.
        from tpu_pipelines.dsl.cond import active_predicates

        self.conditions = active_predicates()

    @property
    def upstream(self) -> List["Component"]:
        deps = []
        for chans in self.input_channels.values():
            for ch in chans:
                if ch.producer is not None:
                    deps.append(ch.producer)
        # Predicate channels are dependencies too: the producer must have
        # run (and published properties) before the condition is evaluated.
        for pred in self.conditions:
            ch = getattr(pred, "channel", None)
            if ch is not None and ch.producer is not None:
                deps.append(ch.producer)
        return deps

    def with_id(self, instance_name: str) -> "Component":
        self.id = instance_name
        return self

    def with_execution_timeout(self, seconds: float) -> "Component":
        """Per-instance deadline override (chainable, like ``with_id``)."""
        if seconds < 0:
            raise ValueError(
                f"{self.id}: execution timeout must be >= 0, got {seconds}"
            )
        self.execution_timeout_s = float(seconds)
        return self

    def with_retry_policy(self, policy=None, **kwargs: Any) -> "Component":
        """Per-instance retry policy override (chainable, like
        ``with_execution_timeout``).

        Pass a :class:`~tpu_pipelines.robustness.RetryPolicy`, its dict
        form, or bare fields::

            trainer.with_retry_policy(max_attempts=3, base_delay_s=1.0)

        ``None`` with no fields clears the override back to the pipeline/
        env default.
        """
        if policy is not None and kwargs:
            raise ValueError(
                f"{self.id}: pass a policy object OR field overrides, "
                "not both"
            )
        self.retry_policy = _coerce_retry_policy(
            kwargs if kwargs else policy, self.id
        )
        return self

    def with_lint_suppressions(self, *rules: str) -> "Component":
        """Suppress analyzer rules for THIS node (chainable).

        ``rules`` are catalog ids ("TPP103"); unknown ids raise so a typo
        cannot silently disable nothing.  Suppressions compile into the IR
        and apply to both graph (TPP1xx) and code (TPP2xx) findings.
        """
        from tpu_pipelines.analysis.findings import RULES

        for r in rules:
            if r.upper() not in RULES:
                raise ValueError(
                    f"{self.id}: unknown lint rule {r!r}; known rules: "
                    f"{sorted(RULES)}"
                )
            if r.upper() not in self.lint_suppress:
                self.lint_suppress.append(r.upper())
        return self

    def __repr__(self) -> str:
        return f"{type(self).__name__}(id={self.id!r})"


def component(
    inputs: Optional[Dict[str, str]] = None,
    outputs: Optional[Dict[str, str]] = None,
    parameters: Optional[Dict[str, Parameter]] = None,
    name: Optional[str] = None,
    external_input_parameters: tuple = (),
    optional_inputs: tuple = (),
    resource_class: str = "host",
    execution_timeout_s: float = 0.0,
    is_sink: bool = False,
    lint_module_fns: tuple = (),
    retry_policy=None,
) -> Callable[[ExecutorFn], Type[Component]]:
    """Decorator: build a Component subclass from a bare executor function.

    ::

        @component(inputs={"examples": "Examples"},
                   outputs={"statistics": "ExampleStatistics"})
        def StatisticsGen(ctx):
            ...
    """

    def wrap(fn: ExecutorFn) -> Type[Component]:
        cls_name = name or fn.__name__
        if resource_class not in ("host", "tpu"):
            raise ValueError(
                f"{cls_name}: resource_class must be 'host' or 'tpu', "
                f"got {resource_class!r}"
            )
        spec = ComponentSpec(
            inputs=dict(inputs or {}),
            outputs=dict(outputs or {}),
            parameters=dict(parameters or {}),
            optional_inputs=tuple(optional_inputs),
        )
        return type(
            cls_name,
            (Component,),
            {
                "SPEC": spec,
                "EXECUTOR": staticmethod(fn),
                "__doc__": fn.__doc__,
                "EXTERNAL_INPUT_PARAMETERS": tuple(external_input_parameters),
                "RESOURCE_CLASS": resource_class,
                "EXECUTION_TIMEOUT_S": float(execution_timeout_s),
                "IS_SINK": bool(is_sink),
                "LINT_MODULE_FNS": tuple(lint_module_fns),
                "RETRY_POLICY": _coerce_retry_policy(retry_policy, cls_name),
            },
        )

    return wrap
