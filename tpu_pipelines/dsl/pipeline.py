"""Pipeline: a named DAG of components with a root artifact directory."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from tpu_pipelines.dsl.component import Component


class Pipeline:
    """A named collection of components; edges come from channel wiring.

    ``pipeline_root`` is where artifact payloads live
    (``<root>/<node>/<output_key>/<execution_id>/``); ``metadata_path`` is the
    SQLite metadata store ( ``:memory:`` for tests).
    """

    def __init__(
        self,
        name: str,
        components: Sequence[Component],
        pipeline_root: str,
        metadata_path: str = ":memory:",
        enable_cache: bool = True,
        node_timeout_s: float = 0.0,
        retry_policy=None,
    ):
        self.name = name
        self.pipeline_root = pipeline_root
        self.metadata_path = metadata_path
        self.enable_cache = enable_cache
        # Default per-node execution deadline (seconds; 0 = none).  A
        # component's own EXECUTION_TIMEOUT_S / with_execution_timeout()
        # overrides it; env TPP_NODE_TIMEOUT_S is the outermost fallback.
        if node_timeout_s < 0:
            raise ValueError(
                f"Pipeline {name!r}: node_timeout_s must be >= 0"
            )
        self.node_timeout_s = float(node_timeout_s)
        # Default per-node retry policy (RetryPolicy | dict | None).  A
        # component's own RETRY_POLICY / with_retry_policy() overrides it;
        # env TPP_RETRY_* is the outermost fallback — the same precedence
        # shape as node_timeout_s (docs/RECOVERY.md).
        from tpu_pipelines.dsl.component import _coerce_retry_policy

        self.retry_policy = _coerce_retry_policy(
            retry_policy, f"Pipeline {name!r}"
        )
        self.components = self._closure_in_topo_order(components)
        ids = [c.id for c in self.components]
        dupes = {i for i in ids if ids.count(i) > 1}
        if dupes:
            # Importer-specific diagnosis (round-4 advisor finding): two
            # Importers of the same artifact_type both default to
            # 'Importer.<type>', and the generic duplicate-id error hides
            # the actual fix (pass instance_name=).
            hints = []
            for d in sorted(dupes):
                uris = {
                    c.exec_properties.get("source_uri")
                    for c in self.components
                    if c.id == d and "source_uri" in c.exec_properties
                }
                if len(uris) > 1:
                    hints.append(
                        f"{d!r} is the default id shared by Importer nodes "
                        f"for different sources {sorted(uris)}; pass "
                        "instance_name= to each Importer to disambiguate"
                    )
            raise ValueError(
                f"Pipeline {name!r}: duplicate component ids {sorted(dupes)}; "
                "use .with_id() to disambiguate"
                + ("".join(f". {h}" for h in hints))
            )

    @staticmethod
    def _closure_in_topo_order(components: Sequence[Component]) -> List[Component]:
        """Transitive closure over upstream producers, topologically sorted.

        Deterministic: stable DFS post-order over the declaration order, so
        compiling the same pipeline twice yields byte-identical IR.
        """
        order: List[Component] = []
        state: Dict[int, int] = {}  # id(component) -> 0 visiting / 1 done

        def visit(c: Component, chain: List[str]) -> None:
            s = state.get(id(c))
            if s == 1:
                return
            if s == 0:
                raise ValueError(
                    f"Pipeline has a cycle through: {' -> '.join(chain + [c.id])}"
                )
            state[id(c)] = 0
            for dep in c.upstream:
                visit(dep, chain + [c.id])
            state[id(c)] = 1
            order.append(c)

        for c in components:
            visit(c, [])
        return order

    def get(self, component_id: str) -> Optional[Component]:
        for c in self.components:
            if c.id == component_id:
                return c
        return None
