"""Standard artifact types — the vocabulary of the canonical DAG.

Mirrors the TFX standard artifact taxonomy (Examples, ExampleStatistics,
Schema, ExampleAnomalies, TransformGraph, Model, ModelEvaluation,
ModelBlessing, InferenceResult, PushedModel, HyperParameters) so every
capability in SURVEY.md §2a has a typed artifact to flow through channels.
"""

from __future__ import annotations

from typing import Dict


class _ArtifactType:
    def __init__(self, name: str, doc: str):
        self.name = name
        self.doc = doc

    def __repr__(self) -> str:
        return f"ArtifactType({self.name})"


ARTIFACT_TYPES: Dict[str, _ArtifactType] = {}


def _register(name: str, doc: str) -> _ArtifactType:
    t = _ArtifactType(name, doc)
    ARTIFACT_TYPES[name] = t
    return t


def register_artifact_type(name: str, doc: str = "") -> _ArtifactType:
    """Register a custom artifact type (TFX custom-Artifact equivalent).

    Idempotent for a same-named existing type; used by pipeline authors
    whose components flow domain artifacts the standard taxonomy lacks
    (and by Importer when pointing at such data)."""
    existing = ARTIFACT_TYPES.get(name)
    if existing is not None:
        return existing
    return _register(name, doc or "Custom artifact type.")


class standard_artifacts:
    """Namespace of the built-in artifact types."""

    Examples = _register(
        "Examples", "Split example data (train/eval), columnar on disk."
    )
    ExampleStatistics = _register(
        "ExampleStatistics", "Per-split full-pass dataset statistics."
    )
    Schema = _register("Schema", "Inferred/curated dataset schema.")
    ExampleAnomalies = _register(
        "ExampleAnomalies", "Anomalies from validating stats against a schema."
    )
    TransformGraph = _register(
        "TransformGraph",
        "Serialized skew-free transform: analyzer state + traced apply fn.",
    )
    Model = _register("Model", "Trained model: params checkpoint + export.")
    ModelRun = _register("ModelRun", "Training logs / TensorBoard run dir.")
    ModelEvaluation = _register(
        "ModelEvaluation", "Sliced metrics from the Evaluator."
    )
    ModelBlessing = _register(
        "ModelBlessing", "Evaluator gate decision consumed by Pusher."
    )
    InfraBlessing = _register(
        "InfraBlessing", "InfraValidator smoke-serving decision."
    )
    InferenceResult = _register(
        "InferenceResult", "BulkInferrer batch predictions."
    )
    PushedModel = _register("PushedModel", "Versioned, served model payload.")
    HyperParameters = _register(
        "HyperParameters", "Best hyperparameters found by the Tuner."
    )
    TunerResults = _register("TunerResults", "Full trial table from the Tuner.")
