"""Compiler: DSL Pipeline → JSON-serializable IR.

Equivalent of TFX's DSL→pipeline-IR-proto compile step (SURVEY.md §1 L3).
The IR is what runners consume: the local runner walks it in-process; the
cluster runner renders one pod spec per IR node.  Golden-IR tests pin the
format (SURVEY.md §4 "Compiler/IR tests").
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Dict, List, Optional

from tpu_pipelines.dsl.component import Component, RuntimeParameter
from tpu_pipelines.dsl.pipeline import Pipeline
from tpu_pipelines.utils.fingerprint import canonical_json, fingerprint_callable

IR_SCHEMA_VERSION = "tpu-pipelines-ir/v1"

_RUNTIME_PARAM_KEY = "__runtime_parameter__"


def encode_property(value: Any) -> Any:
    if isinstance(value, RuntimeParameter):
        return {_RUNTIME_PARAM_KEY: value.name, "default": value.default}
    return value


def is_runtime_param(value: Any) -> bool:
    return isinstance(value, dict) and _RUNTIME_PARAM_KEY in value


def resolve_property(value: Any, runtime_parameters: Dict[str, Any]) -> Any:
    if is_runtime_param(value):
        name = value[_RUNTIME_PARAM_KEY]
        return runtime_parameters.get(name, value.get("default"))
    return value


@dataclasses.dataclass
class InputRef:
    producer: str       # producing node id; "" for external inputs
    output_key: str
    type_name: str

    def to_json(self) -> Dict[str, str]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class NodeIR:
    id: str
    component_type: str
    inputs: Dict[str, List[InputRef]]
    outputs: Dict[str, str]                 # key -> artifact type
    exec_properties: Dict[str, Any]
    executor_version: str
    upstream: List[str]
    # Exec-property keys holding external data paths; the driver fingerprints
    # their content into the cache key (stale-cache guard for ingestion).
    external_input_parameters: List[str] = dataclasses.field(default_factory=list)
    # Input keys allowed to resolve empty (downstream executor sees the key
    # absent) — how a Resolver that found nothing feeds an optional input.
    optional_inputs: List[str] = dataclasses.field(default_factory=list)
    # Driver-level node (TFX Resolver equivalent): the runner resolves its
    # outputs from the metadata store instead of launching an executor, and
    # never caches it (its answer changes as runs accumulate).
    is_resolver: bool = False
    # Serialized Cond predicates (dsl/cond.py); ALL must hold or the runner
    # marks the node COND_SKIPPED and cascades to its consumers.
    conditions: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    # Scheduler resource class ("host" | "tpu"): the concurrent local runner
    # admits at most one "tpu" node at a time; the cluster runner maps the
    # same class to TPU nodeSelectors and the per-pipeline chip mutex.
    resource_class: str = "host"
    # Per-node execution deadline in seconds (0 = fall back to the pipeline
    # default, then env TPP_NODE_TIMEOUT_S).  Local runner: scheduler
    # watchdog; cluster runner: activeDeadlineSeconds.
    execution_timeout_s: float = 0.0
    # Declared side effect (Component.IS_SINK): exempts the node from the
    # TPP101 dead-end analyzer rule — its unconsumed outputs are expected.
    is_sink: bool = False
    # Analyzer rule ids suppressed for this node (Component.LINT_SUPPRESS /
    # .with_lint_suppressions()); tpu_pipelines/analysis drops matching
    # findings.  Operational metadata: excluded from the DAG fingerprint.
    lint_suppress: List[str] = dataclasses.field(default_factory=list)
    # Per-node retry policy in RetryPolicy.to_json() form (None = fall back
    # to PipelineIR.default_retry_policy, then env TPP_RETRY_*).  Local
    # runner: classified backoff retries in the launcher loop; cluster
    # runner: Argo retryStrategy / JobSet restarts.  Operational metadata,
    # excluded from the DAG fingerprint like deadlines.
    retry_policy: Optional[Dict[str, Any]] = None

    def to_json(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "component_type": self.component_type,
            "inputs": {
                k: [r.to_json() for r in refs] for k, refs in self.inputs.items()
            },
            "outputs": dict(self.outputs),
            "exec_properties": self.exec_properties,
            "executor_version": self.executor_version,
            "upstream": list(self.upstream),
            "external_input_parameters": list(self.external_input_parameters),
            "optional_inputs": list(self.optional_inputs),
            "is_resolver": self.is_resolver,
            "conditions": list(self.conditions),
            "resource_class": self.resource_class,
            "execution_timeout_s": self.execution_timeout_s,
            "is_sink": self.is_sink,
            "lint_suppress": list(self.lint_suppress),
            "retry_policy": (
                dict(self.retry_policy) if self.retry_policy else None
            ),
        }


@dataclasses.dataclass
class PipelineIR:
    name: str
    pipeline_root: str
    metadata_path: str
    enable_cache: bool
    nodes: List[NodeIR]
    schema_version: str = IR_SCHEMA_VERSION
    # Pipeline-wide default node deadline (0 = none); a node's own
    # execution_timeout_s takes precedence.
    default_node_timeout_s: float = 0.0
    # Pipeline-wide default retry policy (RetryPolicy.to_json() form, None
    # = none); a node's own retry_policy takes precedence.  Operational —
    # excluded from fingerprint().
    default_retry_policy: Optional[Dict[str, Any]] = None
    # Execution-context flag, set by callers that KNOW this IR will run
    # under the spmd_sync runner (multi-host run_node, `lint --spmd-sync`).
    # Not compiled from the DSL (distribution degree lives in the runner
    # config) and excluded from fingerprint(); the TPP108 analyzer rule
    # reads it to catch in-runner retry policies that the spmd runner
    # would refuse at runtime.
    spmd_sync: bool = False
    # Execution-context flag like spmd_sync, set by callers that KNOW this
    # IR will be driven by the continuous controller (`lint --continuous`,
    # ContinuousController's own pre-flight).  Excluded from fingerprint();
    # the TPP111 analyzer rule reads it: a node with neither a deadline
    # nor a retry policy can wedge the always-on loop forever.
    continuous: bool = False

    def to_json(self) -> Dict[str, Any]:
        return {
            "schema_version": self.schema_version,
            "name": self.name,
            "pipeline_root": self.pipeline_root,
            "metadata_path": self.metadata_path,
            "enable_cache": self.enable_cache,
            "default_node_timeout_s": self.default_node_timeout_s,
            "default_retry_policy": (
                dict(self.default_retry_policy)
                if self.default_retry_policy else None
            ),
            "spmd_sync": self.spmd_sync,
            "continuous": self.continuous,
            "nodes": [n.to_json() for n in self.nodes],
        }

    def fingerprint(self) -> str:
        """Structural DAG fingerprint, recorded per run and checked by
        ``resume_from``: a resume against a run whose compiled graph differs
        (nodes, wiring, exec-properties, executor code) must be refused —
        adopted outputs would no longer be what the current DAG produces.
        Deliberately EXCLUDES relocatable/operational fields (pipeline_root,
        metadata_path, enable_cache, resource_class, timeouts, lint
        metadata): moving the home or retuning deadlines does not change
        what a node computes.  Nodes are serialized SORTED BY ID, not in
        list order, so reordering component declarations — which permutes
        same-level siblings in the topo order — cannot change the
        fingerprint of a structurally identical DAG.
        """
        structural = [
            {
                "id": n.id,
                "component_type": n.component_type,
                "inputs": {
                    k: [r.to_json() for r in refs]
                    for k, refs in n.inputs.items()
                },
                "outputs": dict(n.outputs),
                "exec_properties": n.exec_properties,
                "executor_version": n.executor_version,
                "upstream": list(n.upstream),
                "external_input_parameters": list(
                    n.external_input_parameters
                ),
                "optional_inputs": list(n.optional_inputs),
                "is_resolver": n.is_resolver,
                "conditions": list(n.conditions),
            }
            for n in sorted(self.nodes, key=lambda n: n.id)
        ]
        # canonical_json, not default=str: an exec property whose repr
        # embeds a memory address must not make the DAG fingerprint (and
        # with it resume_from) nondeterministic across processes.
        payload = canonical_json(
            {"schema": self.schema_version, "name": self.name,
             "nodes": structural},
        )
        return hashlib.sha256(payload.encode()).hexdigest()

    def to_json_str(self, indent: int = 2) -> str:
        return json.dumps(self.to_json(), indent=indent, sort_keys=True, default=str)

    def node(self, node_id: str) -> NodeIR:
        for n in self.nodes:
            if n.id == node_id:
                return n
        raise KeyError(node_id)

    def topo_levels(self) -> List[List[str]]:
        """Topological stage groups: level 0 holds the DAG roots, level k the
        nodes whose deepest upstream sits at level k-1.  Nodes within one
        level share no data dependency, so a scheduler may run a whole level
        concurrently — the local runner's ready-set scheduling realizes the
        same parallelism dynamically; the cluster runner records the groups
        as a workflow annotation.  Ids within a level are SORTED so the
        groups (like the fingerprint) are invariant under component-
        declaration reordering — siblings share no dependency, so order
        inside a group carries no scheduling meaning."""
        level: Dict[str, int] = {}
        for n in self.nodes:  # self.nodes is topologically ordered
            level[n.id] = 1 + max(
                (level[u] for u in n.upstream), default=-1
            )
        groups: List[List[str]] = []
        for n in self.nodes:
            depth = level[n.id]
            while len(groups) <= depth:
                groups.append([])
            groups[depth].append(n.id)
        return [sorted(g) for g in groups]

    def n_roots(self) -> int:
        """Number of DAG roots — the concurrent runner's default pool size."""
        return sum(1 for n in self.nodes if not n.upstream)


class Compiler:
    def compile(self, pipeline: Pipeline) -> PipelineIR:
        nodes: List[NodeIR] = []
        for comp in pipeline.components:
            inputs: Dict[str, List[InputRef]] = {}
            upstream: List[str] = []
            for key, chans in comp.input_channels.items():
                refs = []
                for ch in chans:
                    producer_id = ch.producer.id if ch.producer else ""
                    refs.append(
                        InputRef(
                            producer=producer_id,
                            output_key=ch.output_key,
                            type_name=ch.type_name,
                        )
                    )
                    if producer_id and producer_id not in upstream:
                        upstream.append(producer_id)
                inputs[key] = refs
            conditions = []
            for pred in getattr(comp, "conditions", ()):
                conditions.append(pred.to_json())
                ch = getattr(pred, "channel", None)
                if ch is not None and ch.producer is not None:
                    pid = ch.producer.id
                    if pid not in upstream:
                        upstream.append(pid)
            executor_version = self._executor_version(comp)
            nodes.append(
                NodeIR(
                    id=comp.id,
                    component_type=type(comp).__name__,
                    inputs=inputs,
                    outputs=dict(comp.SPEC.outputs),
                    exec_properties={
                        k: encode_property(v)
                        for k, v in sorted(comp.exec_properties.items())
                    },
                    executor_version=executor_version,
                    upstream=upstream,
                    external_input_parameters=sorted(
                        comp.EXTERNAL_INPUT_PARAMETERS
                    ),
                    optional_inputs=sorted(comp.SPEC.optional_inputs),
                    is_resolver=bool(getattr(comp, "IS_RESOLVER", False)),
                    conditions=conditions,
                    resource_class=getattr(comp, "RESOURCE_CLASS", "host"),
                    execution_timeout_s=float(
                        getattr(comp, "execution_timeout_s", 0.0) or 0.0
                    ),
                    is_sink=bool(getattr(comp, "IS_SINK", False)),
                    lint_suppress=sorted(
                        getattr(comp, "lint_suppress", ()) or ()
                    ),
                    retry_policy=(
                        comp.retry_policy.to_json()
                        if getattr(comp, "retry_policy", None) is not None
                        else None
                    ),
                )
            )
        return PipelineIR(
            name=pipeline.name,
            pipeline_root=pipeline.pipeline_root,
            metadata_path=pipeline.metadata_path,
            enable_cache=pipeline.enable_cache,
            nodes=nodes,
            default_node_timeout_s=float(
                getattr(pipeline, "node_timeout_s", 0.0) or 0.0
            ),
            default_retry_policy=(
                pipeline.retry_policy.to_json()
                if getattr(pipeline, "retry_policy", None) is not None
                else None
            ),
        )

    @staticmethod
    def _executor_version(comp: Component) -> str:
        if comp.EXECUTOR is None:
            return "no-executor"
        base = fingerprint_callable(comp.EXECUTOR)
        salt = comp.CACHE_SALT
        return f"{base}:{salt}" if salt else base
