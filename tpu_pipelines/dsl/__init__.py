"""Pipeline DSL: artifact types, channels, components, pipeline, compiler.

TPU-native equivalent of TFX's L2/L3 layers (SURVEY.md §1): a ``Component`` is
a typed spec (inputs / outputs / exec-properties) plus an executor function; a
``Pipeline`` wires components through ``Channel``s; the compiler lowers the DSL
to a JSON-serializable IR that runners execute.
"""

from tpu_pipelines.dsl.artifact_types import ARTIFACT_TYPES, standard_artifacts  # noqa: F401
from tpu_pipelines.dsl.component import (  # noqa: F401
    Channel,
    Component,
    ComponentSpec,
    ExecutorContext,
    Parameter,
    RuntimeParameter,
)
from tpu_pipelines.dsl.pipeline import Pipeline  # noqa: F401
from tpu_pipelines.dsl.compiler import Compiler, PipelineIR  # noqa: F401
from tpu_pipelines.dsl.cond import (  # noqa: F401
    Cond,
    artifact_property,
    runtime_parameter,
)
