"""tpu-pipelines: a TPU-native ML pipeline framework.

A brand-new framework with the capabilities of the TFX-on-Kubeflow stack the
reference workshop (`pablomendes/kubeflow-tfx-workshop`) exercises — the
canonical ExampleGen → StatisticsGen/SchemaGen/ExampleValidator → Transform →
Trainer → Evaluator → Pusher DAG plus Tuner, InfraValidator and BulkInferrer —
designed idiomatically for JAX/XLA on Cloud TPU rather than ported:

- the compute path is ``jax.jit`` over a ``jax.sharding.Mesh`` (collectives
  ride ICI/DCN instead of NCCL),
- preprocessing analyzers are jitted tree-reductions rather than Beam jobs,
- checkpointing is Orbax, input pipelines are Grain/Arrow,
- the cluster runner emits TPU pod specs instead of GPU TFJobs.

See SURVEY.md at the repo root for the full blueprint (note its §0 evidence
caveat: the reference tree was not available; the capability surface is built
from BASELINE.json and the public TFX architecture).
"""

__version__ = "0.1.0"

from tpu_pipelines.dsl.pipeline import Pipeline  # noqa: F401
