"""Sliced metric computation over model predictions.

Problem types: ``binary_classification`` (logits → loss/accuracy/AUC/
precision/recall), ``multiclass`` (logits → loss/accuracy), ``regression``
(predictions → mse/mae).  Slicing follows TFMA: the overall slice plus one
slice per distinct value of each configured slice column.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

BINARY = "binary_classification"
MULTICLASS = "multiclass"
REGRESSION = "regression"

METRICS_FILE = "metrics.json"


@dataclasses.dataclass
class SliceMetrics:
    slice_key: str              # "" for overall, else "column=value"
    num_examples: int
    metrics: Dict[str, float]


@dataclasses.dataclass
class EvalOutcome:
    problem: str
    slices: List[SliceMetrics]

    def overall(self) -> SliceMetrics:
        for s in self.slices:
            if s.slice_key == "":
                return s
        raise ValueError("no overall slice")

    def to_json(self) -> Dict[str, Any]:
        return {
            "problem": self.problem,
            "slices": [dataclasses.asdict(s) for s in self.slices],
        }

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "EvalOutcome":
        return cls(
            problem=d["problem"],
            slices=[SliceMetrics(**s) for s in d["slices"]],
        )

    def save(self, uri: str) -> str:
        os.makedirs(uri, exist_ok=True)
        path = os.path.join(uri, METRICS_FILE)
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=2, sort_keys=True)
        return path

    @classmethod
    def load(cls, uri: str) -> "EvalOutcome":
        with open(os.path.join(uri, METRICS_FILE)) as f:
            return cls.from_json(json.load(f))


def _binary_metrics(scores: np.ndarray, labels: np.ndarray) -> Dict[str, float]:
    labels = labels.astype(np.float64)
    probs = 1.0 / (1.0 + np.exp(-scores.astype(np.float64)))
    eps = 1e-7
    loss = float(
        -np.mean(labels * np.log(probs + eps) + (1 - labels) * np.log(1 - probs + eps))
    )
    pred = (probs >= 0.5).astype(np.float64)
    tp = float(np.sum((pred == 1) & (labels == 1)))
    fp = float(np.sum((pred == 1) & (labels == 0)))
    fn = float(np.sum((pred == 0) & (labels == 1)))
    precision = tp / (tp + fp) if tp + fp else 0.0
    recall = tp / (tp + fn) if tp + fn else 0.0
    out = {
        "loss": loss,
        "accuracy": float(np.mean(pred == labels)),
        "precision": precision,
        "recall": recall,
        "f1": (
            2 * precision * recall / (precision + recall)
            if precision + recall else 0.0
        ),
        # Calibration at the coarsest grain (TFMA's calibration metric):
        # mean predicted probability over the label base rate — 1.0 is
        # perfectly calibrated in aggregate.
        "calibration": (
            float(probs.mean() / labels.mean()) if labels.mean() else 0.0
        ),
    }
    n_pos, n_neg = int(labels.sum()), int(len(labels) - labels.sum())
    if n_pos and n_neg:
        # Exact AUC via the rank-sum (Mann-Whitney) statistic.
        order = np.argsort(scores, kind="mergesort")
        ranks = np.empty(len(scores), dtype=np.float64)
        ranks[order] = np.arange(1, len(scores) + 1)
        # average ties
        sorted_scores = scores[order]
        i = 0
        while i < len(sorted_scores):
            j = i
            while j + 1 < len(sorted_scores) and sorted_scores[j + 1] == sorted_scores[i]:
                j += 1
            if j > i:
                ranks[order[i : j + 1]] = (i + 1 + j + 1) / 2.0
            i = j + 1
        auc = (ranks[labels == 1].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)
        out["auc"] = float(auc)
        # PR-AUC by average precision (step-wise integral of the PR curve
        # in descending-score order — the TFMA/sklearn AP definition).
        desc = np.argsort(-scores, kind="mergesort")
        tp_cum = np.cumsum(labels[desc])
        prec_at_k = tp_cum / np.arange(1, len(labels) + 1)
        out["prauc"] = float(
            (prec_at_k * labels[desc]).sum() / n_pos
        )
    return out


def _multiclass_metrics(logits: np.ndarray, labels: np.ndarray) -> Dict[str, float]:
    logits = logits.astype(np.float64)
    labels = labels.astype(np.int64)
    z = logits - logits.max(axis=-1, keepdims=True)
    logp = z - np.log(np.exp(z).sum(axis=-1, keepdims=True))
    loss = float(-np.mean(logp[np.arange(len(labels)), labels]))
    pred = logits.argmax(axis=-1)
    out = {"loss": loss, "accuracy": float(np.mean(pred == labels))}
    n_classes = logits.shape[-1]
    if n_classes > 2:
        k = min(5, n_classes - 1)
        topk = np.argsort(-logits, axis=-1)[:, :k]
        out[f"top{k}_accuracy"] = float(
            np.mean((topk == labels[:, None]).any(axis=-1))
        )
        # Macro F1 over classes present in labels or predictions.
        f1s = []
        for c in range(n_classes):
            tp = float(np.sum((pred == c) & (labels == c)))
            fp = float(np.sum((pred == c) & (labels != c)))
            fn = float(np.sum((pred != c) & (labels == c)))
            if tp + fp + fn == 0:
                continue            # class absent everywhere: skip, not 0
            f1s.append(2 * tp / (2 * tp + fp + fn) if tp else 0.0)
        if f1s:
            out["macro_f1"] = float(np.mean(f1s))
    return out


def _regression_metrics(preds: np.ndarray, labels: np.ndarray) -> Dict[str, float]:
    preds = preds.astype(np.float64)
    labels = labels.astype(np.float64)
    err = preds - labels
    out = {
        "mse": float(np.mean(err ** 2)),
        "mae": float(np.mean(np.abs(err))),
    }
    var = float(np.mean((labels - labels.mean()) ** 2))
    if var > 0:
        out["r2"] = float(1.0 - np.mean(err ** 2) / var)
    return out


def compute_metrics(
    problem: str, predictions: np.ndarray, labels: np.ndarray
) -> Dict[str, float]:
    if problem == BINARY:
        return _binary_metrics(predictions, labels)
    if problem == MULTICLASS:
        return _multiclass_metrics(predictions, labels)
    if problem == REGRESSION:
        return _regression_metrics(predictions, labels)
    raise ValueError(f"unknown problem type {problem!r}")


# --------------------------------------------------- streaming accumulators
#
# TFMA-posture aggregation (VERDICT r3 weak#4): metrics accumulate per
# batch, never concatenating the dataset on the host, so eval memory is flat
# in the number of examples.  Everything except the ranking metrics
# (AUC/PR-AUC) is exactly streamable from sums and confusion counts.  For
# the ranking metrics there are two modes:
#   auc_buckets=0 (exact-until-large, the default): each slice keeps a
#     compact copy of its scores (original dtype, typically float32) +
#     labels (int8) — ~5 bytes/example/slice — and the final AUC/PR-AUC
#     are computed by the same rank-sum/AP code as the reference concat
#     path, identically.  If a slice crosses AUC_EXACT_MAX_EXAMPLES rows
#     (VERDICT r4 weak#5: BulkInferrer-scale evals must not drift toward
#     unbounded memory), the retained scores spill into the histogram mode
#     below (DEFAULT_AUC_BUCKETS bins) and the per-example state is freed —
#     exact at dataset sizes where exactness is observable, flat memory at
#     scale, with no call-site opt-in.
#   auc_buckets=N (flat from the first row): scores quantize into an N-bin
#     sigmoid histogram per class; AUC is the tie-averaged rank-sum over
#     buckets (exact at bucket granularity), PR-AUC the step integral over
#     bucket boundaries.  Memory is O(N_buckets), independent of dataset
#     size; with the default 16384 buckets the deviation from exact is
#     < 1e-3 in practice.

# Per-slice row count at which exact mode auto-spills to the histogram
# (~5 MB of retained score/label state); 16384 buckets keeps the post-spill
# deviation < 1e-3 while capping memory at 256 KiB per slice.
AUC_EXACT_MAX_EXAMPLES = 1_000_000
DEFAULT_AUC_BUCKETS = 16384


class _BinaryAcc:
    def __init__(
        self,
        auc_buckets: int = 0,
        auto_bucket_threshold: int = AUC_EXACT_MAX_EXAMPLES,
    ):
        self.buckets = int(auc_buckets)
        # 0 disables the auto-spill (exact regardless of size — callers who
        # truly need reference-identical AUC on huge slices opt in).
        self.auto_threshold = int(auto_bucket_threshold)
        self.spilled = False
        self.n = 0
        self.loss_sum = 0.0
        self.tp = self.fp = self.fn = self.tn = 0.0
        self.prob_sum = 0.0
        self.label_sum = 0.0
        if self.buckets:
            self.hist_pos = np.zeros(self.buckets, np.int64)
            self.hist_neg = np.zeros(self.buckets, np.int64)
        else:
            self._scores: List[np.ndarray] = []
            self._labels: List[np.ndarray] = []

    def _hist_update(self, probs: np.ndarray, labels64: np.ndarray) -> None:
        idx = np.minimum(
            (probs * self.buckets).astype(np.int64), self.buckets - 1
        )
        pos = labels64 == 1
        np.add.at(self.hist_pos, idx[pos], 1)
        np.add.at(self.hist_neg, idx[~pos], 1)

    def _spill_to_hist(self) -> None:
        """Convert retained exact state into the flat histogram and free it
        — the auto-switch that keeps BulkInferrer-scale evals from growing
        ~5 bytes/example/slice forever (VERDICT r4 weak#5)."""
        self.buckets = DEFAULT_AUC_BUCKETS
        self.hist_pos = np.zeros(self.buckets, np.int64)
        self.hist_neg = np.zeros(self.buckets, np.int64)
        scores = np.concatenate(self._scores)
        labels64 = np.concatenate(self._labels).astype(np.float64)
        probs = 1.0 / (1.0 + np.exp(-scores.astype(np.float64)))
        self._hist_update(probs, labels64)
        self._scores = self._labels = None  # type: ignore[assignment]
        self.spilled = True

    def update(self, scores: np.ndarray, labels: np.ndarray) -> None:
        labels64 = labels.astype(np.float64)
        probs = 1.0 / (1.0 + np.exp(-scores.astype(np.float64)))
        eps = 1e-7
        self.loss_sum += float(
            -np.sum(labels64 * np.log(probs + eps)
                    + (1 - labels64) * np.log(1 - probs + eps))
        )
        pred = (probs >= 0.5).astype(np.float64)
        self.tp += float(np.sum((pred == 1) & (labels64 == 1)))
        self.fp += float(np.sum((pred == 1) & (labels64 == 0)))
        self.fn += float(np.sum((pred == 0) & (labels64 == 1)))
        self.tn += float(np.sum((pred == 0) & (labels64 == 0)))
        self.prob_sum += float(probs.sum())
        self.label_sum += float(labels64.sum())
        self.n += len(scores)
        if self.buckets:
            self._hist_update(probs, labels64)
        else:
            # Original dtype preserved: a float32->downcast would collapse
            # sub-float32 score differences into ties and change the exact
            # rank-sum vs the reference concat path on float64 predictions.
            self._scores.append(np.asarray(scores).copy())
            self._labels.append(labels.astype(np.int8, copy=True))
            if self.auto_threshold and self.n > self.auto_threshold:
                self._spill_to_hist()

    def result(self) -> Dict[str, float]:
        n = max(self.n, 1)
        precision = self.tp / (self.tp + self.fp) if self.tp + self.fp else 0.0
        recall = self.tp / (self.tp + self.fn) if self.tp + self.fn else 0.0
        out = {
            "loss": self.loss_sum / n,
            "accuracy": (self.tp + self.tn) / n,
            "precision": precision,
            "recall": recall,
            "f1": (
                2 * precision * recall / (precision + recall)
                if precision + recall else 0.0
            ),
            "calibration": (
                self.prob_sum / self.label_sum if self.label_sum else 0.0
            ),
        }
        if self.buckets:
            out.update(self._ranking_from_hist())
        else:
            out.update(self._ranking_exact())
        return out

    def _ranking_exact(self) -> Dict[str, float]:
        if not self._scores:
            return {}
        scores = np.concatenate(self._scores)
        labels = np.concatenate(self._labels).astype(np.float64)
        full = _binary_metrics(scores, labels)
        return {k: full[k] for k in ("auc", "prauc") if k in full}

    def _ranking_from_hist(self) -> Dict[str, float]:
        n_pos = int(self.hist_pos.sum())
        n_neg = int(self.hist_neg.sum())
        if not (n_pos and n_neg):
            return {}
        counts = self.hist_pos + self.hist_neg
        # Tie-averaged rank-sum over buckets (ascending): entries in bucket
        # i share the average rank of the bucket's span.
        below = np.concatenate([[0], np.cumsum(counts)[:-1]])
        avg_rank = below + (counts + 1) / 2.0
        rank_sum_pos = float((self.hist_pos * avg_rank).sum())
        auc = (rank_sum_pos - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)
        # PR step integral over bucket boundaries, descending score.
        tp_cum = np.cumsum(self.hist_pos[::-1])
        pred_cum = np.cumsum(counts[::-1])
        with np.errstate(invalid="ignore", divide="ignore"):
            prec = np.where(pred_cum > 0, tp_cum / pred_cum, 0.0)
        recall_delta = np.diff(np.concatenate([[0], tp_cum])) / n_pos
        return {
            "auc": float(auc),
            "prauc": float((prec * recall_delta).sum()),
        }


class _MulticlassAcc:
    def __init__(self, **_):
        self.n = 0
        self.loss_sum = 0.0
        self.correct = 0
        self.topk_correct = 0
        self.k = 0
        self.n_classes = 0
        self.tp = self.fp = self.fn = None

    def update(self, logits: np.ndarray, labels: np.ndarray) -> None:
        logits = logits.astype(np.float64)
        labels = labels.astype(np.int64)
        z = logits - logits.max(axis=-1, keepdims=True)
        logp = z - np.log(np.exp(z).sum(axis=-1, keepdims=True))
        self.loss_sum += float(-np.sum(logp[np.arange(len(labels)), labels]))
        pred = logits.argmax(axis=-1)
        self.correct += int(np.sum(pred == labels))
        self.n += len(labels)
        c = logits.shape[-1]
        if self.tp is None:
            self.n_classes = c
            self.k = min(5, c - 1)
            self.tp = np.zeros(c, np.int64)
            self.fp = np.zeros(c, np.int64)
            self.fn = np.zeros(c, np.int64)
        elif c != self.n_classes:
            raise ValueError(
                f"logit width changed across batches: {c} vs {self.n_classes}"
            )
        if c > 2:
            topk = np.argsort(-logits, axis=-1)[:, : self.k]
            self.topk_correct += int(
                np.sum((topk == labels[:, None]).any(axis=-1))
            )
        np.add.at(self.tp, labels[pred == labels], 1)
        np.add.at(self.fp, pred[pred != labels], 1)
        np.add.at(self.fn, labels[pred != labels], 1)

    def result(self) -> Dict[str, float]:
        n = max(self.n, 1)
        out = {"loss": self.loss_sum / n, "accuracy": self.correct / n}
        if self.n_classes > 2:
            out[f"top{self.k}_accuracy"] = self.topk_correct / n
            f1s = []
            for c in range(self.n_classes):
                tp, fp, fn = float(self.tp[c]), float(self.fp[c]), float(self.fn[c])
                if tp + fp + fn == 0:
                    continue            # class absent everywhere: skip, not 0
                f1s.append(2 * tp / (2 * tp + fp + fn) if tp else 0.0)
            if f1s:
                out["macro_f1"] = float(np.mean(f1s))
        return out


class _RegressionAcc:
    def __init__(self, **_):
        self.n = 0
        self.err2_sum = 0.0
        self.abs_sum = 0.0
        self.label_sum = 0.0
        self.label2_sum = 0.0

    def update(self, preds: np.ndarray, labels: np.ndarray) -> None:
        preds = preds.astype(np.float64)
        labels = labels.astype(np.float64)
        err = preds - labels
        self.err2_sum += float(np.sum(err ** 2))
        self.abs_sum += float(np.sum(np.abs(err)))
        self.label_sum += float(labels.sum())
        self.label2_sum += float(np.sum(labels ** 2))
        self.n += len(labels)

    def result(self) -> Dict[str, float]:
        n = max(self.n, 1)
        mse = self.err2_sum / n
        out = {"mse": mse, "mae": self.abs_sum / n}
        mean = self.label_sum / n
        var = self.label2_sum / n - mean ** 2
        if var > 0:
            out["r2"] = float(1.0 - mse / var)
        return out


_ACCUMULATORS = {
    BINARY: _BinaryAcc,
    MULTICLASS: _MulticlassAcc,
    REGRESSION: _RegressionAcc,
}


def make_accumulator(
    problem: str,
    auc_buckets: int = 0,
    auto_bucket_threshold: int = AUC_EXACT_MAX_EXAMPLES,
):
    if problem not in _ACCUMULATORS:
        raise ValueError(f"unknown problem type {problem!r}")
    return _ACCUMULATORS[problem](
        auc_buckets=auc_buckets, auto_bucket_threshold=auto_bucket_threshold
    )


from tpu_pipelines.utils.transient import (  # noqa: E402  (section marker)
    is_transient_error as _is_transient_error,
)


def _predict_resilient(
    predict_fn: Callable[[Dict[str, np.ndarray]], Any],
    batch: Dict[str, np.ndarray],
    depth: int = 0,
) -> np.ndarray:
    """predict_fn with transient-failure recovery (SURVEY.md §5 failure
    recovery): a transient platform error retries once as-is, then splits
    the batch in half (recursing, min size 1) so an oversized compile or a
    flaky remote compile degrades to smaller programs instead of killing
    the whole Evaluator execution."""
    try:
        return np.asarray(predict_fn(batch))
    except Exception as e:  # noqa: BLE001 — transient-only, re-raised below
        msg = str(e)
        if not _is_transient_error(msg):
            raise
        try:
            return np.asarray(predict_fn(batch))     # retry once as-is
        except Exception as e2:  # noqa: BLE001
            if not _is_transient_error(str(e2)):
                raise
            rows = len(next(iter(batch.values())))
            if depth >= 4 or rows <= 1:
                raise
            half = rows // 2
            lo = {k: v[:half] for k, v in batch.items()}
            hi = {k: v[half:] for k, v in batch.items()}
            return np.concatenate([
                _predict_resilient(predict_fn, lo, depth + 1),
                _predict_resilient(predict_fn, hi, depth + 1),
            ])


def evaluate_model(
    predict_fn: Callable[[Dict[str, np.ndarray]], Any],
    batches: Iterable[Dict[str, np.ndarray]],
    label_key: str,
    problem: str = BINARY,
    slice_columns: Tuple[str, ...] = (),
    auc_buckets: int = 0,
    auto_bucket_threshold: int = AUC_EXACT_MAX_EXAMPLES,
) -> EvalOutcome:
    """Run jitted predictions over batches, aggregating sliced metrics
    per batch (streaming — see the accumulator note above).

    ``auc_buckets=0`` reproduces the reference concat-path AUC/PR-AUC
    exactly while a slice stays under ``auto_bucket_threshold`` rows
    (default 1M), then auto-spills to the flat histogram (deviation
    < 1e-3); pass ``auto_bucket_threshold=0`` to force exact AUC at any
    size (memory grows ~5 bytes/example/slice — your call).
    ``auc_buckets=N`` forces the O(N)-memory histogram from the first row.
    """
    def new_acc():
        return make_accumulator(
            problem, auc_buckets, auto_bucket_threshold=auto_bucket_threshold
        )

    overall = new_acc()
    by_slice: Dict[str, Any] = {}
    n_batches = 0
    for batch in batches:
        if label_key not in batch:
            raise KeyError(
                f"label column {label_key!r} missing from eval batch "
                f"(have {sorted(batch)})"
            )
        for c in slice_columns:
            if c not in batch:
                raise KeyError(f"slice column {c!r} missing from eval batch")
        preds = _predict_resilient(predict_fn, batch)
        labels = np.asarray(batch[label_key])
        overall.update(preds, labels)
        n_batches += 1
        for c in slice_columns:
            vals = np.asarray(batch[c])
            for v in np.unique(vals):
                key = f"{c}={v}"
                acc = by_slice.get(key)
                if acc is None:
                    acc = by_slice[key] = new_acc()
                mask = vals == v
                acc.update(preds[mask], labels[mask])
    if not n_batches:
        raise ValueError("evaluate_model received no batches")

    slices = [SliceMetrics("", overall.n, overall.result())]
    for key in sorted(by_slice):
        acc = by_slice[key]
        slices.append(SliceMetrics(key, acc.n, acc.result()))
    return EvalOutcome(problem=problem, slices=slices)


def check_thresholds(
    current: Dict[str, float],
    value_thresholds: Dict[str, Dict[str, float]],
    baseline: Optional[Dict[str, float]] = None,
    change_thresholds: Optional[Dict[str, Dict[str, float]]] = None,
    require_baseline: bool = True,
) -> Tuple[bool, List[str]]:
    """Blessing gate.  Returns (blessed, reasons-for-failure).

    ``require_baseline=False`` is the continuous-training bootstrap (TFX
    LatestBlessedModelStrategy semantics): change thresholds are SKIPPED when
    no baseline exists — the first run's model gates on value thresholds
    alone and, once blessed, becomes the baseline for every later run.
    """
    failures: List[str] = []
    for metric, bounds in (value_thresholds or {}).items():
        if metric not in current:
            failures.append(f"metric {metric!r} not computed")
            continue
        v = current[metric]
        if "lower_bound" in bounds and v < bounds["lower_bound"]:
            failures.append(
                f"{metric}={v:.6f} < lower_bound {bounds['lower_bound']}"
            )
        if "upper_bound" in bounds and v > bounds["upper_bound"]:
            failures.append(
                f"{metric}={v:.6f} > upper_bound {bounds['upper_bound']}"
            )
    for metric, bounds in (change_thresholds or {}).items():
        if baseline is None:
            if require_baseline:
                failures.append(
                    f"change threshold on {metric!r} but no baseline model"
                )
            continue
        if metric not in current or metric not in baseline:
            failures.append(f"metric {metric!r} missing for comparison")
            continue
        # higher_is_better defaults True; loss-like metrics set it False.
        hib = bounds.get("higher_is_better", True)
        delta = (
            current[metric] - baseline[metric]
            if hib else baseline[metric] - current[metric]
        )
        min_impr = bounds.get("min_improvement", 0.0)
        if delta < min_impr:
            failures.append(
                f"{metric} improvement {delta:.6f} < required {min_impr}"
                f" (current {current[metric]:.6f}, baseline {baseline[metric]:.6f})"
            )
    return (not failures, failures)
