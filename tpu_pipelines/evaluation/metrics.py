"""Sliced metric computation over model predictions.

Problem types: ``binary_classification`` (logits → loss/accuracy/AUC/
precision/recall), ``multiclass`` (logits → loss/accuracy), ``regression``
(predictions → mse/mae).  Slicing follows TFMA: the overall slice plus one
slice per distinct value of each configured slice column.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

BINARY = "binary_classification"
MULTICLASS = "multiclass"
REGRESSION = "regression"

METRICS_FILE = "metrics.json"


@dataclasses.dataclass
class SliceMetrics:
    slice_key: str              # "" for overall, else "column=value"
    num_examples: int
    metrics: Dict[str, float]


@dataclasses.dataclass
class EvalOutcome:
    problem: str
    slices: List[SliceMetrics]

    def overall(self) -> SliceMetrics:
        for s in self.slices:
            if s.slice_key == "":
                return s
        raise ValueError("no overall slice")

    def to_json(self) -> Dict[str, Any]:
        return {
            "problem": self.problem,
            "slices": [dataclasses.asdict(s) for s in self.slices],
        }

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "EvalOutcome":
        return cls(
            problem=d["problem"],
            slices=[SliceMetrics(**s) for s in d["slices"]],
        )

    def save(self, uri: str) -> str:
        os.makedirs(uri, exist_ok=True)
        path = os.path.join(uri, METRICS_FILE)
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=2, sort_keys=True)
        return path

    @classmethod
    def load(cls, uri: str) -> "EvalOutcome":
        with open(os.path.join(uri, METRICS_FILE)) as f:
            return cls.from_json(json.load(f))


def _binary_metrics(scores: np.ndarray, labels: np.ndarray) -> Dict[str, float]:
    labels = labels.astype(np.float64)
    probs = 1.0 / (1.0 + np.exp(-scores.astype(np.float64)))
    eps = 1e-7
    loss = float(
        -np.mean(labels * np.log(probs + eps) + (1 - labels) * np.log(1 - probs + eps))
    )
    pred = (probs >= 0.5).astype(np.float64)
    tp = float(np.sum((pred == 1) & (labels == 1)))
    fp = float(np.sum((pred == 1) & (labels == 0)))
    fn = float(np.sum((pred == 0) & (labels == 1)))
    precision = tp / (tp + fp) if tp + fp else 0.0
    recall = tp / (tp + fn) if tp + fn else 0.0
    out = {
        "loss": loss,
        "accuracy": float(np.mean(pred == labels)),
        "precision": precision,
        "recall": recall,
        "f1": (
            2 * precision * recall / (precision + recall)
            if precision + recall else 0.0
        ),
        # Calibration at the coarsest grain (TFMA's calibration metric):
        # mean predicted probability over the label base rate — 1.0 is
        # perfectly calibrated in aggregate.
        "calibration": (
            float(probs.mean() / labels.mean()) if labels.mean() else 0.0
        ),
    }
    n_pos, n_neg = int(labels.sum()), int(len(labels) - labels.sum())
    if n_pos and n_neg:
        # Exact AUC via the rank-sum (Mann-Whitney) statistic.
        order = np.argsort(scores, kind="mergesort")
        ranks = np.empty(len(scores), dtype=np.float64)
        ranks[order] = np.arange(1, len(scores) + 1)
        # average ties
        sorted_scores = scores[order]
        i = 0
        while i < len(sorted_scores):
            j = i
            while j + 1 < len(sorted_scores) and sorted_scores[j + 1] == sorted_scores[i]:
                j += 1
            if j > i:
                ranks[order[i : j + 1]] = (i + 1 + j + 1) / 2.0
            i = j + 1
        auc = (ranks[labels == 1].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)
        out["auc"] = float(auc)
        # PR-AUC by average precision (step-wise integral of the PR curve
        # in descending-score order — the TFMA/sklearn AP definition).
        desc = np.argsort(-scores, kind="mergesort")
        tp_cum = np.cumsum(labels[desc])
        prec_at_k = tp_cum / np.arange(1, len(labels) + 1)
        out["prauc"] = float(
            (prec_at_k * labels[desc]).sum() / n_pos
        )
    return out


def _multiclass_metrics(logits: np.ndarray, labels: np.ndarray) -> Dict[str, float]:
    labels = labels.astype(np.int64)
    z = logits - logits.max(axis=-1, keepdims=True)
    logp = z - np.log(np.exp(z).sum(axis=-1, keepdims=True))
    loss = float(-np.mean(logp[np.arange(len(labels)), labels]))
    pred = logits.argmax(axis=-1)
    out = {"loss": loss, "accuracy": float(np.mean(pred == labels))}
    n_classes = logits.shape[-1]
    if n_classes > 2:
        k = min(5, n_classes - 1)
        topk = np.argsort(-logits, axis=-1)[:, :k]
        out[f"top{k}_accuracy"] = float(
            np.mean((topk == labels[:, None]).any(axis=-1))
        )
        # Macro F1 over classes present in labels or predictions.
        f1s = []
        for c in range(n_classes):
            tp = float(np.sum((pred == c) & (labels == c)))
            fp = float(np.sum((pred == c) & (labels != c)))
            fn = float(np.sum((pred != c) & (labels == c)))
            if tp + fp + fn == 0:
                continue            # class absent everywhere: skip, not 0
            f1s.append(2 * tp / (2 * tp + fp + fn) if tp else 0.0)
        if f1s:
            out["macro_f1"] = float(np.mean(f1s))
    return out


def _regression_metrics(preds: np.ndarray, labels: np.ndarray) -> Dict[str, float]:
    preds = preds.astype(np.float64)
    labels = labels.astype(np.float64)
    err = preds - labels
    out = {
        "mse": float(np.mean(err ** 2)),
        "mae": float(np.mean(np.abs(err))),
    }
    var = float(np.mean((labels - labels.mean()) ** 2))
    if var > 0:
        out["r2"] = float(1.0 - np.mean(err ** 2) / var)
    return out


def compute_metrics(
    problem: str, predictions: np.ndarray, labels: np.ndarray
) -> Dict[str, float]:
    if problem == BINARY:
        return _binary_metrics(predictions, labels)
    if problem == MULTICLASS:
        return _multiclass_metrics(predictions, labels)
    if problem == REGRESSION:
        return _regression_metrics(predictions, labels)
    raise ValueError(f"unknown problem type {problem!r}")


def evaluate_model(
    predict_fn: Callable[[Dict[str, np.ndarray]], Any],
    batches: Iterable[Dict[str, np.ndarray]],
    label_key: str,
    problem: str = BINARY,
    slice_columns: Tuple[str, ...] = (),
) -> EvalOutcome:
    """Run jitted predictions over batches, aggregate sliced metrics exactly."""
    all_preds: List[np.ndarray] = []
    all_labels: List[np.ndarray] = []
    slice_vals: Dict[str, List[np.ndarray]] = {c: [] for c in slice_columns}
    for batch in batches:
        if label_key not in batch:
            raise KeyError(
                f"label column {label_key!r} missing from eval batch "
                f"(have {sorted(batch)})"
            )
        preds = np.asarray(predict_fn(batch))
        all_preds.append(preds)
        all_labels.append(np.asarray(batch[label_key]))
        for c in slice_columns:
            if c not in batch:
                raise KeyError(f"slice column {c!r} missing from eval batch")
            slice_vals[c].append(np.asarray(batch[c]))
    if not all_preds:
        raise ValueError("evaluate_model received no batches")
    preds = np.concatenate(all_preds)
    labels = np.concatenate(all_labels)

    slices = [
        SliceMetrics("", len(labels), compute_metrics(problem, preds, labels))
    ]
    for c in slice_columns:
        vals = np.concatenate(slice_vals[c])
        for v in np.unique(vals):
            mask = vals == v
            if not mask.any():
                continue
            slices.append(
                SliceMetrics(
                    f"{c}={v}",
                    int(mask.sum()),
                    compute_metrics(problem, preds[mask], labels[mask]),
                )
            )
    return EvalOutcome(problem=problem, slices=slices)


def check_thresholds(
    current: Dict[str, float],
    value_thresholds: Dict[str, Dict[str, float]],
    baseline: Optional[Dict[str, float]] = None,
    change_thresholds: Optional[Dict[str, Dict[str, float]]] = None,
) -> Tuple[bool, List[str]]:
    """Blessing gate.  Returns (blessed, reasons-for-failure)."""
    failures: List[str] = []
    for metric, bounds in (value_thresholds or {}).items():
        if metric not in current:
            failures.append(f"metric {metric!r} not computed")
            continue
        v = current[metric]
        if "lower_bound" in bounds and v < bounds["lower_bound"]:
            failures.append(
                f"{metric}={v:.6f} < lower_bound {bounds['lower_bound']}"
            )
        if "upper_bound" in bounds and v > bounds["upper_bound"]:
            failures.append(
                f"{metric}={v:.6f} > upper_bound {bounds['upper_bound']}"
            )
    for metric, bounds in (change_thresholds or {}).items():
        if baseline is None:
            failures.append(
                f"change threshold on {metric!r} but no baseline model"
            )
            continue
        if metric not in current or metric not in baseline:
            failures.append(f"metric {metric!r} missing for comparison")
            continue
        # higher_is_better defaults True; loss-like metrics set it False.
        hib = bounds.get("higher_is_better", True)
        delta = (
            current[metric] - baseline[metric]
            if hib else baseline[metric] - current[metric]
        )
        min_impr = bounds.get("min_improvement", 0.0)
        if delta < min_impr:
            failures.append(
                f"{metric} improvement {delta:.6f} < required {min_impr}"
                f" (current {current[metric]:.6f}, baseline {baseline[metric]:.6f})"
            )
    return (not failures, failures)
