"""Evaluation: jitted batch eval, sliced metrics, model comparison.

TPU-native equivalent of TFMA (SURVEY.md §2a Evaluator): predictions come
from the exported model's jitted forward pass; metric aggregation is exact
numpy over collected (prediction, label) arrays, grouped by slice.
"""

from tpu_pipelines.evaluation.metrics import (  # noqa: F401
    EvalOutcome,
    SliceMetrics,
    compute_metrics,
    evaluate_model,
)
