"""Model zoo: flax models for the reference workloads (BASELINE configs).

Present:
  - taxi: Chicago-Taxi wide-and-deep DNN (config 0)
  - mnist: Keras-CNN-equivalent convnet (config 1)
  - resnet: ResNet-18/34/50/101/152, NHWC bfloat16 (config 2)
  - bert: BERT-base encoder + classifier/MLM heads (config 3)
  - t5: T5-small encoder-decoder seq2seq (config 4)
  - transformer: shared sharded blocks (TP over 'model', ring-attention SP
    over 'seq') used by bert/t5

Tabular models (taxi) take a dict of (transformed) feature arrays; array-input
models (mnist, resnet) define an ``apply_fn`` hook in their trainer module file
so the serving/export path can adapt the feature dict (see trainer/export.py).
"""
