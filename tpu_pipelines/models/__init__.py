"""Model zoo: flax models for the reference workloads (BASELINE configs).

Present:
  - taxi: Chicago-Taxi wide-and-deep DNN (config 0)

Planned (BASELINE configs 1-4): mnist convnet, ResNet-50, BERT-base, T5-small.

All models take a dict of (transformed) feature arrays, so the same batch
flows from the input pipeline or the TransformGraph device stage.
"""
