"""BERT-base encoder + heads (BASELINE config 3: BERT-base fine-tune).

The reference fine-tunes BERT-base through TFX Transform (tokenization) +
Trainer (SURVEY.md §0 configs[3]).  Here: the encoder is built from the
sharded transformer blocks (models/transformer.py) — post-LN as in the
original BERT — with a classification head for fine-tuning and an MLM head
for pretraining-style objectives.  Tokenization stays host-side in the
Transform component (SURVEY.md §7 hard part 5); the model consumes
``input_ids`` / ``token_type_ids`` / an attention mask.

Parallelism: batch over mesh ``data``; optional TP over ``model`` via
``bert_partition_rules``; optional ring-attention SP over ``seq`` for long
sequences (attn_impl="ring").
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh, PartitionSpec as P

from tpu_pipelines.models.transformer import (
    TRANSFORMER_PARTITION_RULES,
    TransformerBlock,
)


class BertEncoder(nn.Module):
    vocab_size: int = 30522
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_len: int = 512
    type_vocab_size: int = 2
    dropout_rate: float = 0.1
    dtype: Any = jnp.bfloat16
    attn_impl: str = "dense"
    mesh: Optional[Mesh] = None
    # > 0 makes every other layer (odd i — the Switch convention) a
    # mixture-of-experts MLP with this many experts, expert-parallel over
    # the mesh ``expert`` axis.
    moe_experts: int = 0

    @nn.compact
    def __call__(
        self,
        input_ids,
        *,
        token_type_ids=None,
        attention_mask=None,
        deterministic: bool = True,
    ):
        ids = jnp.asarray(input_ids, jnp.int32)
        b, l = ids.shape
        x = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype,
                     name="embed")(ids)
        x = x + nn.Embed(self.max_len, self.d_model, dtype=self.dtype,
                         name="pos_embed")(jnp.arange(l)[None, :])
        types = (jnp.zeros_like(ids) if token_type_ids is None
                 else jnp.asarray(token_type_ids, jnp.int32))
        x = x + nn.Embed(self.type_vocab_size, self.d_model, dtype=self.dtype,
                         name="type_embed")(types)
        x = nn.LayerNorm(dtype=self.dtype, name="embed_norm")(x)
        if self.dropout_rate:
            x = nn.Dropout(self.dropout_rate)(x, deterministic=deterministic)
        for i in range(self.n_layers):
            x = TransformerBlock(
                n_heads=self.n_heads,
                head_dim=self.d_model // self.n_heads,
                d_ff=self.d_ff,
                dropout_rate=self.dropout_rate,
                dtype=self.dtype,
                attn_impl=self.attn_impl,
                mesh=self.mesh,
                causal=False,
                prenorm=False,          # original BERT is post-LN
                moe_experts=self.moe_experts if i % 2 == 1 else 0,
                name=f"layer_{i}",
            )(x, kv_mask=attention_mask, deterministic=deterministic)
        return x


class BertClassifier(nn.Module):
    """[CLS]-pooled sequence classification (the fine-tune workload)."""

    encoder: BertEncoder
    num_classes: int = 2
    dropout_rate: float = 0.1

    @nn.compact
    def __call__(self, batch: Dict[str, Any], *, deterministic: bool = True):
        x = self.encoder(
            batch["input_ids"],
            token_type_ids=batch.get("token_type_ids"),
            attention_mask=batch.get("attention_mask"),
            deterministic=deterministic,
        )
        pooled = nn.tanh(
            nn.Dense(x.shape[-1], dtype=jnp.float32, name="pooler")(
                x[:, 0].astype(jnp.float32)
            )
        )
        if self.dropout_rate:
            pooled = nn.Dropout(self.dropout_rate)(
                pooled, deterministic=deterministic
            )
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(pooled)


class BertMLMHead(nn.Module):
    """Masked-LM logits over the vocab (pretraining-style objective)."""

    encoder: BertEncoder

    @nn.compact
    def __call__(self, batch: Dict[str, Any], *, deterministic: bool = True):
        x = self.encoder(
            batch["input_ids"],
            token_type_ids=batch.get("token_type_ids"),
            attention_mask=batch.get("attention_mask"),
            deterministic=deterministic,
        )
        x = nn.gelu(nn.Dense(x.shape[-1], dtype=x.dtype, name="mlm_dense")(x))
        x = nn.LayerNorm(dtype=x.dtype, name="mlm_norm")(x)
        return nn.Dense(
            self.encoder.vocab_size, dtype=jnp.float32, name="mlm_head"
        )(x)


DEFAULT_HPARAMS = {
    # bert-base-uncased geometry, vocab padded 30522 → 30528 (divisible by
    # 64) so the TP embedding/MLM-head rules shard cleanly on any mesh —
    # the standard Megatron-style vocab padding.
    "vocab_size": 30528,
    "d_model": 768,
    "n_layers": 12,
    "n_heads": 12,
    "d_ff": 3072,
    "max_len": 512,
    "type_vocab_size": 2,
    "dropout_rate": 0.1,
    "num_classes": 2,
    "attn_impl": "auto",
    "moe_experts": 0,
    "learning_rate": 3e-5,
    "batch_size": 64,
    "head": "classifier",     # or "mlm"
}


def build_bert_model(hparams: Dict, mesh: Optional[Mesh] = None):
    hp = {**DEFAULT_HPARAMS, **(hparams or {})}
    encoder = BertEncoder(
        vocab_size=int(hp["vocab_size"]),
        d_model=int(hp["d_model"]),
        n_layers=int(hp["n_layers"]),
        n_heads=int(hp["n_heads"]),
        d_ff=int(hp["d_ff"]),
        max_len=int(hp["max_len"]),
        type_vocab_size=int(hp["type_vocab_size"]),
        dropout_rate=float(hp["dropout_rate"]),
        attn_impl=str(hp["attn_impl"]),
        mesh=mesh,
        moe_experts=int(hp.get("moe_experts", 0)),
    )
    if hp["head"] == "mlm":
        return BertMLMHead(encoder=encoder)
    return BertClassifier(
        encoder=encoder,
        num_classes=int(hp["num_classes"]),
        dropout_rate=float(hp["dropout_rate"]),
    )


def bert_partition_rules():
    """TP rules for the train loop's ``param_partition`` (first match wins)."""
    return list(TRANSFORMER_PARTITION_RULES) + [
        (r"mlm_head/kernel", P(None, "model")),
    ]
