"""Wide-and-deep model for the Chicago-Taxi workload (BASELINE config 0).

The reference's taxi template trains a wide-and-deep Keras DNN; this is the
same architecture in flax: embeddings + MLP for the deep path, sparse/one-hot
linear for the wide path, summed into a single logit.
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax.numpy as jnp
from flax import linen as nn


class WideAndDeep(nn.Module):
    """Dict-of-features in, (batch,) logit out."""

    numeric_features: Sequence[str]
    # name -> (cardinality, embed_dim); features must be int id columns.
    categorical_features: Dict[str, Tuple[int, int]]
    # names of already-encoded vector features (one-hot / multi-hot).
    wide_features: Sequence[str] = ()
    hidden_dims: Sequence[int] = (64, 32)

    @nn.compact
    def __call__(self, batch: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        deep = [
            jnp.stack(
                [jnp.asarray(batch[f], jnp.float32) for f in self.numeric_features],
                axis=-1,
            )
        ]
        for name, (card, dim) in sorted(self.categorical_features.items()):
            ids = jnp.asarray(batch[name], jnp.int32)
            deep.append(nn.Embed(card, dim, name=f"embed_{name}")(ids))
        x = jnp.concatenate(deep, axis=-1)
        for i, h in enumerate(self.hidden_dims):
            x = nn.relu(nn.Dense(h, name=f"dense_{i}")(x))
        deep_logit = nn.Dense(1, name="deep_head")(x)[..., 0]

        if self.wide_features:
            # .shape[0] (not len()) keeps the batch dim symbolic-friendly
            # for jax2tf polymorphic SavedModel export.
            wide = jnp.concatenate(
                [jnp.asarray(batch[f], jnp.float32)
                 .reshape(deep_logit.shape[0], -1)
                 for f in self.wide_features],
                axis=-1,
            )
            wide_logit = nn.Dense(1, name="wide_head")(wide)[..., 0]
        else:
            wide_logit = 0.0
        return deep_logit + wide_logit


DEFAULT_HPARAMS = {
    "numeric_features": ["miles_z", "fare_01", "log_fare_z", "tip_ratio"],
    "categorical_features": {
        "company_id": [8, 4],
        "hour_bucket": [8, 2],
    },
    "wide_features": ["payment_onehot", "is_cash"],
    "hidden_dims": [64, 32],
    "label": "label_big_tip",
    "learning_rate": 1e-3,
    "batch_size": 64,
}


def build_taxi_model(hparams: Dict) -> WideAndDeep:
    hp = {**DEFAULT_HPARAMS, **(hparams or {})}
    return WideAndDeep(
        numeric_features=tuple(hp["numeric_features"]),
        categorical_features={
            k: tuple(v) for k, v in hp["categorical_features"].items()
        },
        wide_features=tuple(hp["wide_features"]),
        hidden_dims=tuple(hp["hidden_dims"]),
    )
