"""Sharded transformer building blocks (backbone for BERT/T5 configs).

TPU-first design: every matmul is a large batched einsum that XLA tiles onto
the MXU in bfloat16; parallelism is declared, not coded — heads/FFN shard
over the mesh ``model`` axis (TP) via the partition rules below, batch over
``data`` (DP), and long sequences over ``seq`` via ring attention
(parallel/ring_attention.py).  The modules themselves contain no collectives;
XLA inserts them from the shardings, except the explicit ``ppermute`` ring
inside ring attention.

The reference's BERT/T5 workloads (SURVEY.md §0 configs 3-4) run through
these blocks; its only parallelism was data-parallel NCCL allreduce
(SURVEY.md §2c) — TP and SP here are TPU-native additions, kept optional
(mesh axes default to size 1).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax.sharding import Mesh, PartitionSpec as P

from tpu_pipelines.parallel.ring_attention import dense_attention, ring_attention

Dtype = Any

# "auto" attn_impl switchover is MEASURED where a measurement exists and
# memory-feasibility-bounded always (choose_attn_impl): the autotune table
# (ops/autotune.py) stores a per-device flash-vs-dense crossover sequence
# length recorded by the bench flash_probe sweep — dense below it, flash
# at/above it.  With no recorded crossover the rule degrades to the
# feasibility estimate alone, which every probe so far justified: on v5e
# (BENCH_R4/R5 flash_probe, BERT-base geometry b=8 h=12 d=64) dense is
# faster than the untuned Pallas kernel across the whole band where its
# O(L^2) score temporaries fit in HBM — ~30% faster at L=128, ~25% at
# L=2048 — because XLA fuses the fwd score/softmax chain well.  Flash's
# unconditional win is FEASIBILITY: at L=8192 the dense fwd+bwd wants
# 38.7 GB of temporaries (16x the 2.42 GB measured at 2048 — it scales
# with L^2) and cannot compile on a 16 GB chip, while flash runs in
# O(block^2) VMEM scratch.  The feasibility estimate (the OOM guard):
#
#   temp ~= DENSE_ATTN_TEMP_FACTOR * B * H * Lq * Lkv * itemsize
#
# FACTOR=3 calibrates the estimate to XLA's measured allocation (805 MB of
# raw [B,H,L,L] bf16 scores at the probe geometry vs 2.42 GB measured:
# score + softmax-prob + dscore buffers are live at the backward peak).
DENSE_ATTN_TEMP_FACTOR = 3.0
# Dense is chosen while its temp estimate stays under this fraction of
# device memory — headroom for params, optimizer state and activations.
# Override per-process with TPP_DENSE_ATTN_HBM_FRACTION.
DENSE_ATTN_HBM_FRACTION = 0.4
# Long-context gate for "auto" on a mesh whose 'seq' axis is populated:
# self-attention at/above this sequence length rides ring attention
# (sequence-parallel ppermute ring, parallel/ring_attention.py) inside
# the windowed train loop.  Override per-process with TPP_RING_MIN_SEQ.
RING_MIN_SEQ = 2048


def _device_memory_bytes() -> int:
    """Per-device accelerator memory, for the auto attention choice.

    TPP_HBM_BYTES overrides; otherwise the backend's own bytes_limit;
    16 GiB (v5e) as the fallback when the backend reports nothing (CPU
    tests) — the decision only needs the right order of magnitude."""
    env = os.environ.get("TPP_HBM_BYTES")
    if env:
        return int(env)
    try:
        stats = jax.devices()[0].memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
    except Exception:
        pass
    return 16 * 1024**3


def dense_attn_expected_temp_bytes(
    batch: int,
    heads: int,
    seq_q: int,
    seq_kv: int,
    itemsize: int = 2,
    mesh: Optional[Mesh] = None,
) -> int:
    """Calibrated estimate of dense attention's O(L^2) XLA temporaries
    (per shard when a mesh divides batch over ``data`` / heads over
    ``model``).  Exposed so callers that must *skip* a dense compile
    cleanly (the bench OOM precheck) can record the number they acted on
    instead of depending on a backend error string."""
    if mesh is not None:
        shape = dict(mesh.shape)
        batch = -(-batch // max(1, shape.get("data", 1)))
        heads = -(-heads // max(1, shape.get("model", 1)))
    return int(
        DENSE_ATTN_TEMP_FACTOR * batch * heads * seq_q * seq_kv * itemsize
    )


def dense_attn_fits(
    batch: int,
    heads: int,
    seq_q: int,
    seq_kv: int,
    itemsize: int = 2,
    mesh: Optional[Mesh] = None,
) -> bool:
    """True when dense attention's O(L^2) temporaries fit comfortably —
    the OOM guard inside the "auto" attn_impl rule (see module comment
    for the calibration; ``choose_attn_impl`` layers the measured
    crossover on top).

    The estimate is PER SHARD: on a mesh, the batch dim shards over the
    ``data`` axis and heads over ``model`` (TP), so each device only
    materializes its slice of the [B, H, Lq, Lkv] score tensor.  Without
    the division, "auto" flipped to flash on multi-chip geometries where
    dense fits per-device and is ~25% faster (round-5 advisor finding)."""
    frac = float(
        os.environ.get("TPP_DENSE_ATTN_HBM_FRACTION", DENSE_ATTN_HBM_FRACTION)
    )
    temp = dense_attn_expected_temp_bytes(
        batch, heads, seq_q, seq_kv, itemsize, mesh=mesh
    )
    return temp <= frac * _device_memory_bytes()


def choose_attn_impl(
    batch: int,
    heads: int,
    seq_q: int,
    seq_kv: int,
    itemsize: int = 2,
    mesh: Optional[Mesh] = None,
) -> str:
    """The measured "auto" rule: dense vs flash from the autotune table's
    per-device crossover, with memory feasibility as the OOM guard only.

    Decision order:
      0. the mesh's ``seq`` axis is populated and the (self-attention)
         shape is long-context — ``seq_q == seq_kv`` at/above
         ``TPP_RING_MIN_SEQ`` (default 2048), or even the per-shard dense
         tile doesn't fit — => "ring": the sequence is sharded over the
         axis, so single-device kernels never see the full L; ring
         attention streams the kv blocks around the mesh with overlapped
         ``ppermute`` (the long-context window path, ISSUE 18).  Short
         sequences on a seq mesh stay on the measured rule below — the
         ring's per-hop latency only pays for itself once L is large;
      1. dense's O(L^2) temporaries don't fit => "flash" (the guard —
         feasibility, exactly what ``dense_attn_fits`` was built for);
      2. a measured crossover exists for this device_kind (recorded by
         the bench flash_probe sweep via ``autotune.record_crossover``)
         => "flash" at/above it, "dense" below it;
      3. no measurement => "dense" (every probe so far measured dense
         faster wherever it fits; flash must EARN the hot path).
    """
    if (
        mesh is not None
        and mesh.shape.get("seq", 1) > 1
        and seq_q == seq_kv
    ):
        floor = int(os.environ.get("TPP_RING_MIN_SEQ", RING_MIN_SEQ))
        if seq_q >= floor or not dense_attn_fits(
            batch, heads, seq_q, seq_kv, itemsize, mesh=mesh
        ):
            return "ring"
    if not dense_attn_fits(batch, heads, seq_q, seq_kv, itemsize, mesh=mesh):
        return "flash"
    from tpu_pipelines.ops import autotune

    crossover = autotune.lookup_crossover()
    if crossover is not None and max(seq_q, seq_kv) >= crossover:
        return "flash"
    return "dense"


def choose_decode_impl(
    batch: int,
    heads: int,
    kv_len: int,
    head_dim: int,
) -> str:
    """The "auto" rule for the single-query DECODE regime (KV-cache
    attention during autoregressive generation).

    A decode step's score temporaries are [B, H, 1, L] — tiny — so there
    is no OOM guard here; the only question is measured speed.  The
    decode step streams the whole KV cache per token, a bandwidth-bound
    profile unlike the training shapes, so it gets its OWN crossover
    (``autotune.lookup_decode_crossover``, recorded by the bench
    ``t5_decode`` leg): flash-decode at/above the measured cache length,
    dense below it, and dense whenever no measurement exists — the
    kernel must earn the hot path, same as training flash (PR 9).
    """
    del batch, heads, head_dim  # keyed per device kind + cache length only
    from tpu_pipelines.ops import autotune

    crossover = autotune.lookup_decode_crossover()
    if crossover is not None and kv_len >= crossover:
        return "flash"
    return "dense"


class MlpBlock(nn.Module):
    d_ff: int
    dropout_rate: float = 0.0
    dtype: Dtype = jnp.bfloat16
    activation: str = "gelu"
    # Where dropout lands, matching each family's canonical recipe:
    # "output" (BERT: HF BertOutput drops the d_model-wide projection) or
    # "hidden" (T5: DenseReluDense drops the d_ff-wide activation).  The
    # site is also a throughput lever — dropout RNG+mask measured ~16% of
    # the BERT-base fine-tune step on v5e, and the output site has 4x fewer
    # mask elements than the hidden site at BERT geometry.
    dropout_site: str = "output"

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True):
        d_model = x.shape[-1]
        h = nn.Dense(self.d_ff, dtype=self.dtype, name="wi")(x)
        h = getattr(nn, self.activation)(h)
        if self.dropout_rate and self.dropout_site == "hidden":
            h = nn.Dropout(self.dropout_rate)(h, deterministic=deterministic)
        out = nn.Dense(d_model, dtype=self.dtype, name="wo")(h)
        if self.dropout_rate and self.dropout_site == "output":
            out = nn.Dropout(self.dropout_rate)(out, deterministic=deterministic)
        return out


class MoEMlpBlock(nn.Module):
    """Switch-Transformer-style mixture-of-experts MLP (expert parallelism).

    Top-1 routing with a fixed per-expert capacity, implemented as DENSE
    dispatch/combine einsums over a [tokens, experts, capacity] one-hot —
    the Mesh-TF/Switch algorithm: no ragged shapes, everything tiles onto
    the MXU, and sharding the expert dim of ``wi``/``wo`` over the mesh
    ``expert`` axis (TRANSFORMER_PARTITION_RULES) makes XLA insert the
    dispatch all-to-alls from the shardings alone — no hand-written
    collectives, consistent with the rest of this module.

    Tokens routed past an expert's capacity are DROPPED (output zero);
    the surrounding residual connection carries them through unchanged —
    standard Switch behavior.  Routing is PER GROUP (default: one group
    per sequence row, the Mesh-TF convention): capacity and the dispatch
    one-hot scale with the group size, not the whole flattened batch, so
    dispatch cost stays linear in total tokens.  The load-balancing
    auxiliary loss (E * sum over experts of token_fraction * prob_fraction;
    1.0 at perfect balance) is sown into the ``losses`` collection as
    ``moe_aux_loss`` — training objectives MUST consume it or routing can
    collapse onto one expert; use :func:`apply_with_moe_aux` in a loss_fn:

        logits, aux = apply_with_moe_aux(model, {"params": p}, batch, ...)
        loss = task_loss(logits) + 0.01 * aux
    """

    num_experts: int
    d_ff: int
    capacity_factor: float = 1.25
    dtype: Dtype = jnp.bfloat16
    activation: str = "gelu"
    dropout_rate: float = 0.0
    group_size: int = 0     # tokens per routing group; 0 = sequence length

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True):
        b, l, d = x.shape
        n = b * l
        e = self.num_experts
        g_size = self.group_size or l
        if n % g_size:
            raise ValueError(
                f"{n} tokens not divisible by MoE group_size {g_size}"
            )
        n_groups = n // g_size
        t = x.reshape(n_groups, g_size, d)
        # Router in f32: tiny matmul, and argmax ties/softmax stability
        # matter more than MXU throughput here.
        logits = nn.Dense(e, dtype=jnp.float32, name="router")(
            t.astype(jnp.float32)
        )
        probs = jax.nn.softmax(logits, axis=-1)            # [G, g, e]
        expert = jnp.argmax(probs, axis=-1)                # [G, g]
        gate = jnp.take_along_axis(probs, expert[..., None], axis=-1)[..., 0]

        capacity = max(1, int(np.ceil(self.capacity_factor * g_size / e)))
        sel = jax.nn.one_hot(expert, e, dtype=jnp.int32)   # [G, g, e]
        # Position of each token in its expert's per-group queue.
        pos = jnp.cumsum(sel, axis=1) * sel                # 1-based where sel
        pos_in_expert = pos.sum(axis=-1) - 1               # [G, g], -1 if none
        keep = (pos_in_expert >= 0) & (pos_in_expert < capacity)
        dispatch = (
            sel.astype(self.dtype)[..., None]
            * jax.nn.one_hot(
                jnp.where(keep, pos_in_expert, capacity),
                capacity, dtype=self.dtype,
            )[:, :, None, :]
        )                                                   # [G, g, e, c]

        # batch_axis=0: fan is computed PER EXPERT slice — plain
        # lecun_normal would count the expert dim as receptive field and
        # under-scale every expert by sqrt(e) vs the dense MLP it replaces.
        expert_init = nn.initializers.variance_scaling(
            1.0, "fan_in", "truncated_normal", in_axis=-2, out_axis=-1,
            batch_axis=0,
        )
        wi = self.param(
            "wi", expert_init, (e, d, self.d_ff)
        ).astype(self.dtype)
        wo = self.param(
            "wo", expert_init, (e, self.d_ff, d)
        ).astype(self.dtype)
        expert_in = jnp.einsum(
            "gnec,gnd->gecd", dispatch, t.astype(self.dtype)
        )
        h = getattr(nn, self.activation)(
            jnp.einsum("gecd,edf->gecf", expert_in, wi)
        )
        expert_out = jnp.einsum("gecf,efd->gecd", h, wo)
        combine = dispatch * gate.astype(self.dtype)[..., None, None]
        out = jnp.einsum("gnec,gecd->gnd", combine, expert_out)

        # Switch aux loss: e * sum_e(fraction_of_tokens * mean_router_prob).
        frac_tokens = sel.astype(jnp.float32).mean(axis=(0, 1))  # [e]
        frac_probs = probs.mean(axis=(0, 1))                     # [e]
        self.sow(
            "losses", "moe_aux_loss",
            e * jnp.sum(frac_tokens * frac_probs),
        )
        out = out.reshape(b, l, d)
        if self.dropout_rate:
            # Same output-site dropout as the dense MlpBlock it replaces.
            out = nn.Dropout(self.dropout_rate)(
                out, deterministic=deterministic
            )
        return out


def apply_with_moe_aux(model, variables, *args, **kwargs):
    """``model.apply`` that also returns the summed MoE auxiliary loss.

    The supported way to train MoE models: runs apply with the ``losses``
    collection mutable and sums every sown ``moe_aux_loss`` (one per MoE
    layer; 0.0 when the model has none), so loss functions can add
    ``aux_weight * aux`` without touching flax collection plumbing.
    """
    out, state = model.apply(variables, *args, mutable=["losses"], **kwargs)
    leaves = jax.tree_util.tree_leaves(state.get("losses", {}))
    aux = sum(leaves) if leaves else jnp.zeros((), jnp.float32)
    return out, aux


class MultiHeadAttention(nn.Module):
    """Self/cross attention; TP over heads, optional ring SP over sequence.

    ``attn_impl``:
      - "dense": plain XLA attention (any mask/bias/cross).
      - "ring":  sequence-parallel ring attention over the mesh ``seq``
        axis (ppermute pipeline; scales past one chip's memory).
      - "ulysses": all-to-all sequence parallelism over ``seq`` (two
        collectives, full-sequence dense math per head slice; lower latency
        at moderate lengths, needs local heads divisible by the axis).
      - "flash": the Pallas blockwise kernel (ops/flash_attention.py) — no
        O(L²) score tensor in HBM, fwd and bwd.
      - "auto":  measured flash-vs-dense choice (choose_attn_impl): dense
        below the device's recorded crossover sequence length (autotune
        table, written by the bench flash_probe sweep), flash at/above
        it, and always flash when dense's O(L²) score temporaries cannot
        fit (dense_attn_fits stays as the OOM guard).  With no recorded
        crossover: dense wherever it fits — the measured default on v5e
        (BENCH_R4/R5 flash_probe: dense ~25-30% faster at L=128-2048;
        flash's win is running at L=8192+ where dense cannot compile).
    Ring/ulysses/flash require self-attention without an additive bias;
    cross attention and biased attention (T5 relative positions) always
    take the dense path.
    """

    n_heads: int
    head_dim: int
    dropout_rate: float = 0.0
    dtype: Dtype = jnp.bfloat16
    attn_impl: str = "dense"
    mesh: Optional[Mesh] = None
    causal: bool = False

    @nn.compact
    def __call__(
        self,
        x_q,
        x_kv=None,
        *,
        kv_mask=None,
        bias=None,
        deterministic: bool = True,
        decode_pos=None,
        max_decode_len: Optional[int] = None,
    ):
        is_self = x_kv is None
        x_kv = x_q if is_self else x_kv
        proj = lambda name: nn.DenseGeneral(
            (self.n_heads, self.head_dim), axis=-1, dtype=self.dtype, name=name
        )
        q = proj("query")(x_q)

        if decode_pos is not None and not is_self:
            # Cross attention during incremental decoding: the encoder output
            # is constant across decode steps, so its K/V projections are
            # computed exactly once — the variable initializer runs only on
            # the cache-creating apply (step 0) and later steps reuse the
            # stored arrays instead of re-projecting [b, enc_len, d_model]
            # through two matmuls per layer per token.
            cached_ek = self.variable(
                "cache", "cached_enc_key", lambda: proj("key")(x_kv)
            )
            cached_ev = self.variable(
                "cache", "cached_enc_value", lambda: proj("value")(x_kv)
            )
            out = dense_attention(
                q, cached_ek.value, cached_ev.value, causal=False,
                kv_mask=kv_mask, bias=bias,
            )
            return nn.DenseGeneral(
                x_q.shape[-1], axis=(-2, -1), dtype=self.dtype, name="out"
            )(out)

        k = proj("key")(x_kv)
        v = proj("value")(x_kv)

        if decode_pos is not None and is_self:
            # Incremental decoding: x_q is this step's single token
            # ([b, 1, d_model]); K/V land in a static-shape cache at
            # ``decode_pos`` and attention runs over the filled prefix.
            # The cache is a flax "cache" collection created on the first
            # mutable apply — static shapes keep the whole decode loop
            # jit/scan-compatible (no growing arrays).
            #
            # ``decode_pos`` may be a scalar (every row at the same step:
            # the greedy/beam scan) or a [b] vector (continuous batching:
            # each sequence in the batch sits at its OWN step, so the
            # update is a per-row scatter and the validity mask is
            # per-row).  Both paths compute identical per-row math.
            if max_decode_len is None:
                raise ValueError("decode_pos requires max_decode_len")
            b = q.shape[0]
            cached_k = self.variable(
                "cache", "cached_key", jnp.zeros,
                (b, max_decode_len, self.n_heads, self.head_dim), k.dtype,
            )
            cached_v = self.variable(
                "cache", "cached_value", jnp.zeros,
                (b, max_decode_len, self.n_heads, self.head_dim), v.dtype,
            )
            pos = jnp.asarray(decode_pos, jnp.int32)
            verify_window = False
            if pos.ndim == 0:
                cached_k.value = jax.lax.dynamic_update_slice_in_dim(
                    cached_k.value, k, pos, axis=1
                )
                cached_v.value = jax.lax.dynamic_update_slice_in_dim(
                    cached_v.value, v, pos, axis=1
                )
                # Positions after ``pos`` are zeros (future steps): mask.
                valid = jnp.broadcast_to(
                    (jnp.arange(max_decode_len) <= pos)[None, :],
                    (b, max_decode_len),
                )
            elif q.shape[1] == 1:
                rows = jnp.arange(b)
                cached_k.value = cached_k.value.at[rows, pos].set(k[:, 0])
                cached_v.value = cached_v.value.at[rows, pos].set(v[:, 0])
                valid = jnp.arange(max_decode_len)[None, :] <= pos[:, None]
            else:
                # Speculative verify: ``qlen`` candidate tokens per row,
                # row i's queries occupying positions
                # ``pos[i] .. pos[i]+qlen-1`` — one scatter of a window
                # per row, then per-QUERY causal validity (query j sees
                # cache positions <= pos+j).  dense_attention's kv_mask
                # is per-row, so the per-query window folds into the
                # additive bias instead; same NEG_INF -> exact-zero
                # weight semantics as every other mask here.
                from tpu_pipelines.parallel.ring_attention import NEG_INF

                rows = jnp.arange(b)
                qlen = q.shape[1]
                idx = pos[:, None] + jnp.arange(qlen)[None, :]  # [b, q]
                cached_k.value = cached_k.value.at[rows[:, None], idx].set(k)
                cached_v.value = cached_v.value.at[rows[:, None], idx].set(v)
                win = (
                    jnp.arange(max_decode_len)[None, None, :]
                    <= idx[:, :, None]
                )                                               # [b, q, kv]
                wbias = jnp.where(win, 0.0, NEG_INF)[:, None]   # [b,1,q,kv]
                bias = wbias if bias is None else bias + wbias
                valid = None
                verify_window = True
            impl = self.attn_impl
            if verify_window:
                # flash_decode_attention is a single-query kernel; the
                # verify window runs dense (it is one fused step per
                # round, not the per-token hot path).
                impl = "dense"
            if impl == "auto":
                # Decode-regime choice: the single-query step is bandwidth-
                # bound on the KV cache, a different balance from training
                # attention — its own measured crossover applies
                # (choose_decode_impl), never the training-shape one.
                impl = choose_decode_impl(
                    b, self.n_heads, max_decode_len, self.head_dim
                )
            if impl == "flash":
                from tpu_pipelines.ops.flash_attention import (
                    flash_decode_attention,
                )

                out = flash_decode_attention(
                    q, cached_k.value, cached_v.value,
                    kv_mask=valid, bias=bias,
                )
            else:
                out = dense_attention(
                    q, cached_k.value, cached_v.value, causal=False,
                    kv_mask=valid, bias=bias,
                )
            return nn.DenseGeneral(
                x_q.shape[-1], axis=(-2, -1), dtype=self.dtype, name="out"
            )(out)

        impl = self.attn_impl
        if impl == "auto":
            # Measured crossover (autotune table) over per-shard memory
            # feasibility: dense below the device's recorded flash-vs-dense
            # crossover, flash at/above it, and always flash when dense's
            # per-shard O(L^2) score footprint cannot fit (the OOM guard).
            impl = choose_attn_impl(
                q.shape[0], self.n_heads, q.shape[1], k.shape[1],
                jnp.dtype(self.dtype).itemsize,
                mesh=self.mesh,
            )
        has_seq_axis = (
            self.mesh is not None and self.mesh.shape.get("seq", 1) > 1
        )
        use_ring = impl == "ring" and is_self and bias is None and has_seq_axis
        use_ulysses = (
            impl == "ulysses" and is_self and bias is None and has_seq_axis
        )
        use_flash = (
            impl == "flash" and is_self and bias is None
        )
        if use_ring:
            out = ring_attention(
                q, k, v, mesh=self.mesh, causal=self.causal, kv_mask=kv_mask
            )
        elif use_ulysses:
            from tpu_pipelines.parallel.ring_attention import ulysses_attention

            out = ulysses_attention(
                q, k, v, mesh=self.mesh, causal=self.causal, kv_mask=kv_mask
            )
        elif use_flash:
            from tpu_pipelines.ops.flash_attention import flash_attention

            out = flash_attention(
                q, k, v, causal=self.causal, kv_mask=kv_mask
            )
        else:
            out = dense_attention(
                q, k, v, causal=self.causal, kv_mask=kv_mask, bias=bias
            )
        out = nn.DenseGeneral(
            x_q.shape[-1], axis=(-2, -1), dtype=self.dtype, name="out"
        )(out)
        if self.dropout_rate:
            out = nn.Dropout(self.dropout_rate)(out, deterministic=deterministic)
        return out


class TransformerBlock(nn.Module):
    """Pre- or post-LN encoder/decoder block (self-attn [+cross] + MLP)."""

    n_heads: int
    head_dim: int
    d_ff: int
    dropout_rate: float = 0.0
    dtype: Dtype = jnp.bfloat16
    attn_impl: str = "dense"
    mesh: Optional[Mesh] = None
    causal: bool = False
    prenorm: bool = True
    use_cross: bool = False
    norm: str = "layernorm"   # "layernorm" (BERT) or "rmsnorm" (T5)
    mlp_dropout_site: str = "output"   # see MlpBlock.dropout_site
    # > 0 replaces the dense MLP with a MoEMlpBlock of this many experts
    # (expert-parallel over the mesh ``expert`` axis).
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25

    @nn.compact
    def __call__(
        self,
        x,
        *,
        encoded=None,
        kv_mask=None,
        enc_mask=None,
        self_bias=None,
        deterministic: bool = True,
        decode_pos=None,
        max_decode_len: Optional[int] = None,
    ):
        mha = lambda name, causal: MultiHeadAttention(
            n_heads=self.n_heads, head_dim=self.head_dim,
            dropout_rate=self.dropout_rate, dtype=self.dtype,
            attn_impl=self.attn_impl, mesh=self.mesh, causal=causal,
            name=name,
        )
        norm_cls = nn.RMSNorm if self.norm == "rmsnorm" else nn.LayerNorm
        ln = lambda name: norm_cls(dtype=self.dtype, name=name)

        def sub(x, name, fn):
            if self.prenorm:
                return x + fn(ln(f"{name}_norm")(x))
            return ln(f"{name}_norm")(x + fn(x))

        x = sub(x, "attn", lambda h: mha("attn", self.causal)(
            h, kv_mask=kv_mask, bias=self_bias, deterministic=deterministic,
            decode_pos=decode_pos, max_decode_len=max_decode_len,
        ))
        if self.use_cross:
            x = sub(x, "cross", lambda h: mha("cross", False)(
                h, encoded, kv_mask=enc_mask, deterministic=deterministic,
                decode_pos=decode_pos,
            ))
        if self.moe_experts > 0:
            x = sub(x, "mlp", lambda h: MoEMlpBlock(
                num_experts=self.moe_experts, d_ff=self.d_ff,
                capacity_factor=self.moe_capacity_factor,
                dropout_rate=self.dropout_rate,
                dtype=self.dtype, name="moe",
            )(h, deterministic=deterministic))
        else:
            x = sub(x, "mlp", lambda h: MlpBlock(
                d_ff=self.d_ff, dropout_rate=self.dropout_rate,
                dtype=self.dtype, dropout_site=self.mlp_dropout_site,
                name="mlp",
            )(h, deterministic=deterministic))
        return x


# Megatron-style TP rules for the blocks above (parallel/partition.py):
# QKV projections and MLP wi shard their output dim over `model`
# (column-parallel); attention out and MLP wo shard their input dim
# (row-parallel) so XLA inserts one all-reduce per block, over ICI.
TRANSFORMER_PARTITION_RULES = [
    (r"(query|key|value)/kernel", P(None, "model", None)),
    (r"attn/out/kernel", P("model", None, None)),
    (r"cross/out/kernel", P("model", None, None)),
    (r"mlp/wi/kernel", P(None, "model")),
    (r"mlp/wo/kernel", P("model", None)),
    # MoE experts shard over `expert` (EP), their ff dim over `model` (TP);
    # the router stays replicated (tiny).
    (r"moe/wi", P("expert", None, "model")),
    (r"moe/wo", P("expert", "model", None)),
    # token embeddings only (vocab dim sharded); positional/type tables are
    # small and replicate — (^|/) anchors to a whole path segment so
    # e.g. "type_embed" does not match.
    (r"(^|/)(embed|shared)/embedding", P("model", None)),
]
