"""Pipeline-staged transformer classifier: PP reachable from the Trainer.

This makes the GPipe library (parallel/pipeline_parallel.py) a capability
of the framework proper (VERDICT r3 next#5): a token classifier whose
transformer depth splits into ``n_stages`` stages with per-stage params
STACKED on a leading stage dim and sharded ``P("pipe", ...)``, so a
Trainer component configured with ``mesh={"data": D, "pipe": S}`` trains
dp×pp through the ordinary ``run_fn`` contract
(examples/staged/staged_trainer_module.py).

Design constraints inherited from the one-scan GPipe schedule:
  - stage activations are a single ``[batch, seq, d_model]`` array, so the
    staged path runs UNMASKED full self-attention (pad tokens attend; the
    residual signal dominates for classification) — masks would have to
    ride the pipeline as part of the activation;
  - stages run ``deterministic`` (no dropout inside the shard_map schedule).
The sequential path (``mesh=None`` or ``pipe == 1``) scans the same stacked
params in order — numerically the same network, which is both the loss
parity oracle in tests/test_pp_trainer.py and the serving path after
export (the loaded model needs no pipe mesh).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh, PartitionSpec as P

from tpu_pipelines.models.transformer import TransformerBlock
from tpu_pipelines.parallel.pipeline_parallel import gpipe

DEFAULT_HPARAMS: Dict[str, Any] = {
    "vocab_size": 64,
    "d_model": 32,
    "n_heads": 2,
    "head_dim": 16,
    "d_ff": 64,
    "max_len": 16,
    "num_classes": 4,
    "n_stages": 4,
    "layers_per_stage": 1,
    "num_microbatches": 4,
    "dtype": "float32",
    "learning_rate": 1e-3,
    "batch_size": 32,
}


class _Embed(nn.Module):
    vocab_size: int
    d_model: int
    max_len: int
    dtype: Any

    @nn.compact
    def __call__(self, tokens):
        x = nn.Embed(
            self.vocab_size, self.d_model, dtype=self.dtype, name="token"
        )(tokens)
        pos = self.param(
            "pos_embedding",
            nn.initializers.normal(0.02),
            (self.max_len, self.d_model),
        )
        return x + pos[None, : tokens.shape[1]].astype(self.dtype)


class _Stage(nn.Module):
    """One pipeline stage: ``layers_per_stage`` transformer blocks.

    Must preserve activation shape/dtype and be code-identical across
    stages — the SPMD contract gpipe() requires."""

    layers_per_stage: int
    n_heads: int
    head_dim: int
    d_ff: int
    dtype: Any

    @nn.compact
    def __call__(self, x):
        for i in range(self.layers_per_stage):
            x = TransformerBlock(
                n_heads=self.n_heads, head_dim=self.head_dim,
                d_ff=self.d_ff, dropout_rate=0.0, dtype=self.dtype,
                attn_impl="dense", name=f"layer_{i}",
            )(x, deterministic=True)
        return x


class _Head(nn.Module):
    num_classes: int
    dtype: Any

    @nn.compact
    def __call__(self, x):
        x = nn.LayerNorm(dtype=self.dtype, name="final_norm")(x)
        x = x.mean(axis=1).astype(jnp.float32)
        return nn.Dense(self.num_classes, name="classifier")(x)


@dataclasses.dataclass
class StagedClassifier:
    """embed -> S stacked stages (gpipe or sequential scan) -> head."""

    hp: Dict[str, Any]

    def __post_init__(self):
        hp = self.hp
        dtype = jnp.dtype(hp["dtype"])
        self.embed = _Embed(
            vocab_size=int(hp["vocab_size"]), d_model=int(hp["d_model"]),
            max_len=int(hp["max_len"]), dtype=dtype,
        )
        self.stage = _Stage(
            layers_per_stage=int(hp["layers_per_stage"]),
            n_heads=int(hp["n_heads"]), head_dim=int(hp["head_dim"]),
            d_ff=int(hp["d_ff"]), dtype=dtype,
        )
        self.head = _Head(num_classes=int(hp["num_classes"]), dtype=dtype)
        self.n_stages = int(hp["n_stages"])
        self.num_microbatches = int(hp["num_microbatches"])

    def init(self, rng: jax.Array, tokens) -> Dict[str, Any]:
        tokens = jnp.asarray(tokens, jnp.int32)
        r_embed, r_stage, r_head = jax.random.split(rng, 3)
        embed_p = self.embed.init(r_embed, tokens)["params"]
        x = self.embed.apply({"params": embed_p}, tokens)
        keys = jax.random.split(r_stage, self.n_stages)
        # One stage traced once, init vmapped over stage keys: leaves gain
        # the leading stage dim gpipe() shards over "pipe".
        stage_p = jax.vmap(
            lambda k: self.stage.init(k, x)["params"]
        )(keys)
        head_p = self.head.init(r_head, x)["params"]
        return {"embed": embed_p, "stages": stage_p, "head": head_p}

    def apply(
        self,
        params: Dict[str, Any],
        tokens,
        *,
        mesh: Optional[Mesh] = None,
    ) -> jax.Array:
        tokens = jnp.asarray(tokens, jnp.int32)
        x = self.embed.apply({"params": params["embed"]}, tokens)

        def stage_fn(p, a):
            return self.stage.apply({"params": p}, a)

        if mesh is not None and mesh.shape.get("pipe", 1) > 1:
            x = gpipe(
                stage_fn, params["stages"], x,
                mesh=mesh, num_microbatches=self.num_microbatches,
            )
        else:
            # Sequential oracle/serving path: scan the stacked stage params
            # in order — the same network gpipe computes, without a mesh.
            def body(a, p):
                return stage_fn(p, a), None

            x, _ = jax.lax.scan(body, x, params["stages"])
        return self.head.apply({"params": params["head"]}, x)


def build_staged_model(
    hparams: Optional[Dict[str, Any]] = None,
) -> StagedClassifier:
    hp = {**DEFAULT_HPARAMS, **(hparams or {})}
    return StagedClassifier(hp)


def staged_partition_rules():
    """``param_partition`` rules: stacked stage params shard their leading
    stage dim over ``pipe``; embed/head replicate (first match wins)."""
    return [(r"^stages/", P("pipe"))]
