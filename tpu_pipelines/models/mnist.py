"""MNIST CNN (BASELINE config 1: "MNIST Keras CNN via TFX Trainer").

The reference trains a small Keras convnet through the Trainer's ``run_fn``
under a single-host strategy (SURVEY.md §0, configs[1]).  Same capability
here as a flax module driven by the framework train loop: two conv blocks +
MLP head, NHWC layout (what XLA:TPU expects for conv tiling).
"""

from __future__ import annotations

from typing import Dict, Sequence

import jax.numpy as jnp
from flax import linen as nn


class MnistCNN(nn.Module):
    """(batch, 28, 28, 1) images in, (batch, num_classes) logits out."""

    num_classes: int = 10
    conv_features: Sequence[int] = (32, 64)
    hidden_dim: int = 128
    dropout_rate: float = 0.25

    @nn.compact
    def __call__(self, images: jnp.ndarray, *, train: bool = False,
                 dropout_rng=None) -> jnp.ndarray:
        x = jnp.asarray(images, jnp.float32)
        if x.ndim == 3:
            x = x[..., None]
        for i, feat in enumerate(self.conv_features):
            x = nn.Conv(feat, kernel_size=(3, 3), name=f"conv_{i}")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.hidden_dim, name="dense_0")(x))
        if train and self.dropout_rate > 0:
            x = nn.Dropout(rate=self.dropout_rate, deterministic=False)(
                x, rng=dropout_rng
            )
        return nn.Dense(self.num_classes, name="head")(x)


DEFAULT_HPARAMS = {
    "num_classes": 10,
    "conv_features": [32, 64],
    "hidden_dim": 128,
    "dropout_rate": 0.25,
    "learning_rate": 1e-3,
    "batch_size": 256,
}


def build_mnist_model(hparams: Dict) -> MnistCNN:
    hp = {**DEFAULT_HPARAMS, **(hparams or {})}
    return MnistCNN(
        num_classes=int(hp["num_classes"]),
        conv_features=tuple(hp["conv_features"]),
        hidden_dim=int(hp["hidden_dim"]),
        dropout_rate=float(hp["dropout_rate"]),
    )
