"""ResNet for image classification (BASELINE config 2: ResNet-50 ImageNet).

The reference runs ResNet-50 under ``MultiWorkerMirroredStrategy`` in a
multi-worker Kubeflow pod (SURVEY.md §0 configs[2]); here the model is a flax
module whose scaling comes from the framework mesh (batch over ``data``) —
the train loop, not the model, owns distribution.

TPU-first choices: NHWC layout, bfloat16 compute with float32 params/batch
stats (MXU-friendly), BatchNorm folded into flax's mutable-collection idiom.
``v1.5`` bottleneck ordering (stride on the 3x3) matches the torchvision /
Keras variant the reference family uses.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Sequence, Tuple

import jax.numpy as jnp
from flax import linen as nn

ModuleDef = Any

# depth -> per-stage block counts
STAGE_SIZES = {
    18: (2, 2, 2, 2),
    34: (3, 4, 6, 3),
    50: (3, 4, 6, 3),
    101: (3, 4, 23, 3),
    152: (3, 8, 36, 3),
}


class BottleneckBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = nn.relu(self.norm()(y))
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = nn.relu(self.norm()(y))
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="proj"
            )(residual)
            residual = self.norm(name="proj_bn")(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = nn.relu(self.norm()(y))
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="proj")(residual)
            residual = self.norm(name="proj_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """NHWC images in, (batch, num_classes) logits out.

    Call with ``train=True`` inside ``nn.Module.apply(..., mutable=["batch_stats"])``
    to update BatchNorm statistics.
    """

    num_classes: int = 1000
    depth: int = 50
    width: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, images: jnp.ndarray, *, train: bool = False):
        stage_sizes = STAGE_SIZES[self.depth]
        block_cls = BottleneckBlock if self.depth >= 50 else BasicBlock
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=self.dtype, padding="SAME"
        )
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype,
        )
        x = jnp.asarray(images, self.dtype)
        x = conv(self.width, (7, 7), (2, 2), name="conv_init")(x)
        x = nn.relu(norm(name="bn_init")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(stage_sizes):
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = block_cls(
                    filters=self.width * 2 ** i, conv=conv, norm=norm,
                    strides=strides, name=f"stage{i}_block{j}",
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        # Head in float32 for numerically stable softmax/loss.
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


DEFAULT_HPARAMS = {
    "num_classes": 1000,
    "depth": 50,
    "width": 64,
    "learning_rate": 0.1,
    "batch_size": 1024,
}


def build_resnet_model(hparams: Dict) -> ResNet:
    hp = {**DEFAULT_HPARAMS, **(hparams or {})}
    return ResNet(
        num_classes=int(hp["num_classes"]),
        depth=int(hp["depth"]),
        width=int(hp["width"]),
    )
