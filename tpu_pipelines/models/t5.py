"""T5 encoder-decoder seq2seq (BASELINE config 4: T5-small, JAX run_fn).

The reference's stretch config runs a T5-small seq2seq fine-tune through a
JAX ``run_fn`` (SURVEY.md §0 configs[4]).  Built from the sharded transformer
blocks with the T5 particulars: RMSNorm pre-normalization, bucketed
relative-position attention bias shared across each stack's self-attention
layers, tied input/output embedding scaled by 1/sqrt(d_model) at the logits.

Relative-position bias is an additive [h, q, k] score term, so these
attention calls take the dense path (ring attention covers unbiased
self-attention; see models/transformer.py).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax.sharding import Mesh, PartitionSpec as P

from tpu_pipelines.models.transformer import (
    TRANSFORMER_PARTITION_RULES,
    TransformerBlock,
)


def relative_position_buckets(
    qlen: int, klen: int, *, bidirectional: bool, num_buckets: int = 32,
    max_distance: int = 128,
):
    """T5's log-bucketed relative positions; returns int32 [qlen, klen]."""
    ctx = np.arange(qlen)[:, None]
    mem = np.arange(klen)[None, :]
    rel = mem - ctx
    buckets = np.zeros_like(rel)
    n = num_buckets
    if bidirectional:
        n //= 2
        buckets += (rel > 0).astype(np.int64) * n
        rel = np.abs(rel)
    else:
        rel = -np.minimum(rel, 0)
    max_exact = n // 2
    is_small = rel < max_exact
    large = max_exact + (
        np.log(np.maximum(rel, 1) / max_exact)
        / np.log(max_distance / max_exact)
        * (n - max_exact)
    ).astype(np.int64)
    large = np.minimum(large, n - 1)
    buckets += np.where(is_small, rel, large)
    return jnp.asarray(buckets, jnp.int32)


class RelativePositionBias(nn.Module):
    n_heads: int
    bidirectional: bool
    num_buckets: int = 32
    max_distance: int = 128

    @nn.compact
    def __call__(self, qlen: int, klen: int, row=None):
        buckets = relative_position_buckets(
            qlen, klen, bidirectional=self.bidirectional,
            num_buckets=self.num_buckets, max_distance=self.max_distance,
        )
        table = self.param(
            "rel_embedding",
            nn.initializers.normal(stddev=1.0),
            (self.num_buckets, self.n_heads),
        )
        if row is not None:
            row = jnp.asarray(row, jnp.int32)
            if row.ndim == 0:
                # Incremental decode: only query position ``row`` is live
                # this step — slice its bucket row so the bias is
                # [1, h, 1, klen].
                buckets = jax.lax.dynamic_slice_in_dim(buckets, row, 1, axis=0)
            elif row.ndim == 1:
                # Continuous batching: each batch row sits at its OWN
                # decode position, so gather one bucket row per sequence —
                # bias [b, h, 1, klen], row i carrying position row[i]'s
                # slice of the full relative-position matrix.
                rows = jnp.take(buckets, row, axis=0)      # [b, klen]
                return jnp.transpose(
                    table[rows], (0, 2, 1)
                )[:, :, None, :].astype(jnp.float32)
            else:
                # Speculative verify window: ``row`` is [b, q] — query j
                # of sequence i sits at position row[i, j].  Gather a
                # bucket row per query: bias [b, h, q, klen].
                rows = jnp.take(buckets, row, axis=0)      # [b, q, klen]
                return jnp.transpose(
                    table[rows], (0, 3, 1, 2)
                ).astype(jnp.float32)
        # [q, k, h] -> [1, h, q, k] additive bias
        return jnp.transpose(table[buckets], (2, 0, 1))[None].astype(jnp.float32)


class T5Stack(nn.Module):
    n_layers: int
    n_heads: int
    head_dim: int
    d_ff: int
    dropout_rate: float
    dtype: Any
    causal: bool          # True = decoder
    mesh: Optional[Mesh] = None
    # Forwarded to the attention blocks.  T5's biased self-attention always
    # takes the dense path in training/full passes; the knob matters for
    # the single-query DECODE step, where "flash"/"auto" select the
    # flash-decode kernel against the KV cache (ops/flash_attention.py).
    attn_impl: str = "dense"

    @nn.compact
    def __call__(self, x, *, encoded=None, kv_mask=None, enc_mask=None,
                 deterministic: bool = True, decode_pos=None,
                 max_decode_len: Optional[int] = None):
        if decode_pos is not None:
            # One-token decode step: bias is the single row of the full
            # [max_decode_len, max_decode_len] relative-position matrix at
            # this step's position; the causal structure comes from the
            # attention cache's <=pos validity mask.  A multi-token
            # decoder input with per-row positions is the speculative
            # verify window: query j of row i sits at decode_pos[i] + j,
            # so the gather widens to one bias row per query (the window
            # mask lives in the attention layer).
            row = jnp.asarray(decode_pos, jnp.int32)
            if row.ndim == 1 and x.shape[1] > 1:
                row = row[:, None] + jnp.arange(x.shape[1])[None, :]
            bias = RelativePositionBias(
                n_heads=self.n_heads, bidirectional=not self.causal,
                name="rel_pos",
            )(max_decode_len, max_decode_len, row=row)
            kv_mask = None
        else:
            bias = RelativePositionBias(
                n_heads=self.n_heads, bidirectional=not self.causal,
                name="rel_pos",
            )(x.shape[1], x.shape[1])
        for i in range(self.n_layers):
            x = TransformerBlock(
                n_heads=self.n_heads, head_dim=self.head_dim, d_ff=self.d_ff,
                dropout_rate=self.dropout_rate, dtype=self.dtype,
                causal=self.causal, prenorm=True, norm="rmsnorm",
                mlp_dropout_site="hidden",   # T5's DenseReluDense recipe
                use_cross=self.causal and encoded is not None,
                attn_impl=self.attn_impl,
                mesh=self.mesh, name=f"layer_{i}",
            )(
                x, encoded=encoded, kv_mask=kv_mask, enc_mask=enc_mask,
                self_bias=bias, deterministic=deterministic,
                decode_pos=decode_pos, max_decode_len=max_decode_len,
            )
        return nn.RMSNorm(dtype=self.dtype, name="final_norm")(x)


class T5(nn.Module):
    """batch {inputs, targets [, input_mask, target_mask]} -> vocab logits.

    ``targets`` are teacher-forcing decoder inputs shifted right internally
    (BOS = 0, the T5 convention).
    """

    vocab_size: int = 32128
    d_model: int = 512
    n_layers: int = 6
    n_heads: int = 8
    head_dim: int = 64
    d_ff: int = 2048
    dropout_rate: float = 0.1
    dtype: Any = jnp.bfloat16
    mesh: Optional[Mesh] = None
    attn_impl: str = "dense"   # decode-step kernel choice; see T5Stack

    def setup(self):
        self.shared = nn.Embed(
            self.vocab_size, self.d_model, dtype=self.dtype, name="shared"
        )
        common = dict(
            n_heads=self.n_heads, head_dim=self.head_dim, d_ff=self.d_ff,
            dropout_rate=self.dropout_rate, dtype=self.dtype, mesh=self.mesh,
            attn_impl=self.attn_impl,
        )
        self.encoder = T5Stack(n_layers=self.n_layers, causal=False,
                               name="encoder", **common)
        self.decoder = T5Stack(n_layers=self.n_layers, causal=True,
                               name="decoder", **common)

    def encode(self, inputs, input_mask=None, *, deterministic=True):
        x = self.shared(jnp.asarray(inputs, jnp.int32))
        return self.encoder(x, kv_mask=input_mask, deterministic=deterministic)

    def decode(self, decoder_input_ids, encoded, *, target_mask=None,
               enc_mask=None, deterministic=True, decode_pos=None,
               max_decode_len=None):
        y = self.shared(jnp.asarray(decoder_input_ids, jnp.int32))
        y = self.decoder(
            y, encoded=encoded, kv_mask=target_mask, enc_mask=enc_mask,
            deterministic=deterministic, decode_pos=decode_pos,
            max_decode_len=max_decode_len,
        )
        # tied embedding as the output projection, T5's 1/sqrt(d) scaling;
        # logits in float32 for a stable softmax loss
        y = y * (self.d_model ** -0.5)
        return jnp.einsum(
            "bld,vd->blv", y.astype(jnp.float32),
            self.shared.embedding.astype(jnp.float32),
        )

    def __call__(self, batch: Dict[str, Any], *, deterministic: bool = True):
        inputs = jnp.asarray(batch["inputs"], jnp.int32)
        targets = jnp.asarray(batch["targets"], jnp.int32)
        input_mask = batch.get("input_mask")
        decoder_inputs = jnp.pad(targets, ((0, 0), (1, 0)))[:, :-1]
        encoded = self.encode(
            inputs, input_mask, deterministic=deterministic
        )
        return self.decode(
            decoder_inputs, encoded,
            target_mask=batch.get("target_mask"), enc_mask=input_mask,
            deterministic=deterministic,
        )


DEFAULT_HPARAMS = {
    # t5-small geometry
    "vocab_size": 32128,
    "d_model": 512,
    "n_layers": 6,
    "n_heads": 8,
    "head_dim": 64,
    "d_ff": 2048,
    "dropout_rate": 0.1,
    "learning_rate": 1e-3,
    "batch_size": 64,
}


def build_t5_model(hparams: Dict, mesh: Optional[Mesh] = None) -> T5:
    hp = {**DEFAULT_HPARAMS, **(hparams or {})}
    return T5(
        vocab_size=int(hp["vocab_size"]),
        d_model=int(hp["d_model"]),
        n_layers=int(hp["n_layers"]),
        n_heads=int(hp["n_heads"]),
        head_dim=int(hp["head_dim"]),
        d_ff=int(hp["d_ff"]),
        dropout_rate=float(hp["dropout_rate"]),
        attn_impl=str(hp.get("attn_impl", "dense")),
        mesh=mesh,
    )


def t5_partition_rules():
    return list(TRANSFORMER_PARTITION_RULES) + [
        (r"rel_pos/rel_embedding", P(None, "model")),
    ]


# ---------------------------------------------------------------------------
# Autoregressive generation (the seq2seq inference path).
#
# The reference's BulkInferrer/serving story for seq2seq needs real decoding,
# not teacher forcing.  TPU-first shape discipline: the whole decode is ONE
# jitted computation — encoder forward, then a lax.scan over decode steps,
# each step a single-token decoder pass against the static-shape KV cache
# (models/transformer.py decode path).  No growing arrays, no host round
# trips per token; EOS handling is masking, not control flow.
# ---------------------------------------------------------------------------


def _decode_one(model, params, cache, tok, encoded, enc_mask, pos,
                max_decode_len: int):
    """One single-token decoder pass; returns (new_cache, logits [b, V])."""
    variables = {"params": params}
    if cache is not None:
        variables["cache"] = cache
    logits, mut = model.apply(
        variables, tok[:, None], encoded, enc_mask=enc_mask,
        decode_pos=pos, max_decode_len=max_decode_len,
        method=T5.decode, mutable=["cache"],
    )
    return mut["cache"], logits[:, 0]


def prefill_decode(model, params, inputs, input_mask, max_decode_len: int,
                   pad_id: int = 0):
    """Encoder pass + the cache-creating step-0 decoder pass, once per ROW.

    The shared front half of every decode entry point: greedy, beam
    (which tiles this result across beams instead of re-running the
    encoder K/V projections and the step-0 decoder pass per beam) and the
    continuous-batching engine's per-request prefill
    (serving/generative.py) all run the identical step-0 math through
    here.  Returns ``(cache, encoded, logits0 [b, V])`` — the cache holds
    the BOS K/V at position 0 plus the cross-attention K/V projected from
    ``encoded``.
    """
    encoded = model.apply(
        {"params": params}, inputs, input_mask, method=T5.encode
    )
    bos = jnp.full((inputs.shape[0],), pad_id, jnp.int32)
    cache, logits0 = _decode_one(
        model, params, None, bos, encoded, input_mask, 0, max_decode_len
    )
    return cache, encoded, logits0


def make_continuous_decode_fns(
    model: T5,
    *,
    max_decode_len: int = 32,
    eos_id: int = 1,
    pad_id: int = 0,
    max_input_len: int = 64,
):
    """Decode fns for the continuous-batching engine (serving/generative.py).

    Returns a namespace with the engine's duck-typed contract:

      - ``prefill(params, inputs [1, enc_len], input_mask)`` ->
        ``(cache, encoded, logits0)`` — one request's encoder pass + the
        cache-creating step-0 decoder pass (``prefill_decode``, the same
        math greedy/beam step 0 runs);
      - ``step(params, cache, tok [b], pos [b], encoded, enc_mask, klen)``
        -> ``(cache, logits [b, V])`` — ONE decode step for a batch whose
        rows sit at per-row positions ``pos``, over a cache sliced to the
        static KV bucket ``klen`` (the engine's paged-arena slice; the
        per-row masking makes the result independent of ``klen`` as long
        as every live position fits);
      - geometry/vocabulary constants (``max_decode_len``, ``eos_id``,
        ``pad_id``, ``max_input_len``) the engine sizes its arena from.

    Exported modules opt their payloads into generative serving by
    defining ``make_decode_fns(model, hyperparameters)`` returning this
    (trainer/export.py wires it onto ``LoadedModel.decode_fns``).
    """
    from types import SimpleNamespace

    def prefill(params, inputs, input_mask=None):
        return prefill_decode(
            model, params, inputs, input_mask, max_decode_len, pad_id
        )

    def step(params, cache, tok, pos, encoded, enc_mask, klen: int):
        variables = {"params": params, "cache": cache}
        logits, mut = model.apply(
            variables, tok[:, None], encoded, enc_mask=enc_mask,
            decode_pos=pos, max_decode_len=klen,
            method=T5.decode, mutable=["cache"],
        )
        return mut["cache"], logits[:, 0]

    def verify(params, cache, toks, pos, encoded, enc_mask, klen: int):
        # Speculative verify: score ``k`` fed tokens per row in ONE
        # decoder pass — toks[b, k] at positions pos..pos+k-1 (the
        # attention layer scatters the window and applies the per-query
        # causal mask).  Returns logits [b, k, V]; the engine keeps the
        # accepted prefix and the position-validity mask hides the rest.
        variables = {"params": params, "cache": cache}
        logits, mut = model.apply(
            variables, toks, encoded, enc_mask=enc_mask,
            decode_pos=pos, max_decode_len=klen,
            method=T5.decode, mutable=["cache"],
        )
        return mut["cache"], logits

    return SimpleNamespace(
        prefill=prefill,
        step=step,
        verify=verify,
        max_decode_len=int(max_decode_len),
        eos_id=int(eos_id),
        pad_id=int(pad_id),
        max_input_len=int(max_input_len),
    )


def make_greedy_generate(
    model: T5,
    *,
    max_decode_len: int = 32,
    eos_id: int = 1,
    pad_id: int = 0,
    temperature: float = 0.0,
):
    """Build a jitted ``fn(params, inputs, input_mask=None, rng=None) ->
    (tokens [b, max_decode_len], done [b])``.

    ``temperature == 0`` is greedy argmax; ``> 0`` samples from the scaled
    softmax (``rng`` required).  Sequences emit EOS then pad; ``done`` marks
    rows that finished within the budget.  The T5 shift-right convention
    (BOS = pad = 0) starts the decoder.
    """
    if temperature < 0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")

    def pick(logits, rng):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            rng, logits / jnp.asarray(temperature, logits.dtype), axis=-1
        ).astype(jnp.int32)

    def fn(params, inputs, input_mask=None, rng=None):
        if temperature > 0.0 and rng is None:
            raise ValueError("sampling (temperature > 0) requires rng")
        if rng is None:
            rng = jax.random.key(0)
        # Step 0 runs outside the scan (prefill_decode): its mutable apply
        # CREATES the cache collection, so the scan carry has a fixed
        # structure.
        rng, r0 = jax.random.split(rng)
        cache, encoded, logits0 = prefill_decode(
            model, params, inputs, input_mask, max_decode_len, pad_id
        )
        tok0 = pick(logits0, r0)
        finished0 = tok0 == eos_id

        def step(carry, t):
            cache, tok, finished, rng = carry
            rng, r = jax.random.split(rng)
            cache, logits = _decode_one(
                model, params, cache, tok, encoded, input_mask, t,
                max_decode_len,
            )
            nxt = jnp.where(finished, pad_id, pick(logits, r))
            return (cache, nxt, finished | (nxt == eos_id), rng), nxt

        (_, _, finished, _), rest = jax.lax.scan(
            step, (cache, tok0, finished0, rng),
            jnp.arange(1, max_decode_len),
        )
        tokens = jnp.concatenate([tok0[:, None], rest.T], axis=1)
        return tokens, finished

    return jax.jit(fn)


def make_beam_generate(
    model: T5,
    *,
    beam_size: int = 4,
    max_decode_len: int = 32,
    eos_id: int = 1,
    pad_id: int = 0,
    length_alpha: float = 0.6,
):
    """Build a jitted beam search ``fn(params, inputs, input_mask=None) ->
    (tokens [b, max_decode_len], score [b])``.

    Freeze-in-place beams: a finished beam may only emit pad at zero added
    log-prob, so its cumulative score is frozen while it stays a candidate —
    one topk over ``beam_size * vocab`` per step, no separate alive/finished
    sets.  Final selection maximizes ``logp / ((5 + len) / 6) ** alpha``
    (the GNMT length penalty).  Encoder runs once; beams share it via a
    flat ``batch * beam`` layout, and each step reorders the KV cache with
    one gather.
    """

    def fn(params, inputs, input_mask=None):
        b, k = inputs.shape[0], beam_size
        # Encoder + step-0 decoder run ONCE PER ROW (prefill_decode — the
        # same entry greedy and the continuous-batch engine use) and the
        # result is TILED across beams below: the k beams of a row are
        # identical at step 0, so the old flat [b*k] step 0 re-ran the
        # encoder K/V projections and the BOS decoder pass k x for
        # nothing.
        cache, encoded, logits0 = prefill_decode(
            model, params, inputs, input_mask, max_decode_len, pad_id
        )
        # Flat [b*k, ...] layout: beam j of row i lives at i*k + j.  The
        # cross-attention K/V ride inside the tiled cache; flat_encoded
        # is only the decode call's x_kv placeholder from here on (the
        # cached projections are what attention reads), so XLA DCEs it.
        flat_encoded = jnp.repeat(encoded, k, axis=0)
        flat_enc_mask = (
            None if input_mask is None else jnp.repeat(input_mask, k, axis=0)
        )

        def reorder(tree, beam_idx):
            """Permute beam rows ([b, k] indices into the beam axis).

            As a ONE-HOT EINSUM, not take_along_axis: XLA:TPU lowers an
            axis-1 gather with a broadcast index tensor to a generic
            per-element gather — measured 795 ms/step on the beam-4 T5-small
            cache (v5e) vs 1.9 ms for the equivalent one-hot contraction,
            which is a dense [k x k] mix the MXU eats.  Exact because the
            one-hot matrix is a permutation/selection of rows.

            Cross-attention K/V (``cached_enc_*``) are identical across the
            k beams of a row — built by repeating one encoder pass — so
            reordering them is a no-op and they are skipped outright."""
            oh = jax.nn.one_hot(beam_idx, k)               # [b, new, old]

            def leaf(path, x):
                if any("cached_enc" in str(getattr(p, "key", p)) for p in path):
                    return x
                y = x.reshape(b, k, -1)
                # TPU DEFAULT matmul precision rounds f32 *inputs* to bf16;
                # for f32 caches that would requantize K/V every step, so
                # force HIGHEST there (bf16 caches are exact under DEFAULT).
                out = jnp.einsum(
                    "bji,bif->bjf", oh.astype(x.dtype), y,
                    preferred_element_type=x.dtype,
                    precision=(
                        jax.lax.Precision.HIGHEST
                        if x.dtype == jnp.float32 else None
                    ),
                )
                return out.reshape(x.shape)
            return jax.tree_util.tree_map_with_path(leaf, tree)

        vocab = logits0.shape[-1]
        logprobs0 = jax.nn.log_softmax(logits0.astype(jnp.float32))  # [b, V]
        # All beams share the step-0 distribution, so one top-k over the
        # per-row vocab picks the k DISTINCT first tokens directly.
        top0, idx0 = jax.lax.top_k(logprobs0, k)
        tok0 = idx0.astype(jnp.int32)                   # [b, k]
        # Tile the shared step-0 state into the beam layout: self-KV row 0
        # (the BOS K/V) is identical across beams, and the cross-attention
        # K/V were projected once per row instead of once per beam.
        cache = jax.tree_util.tree_map(
            lambda x: jnp.repeat(x, k, axis=0), cache
        )
        logp = top0                                     # [b, k]
        finished = tok0 == eos_id
        lengths = jnp.ones((b, k), jnp.int32)
        tokens = jnp.full((b, k, max_decode_len), pad_id, jnp.int32)
        tokens = tokens.at[:, :, 0].set(tok0)

        neg_inf = jnp.float32(-1e30)
        pad_only = jnp.where(
            jnp.arange(vocab) == pad_id, 0.0, neg_inf
        )[None, None, :]                                # finished: pad, +0

        def step(carry, t):
            cache, tok, logp, lengths, finished, tokens = carry
            cache, logits = _decode_one(
                model, params, cache, tok.reshape(b * k), flat_encoded,
                flat_enc_mask, t, max_decode_len,
            )
            lp = jax.nn.log_softmax(
                logits.astype(jnp.float32)
            ).reshape(b, k, vocab)
            cand = logp[:, :, None] + jnp.where(
                finished[:, :, None], pad_only, lp
            )
            top, idx = jax.lax.top_k(cand.reshape(b, k * vocab), k)
            beam_idx = idx // vocab
            nxt = (idx % vocab).astype(jnp.int32)
            cache = reorder(cache, beam_idx)
            take = lambda a: jnp.take_along_axis(a, beam_idx, axis=1)
            was_finished = take(finished)
            lengths = take(lengths) + jnp.where(was_finished, 0, 1)
            finished = was_finished | (nxt == eos_id)
            # Token history rides the same one-hot permutation as the cache,
            # in INTEGER arithmetic: a float einsum at TPU DEFAULT precision
            # rounds its f32 inputs to bf16, corrupting ids >= 257.  The
            # array is tiny ([b, k, L] int32), so the VPU integer path costs
            # nothing next to the decoder step.
            oh = jax.nn.one_hot(beam_idx, k, dtype=jnp.int32)
            tokens = jnp.einsum("bji,bil->bjl", oh, tokens)
            tokens = tokens.at[:, :, t].set(jnp.where(was_finished, pad_id, nxt))
            return (cache, nxt, top, lengths, finished, tokens), None

        (_, _, logp, lengths, _, tokens), _ = jax.lax.scan(
            step, (cache, tok0, logp, lengths, finished, tokens),
            jnp.arange(1, max_decode_len),
        )
        penalty = ((5.0 + lengths.astype(jnp.float32)) / 6.0) ** length_alpha
        score = logp / penalty                          # [b, k]
        best = jnp.argmax(score, axis=1)
        out = jnp.take_along_axis(
            tokens, best[:, None, None], axis=1
        )[:, 0]
        return out, jnp.take_along_axis(score, best[:, None], axis=1)[:, 0]

    return jax.jit(fn)
