"""T5 encoder-decoder seq2seq (BASELINE config 4: T5-small, JAX run_fn).

The reference's stretch config runs a T5-small seq2seq fine-tune through a
JAX ``run_fn`` (SURVEY.md §0 configs[4]).  Built from the sharded transformer
blocks with the T5 particulars: RMSNorm pre-normalization, bucketed
relative-position attention bias shared across each stack's self-attention
layers, tied input/output embedding scaled by 1/sqrt(d_model) at the logits.

Relative-position bias is an additive [h, q, k] score term, so these
attention calls take the dense path (ring attention covers unbiased
self-attention; see models/transformer.py).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax.sharding import Mesh, PartitionSpec as P

from tpu_pipelines.models.transformer import (
    TRANSFORMER_PARTITION_RULES,
    TransformerBlock,
)


def relative_position_buckets(
    qlen: int, klen: int, *, bidirectional: bool, num_buckets: int = 32,
    max_distance: int = 128,
):
    """T5's log-bucketed relative positions; returns int32 [qlen, klen]."""
    ctx = np.arange(qlen)[:, None]
    mem = np.arange(klen)[None, :]
    rel = mem - ctx
    buckets = np.zeros_like(rel)
    n = num_buckets
    if bidirectional:
        n //= 2
        buckets += (rel > 0).astype(np.int64) * n
        rel = np.abs(rel)
    else:
        rel = -np.minimum(rel, 0)
    max_exact = n // 2
    is_small = rel < max_exact
    large = max_exact + (
        np.log(np.maximum(rel, 1) / max_exact)
        / np.log(max_distance / max_exact)
        * (n - max_exact)
    ).astype(np.int64)
    large = np.minimum(large, n - 1)
    buckets += np.where(is_small, rel, large)
    return jnp.asarray(buckets, jnp.int32)


class RelativePositionBias(nn.Module):
    n_heads: int
    bidirectional: bool
    num_buckets: int = 32
    max_distance: int = 128

    @nn.compact
    def __call__(self, qlen: int, klen: int):
        buckets = relative_position_buckets(
            qlen, klen, bidirectional=self.bidirectional,
            num_buckets=self.num_buckets, max_distance=self.max_distance,
        )
        table = self.param(
            "rel_embedding",
            nn.initializers.normal(stddev=1.0),
            (self.num_buckets, self.n_heads),
        )
        # [q, k, h] -> [1, h, q, k] additive bias
        return jnp.transpose(table[buckets], (2, 0, 1))[None].astype(jnp.float32)


class T5Stack(nn.Module):
    n_layers: int
    n_heads: int
    head_dim: int
    d_ff: int
    dropout_rate: float
    dtype: Any
    causal: bool          # True = decoder
    mesh: Optional[Mesh] = None

    @nn.compact
    def __call__(self, x, *, encoded=None, kv_mask=None, enc_mask=None,
                 deterministic: bool = True):
        bias = RelativePositionBias(
            n_heads=self.n_heads, bidirectional=not self.causal,
            name="rel_pos",
        )(x.shape[1], x.shape[1])
        for i in range(self.n_layers):
            x = TransformerBlock(
                n_heads=self.n_heads, head_dim=self.head_dim, d_ff=self.d_ff,
                dropout_rate=self.dropout_rate, dtype=self.dtype,
                causal=self.causal, prenorm=True, norm="rmsnorm",
                use_cross=self.causal and encoded is not None,
                mesh=self.mesh, name=f"layer_{i}",
            )(
                x, encoded=encoded, kv_mask=kv_mask, enc_mask=enc_mask,
                self_bias=bias, deterministic=deterministic,
            )
        return nn.RMSNorm(dtype=self.dtype, name="final_norm")(x)


class T5(nn.Module):
    """batch {inputs, targets [, input_mask, target_mask]} -> vocab logits.

    ``targets`` are teacher-forcing decoder inputs shifted right internally
    (BOS = 0, the T5 convention).
    """

    vocab_size: int = 32128
    d_model: int = 512
    n_layers: int = 6
    n_heads: int = 8
    head_dim: int = 64
    d_ff: int = 2048
    dropout_rate: float = 0.1
    dtype: Any = jnp.bfloat16
    mesh: Optional[Mesh] = None

    def setup(self):
        self.shared = nn.Embed(
            self.vocab_size, self.d_model, dtype=self.dtype, name="shared"
        )
        common = dict(
            n_heads=self.n_heads, head_dim=self.head_dim, d_ff=self.d_ff,
            dropout_rate=self.dropout_rate, dtype=self.dtype, mesh=self.mesh,
        )
        self.encoder = T5Stack(n_layers=self.n_layers, causal=False,
                               name="encoder", **common)
        self.decoder = T5Stack(n_layers=self.n_layers, causal=True,
                               name="decoder", **common)

    def encode(self, inputs, input_mask=None, *, deterministic=True):
        x = self.shared(jnp.asarray(inputs, jnp.int32))
        return self.encoder(x, kv_mask=input_mask, deterministic=deterministic)

    def decode(self, decoder_input_ids, encoded, *, target_mask=None,
               enc_mask=None, deterministic=True):
        y = self.shared(jnp.asarray(decoder_input_ids, jnp.int32))
        y = self.decoder(
            y, encoded=encoded, kv_mask=target_mask, enc_mask=enc_mask,
            deterministic=deterministic,
        )
        # tied embedding as the output projection, T5's 1/sqrt(d) scaling;
        # logits in float32 for a stable softmax loss
        y = y * (self.d_model ** -0.5)
        return jnp.einsum(
            "bld,vd->blv", y.astype(jnp.float32),
            self.shared.embedding.astype(jnp.float32),
        )

    def __call__(self, batch: Dict[str, Any], *, deterministic: bool = True):
        inputs = jnp.asarray(batch["inputs"], jnp.int32)
        targets = jnp.asarray(batch["targets"], jnp.int32)
        input_mask = batch.get("input_mask")
        decoder_inputs = jnp.pad(targets, ((0, 0), (1, 0)))[:, :-1]
        encoded = self.encode(
            inputs, input_mask, deterministic=deterministic
        )
        return self.decode(
            decoder_inputs, encoded,
            target_mask=batch.get("target_mask"), enc_mask=input_mask,
            deterministic=deterministic,
        )


DEFAULT_HPARAMS = {
    # t5-small geometry
    "vocab_size": 32128,
    "d_model": 512,
    "n_layers": 6,
    "n_heads": 8,
    "head_dim": 64,
    "d_ff": 2048,
    "dropout_rate": 0.1,
    "learning_rate": 1e-3,
    "batch_size": 64,
}


def build_t5_model(hparams: Dict, mesh: Optional[Mesh] = None) -> T5:
    hp = {**DEFAULT_HPARAMS, **(hparams or {})}
    return T5(
        vocab_size=int(hp["vocab_size"]),
        d_model=int(hp["d_model"]),
        n_layers=int(hp["n_layers"]),
        n_heads=int(hp["n_heads"]),
        head_dim=int(hp["head_dim"]),
        d_ff=int(hp["d_ff"]),
        dropout_rate=float(hp["dropout_rate"]),
        mesh=mesh,
    )


def t5_partition_rules():
    return list(TRANSFORMER_PARTITION_RULES) + [
        (r"rel_pos/rel_embedding", P(None, "model")),
    ]
