"""Mesh construction and standard shardings.

The reference's only scaling axis is synchronous data parallelism
(``MultiWorkerMirroredStrategy`` — SURVEY.md §2c); here that is batch-dim
sharding over the mesh's ``data`` axis, with gradient ``psum`` emitted by
XLA.  The mesh carries the full set of named parallelism axes — ``model``
(TP), ``seq`` (ring/ulysses SP), ``expert`` (MoE EP), ``pipe`` (GPipe PP)
— all defaulting to 1, so any combination slots in without reshaping the
design.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical axis names, in fixed order.  data = batch/DP, model = tensor
# parallelism, seq = sequence/context parallelism, expert = MoE expert
# parallelism, pipe = pipeline-stage parallelism.
AXES = ("data", "model", "seq", "expert", "pipe")


@dataclasses.dataclass
class MeshConfig:
    """Declarative mesh shape; unspecified axes default to 1."""

    data: int = -1      # -1 = all remaining devices
    model: int = 1
    seq: int = 1
    expert: int = 1
    pipe: int = 1

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = {"data": self.data, "model": self.model, "seq": self.seq,
                 "expert": self.expert, "pipe": self.pipe}
        fixed = math.prod(v for v in sizes.values() if v > 0)
        free = [k for k, v in sizes.items() if v == -1]
        if len(free) > 1:
            raise ValueError("at most one mesh axis may be -1")
        if free:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {sizes}"
                )
            sizes[free[0]] = n_devices // fixed
        if math.prod(sizes.values()) != n_devices:
            raise ValueError(
                f"mesh {sizes} does not cover {n_devices} devices"
            )
        return sizes


def make_mesh(
    config: Optional[MeshConfig] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh over the given (default: all) devices.

    Uses ``jax.experimental.mesh_utils`` on real TPU so the axis order maps
    onto the physical ICI torus; on CPU/virtual devices a plain reshape.
    """
    devices = list(devices if devices is not None else jax.devices())
    config = config or MeshConfig()
    sizes = config.resolve(len(devices))
    shape = tuple(sizes[a] for a in AXES)
    if devices[0].platform == "tpu":
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    else:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXES)


def data_parallel_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Batch-dim sharding: dim 0 over 'data', rest replicated."""
    return NamedSharding(mesh, P("data", *([None] * (ndim - 1))))


def replicate(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# Key under which shard_batch records row validity when it had to pad a
# short batch to the mesh data axis: float32 [batch], 1.0 = real row,
# 0.0 = zero padding.  Loss/metric code weights by it via masked_mean().
VALID_MASK_KEY = "__valid__"


def shard_batch(batch: Any, mesh: Mesh, *, pad_to_mesh: bool = True) -> Any:
    """Place a host pytree of arrays on the mesh, batch dim over 'data'.

    This is the host→device infeed boundary (SURVEY.md §3.3): one
    ``device_put`` per step; everything after is on-chip.

    A dict batch whose row count does not divide the mesh ``data`` axis —
    the short tail of a ``drop_remainder=False`` epoch, or a window tail —
    is zero-padded up to the next multiple and gains a ``VALID_MASK_KEY``
    float32 row-validity mask (1.0 real, 0.0 padding) so the tail still
    shards evenly instead of erroring; weight per-row losses/metrics with
    :func:`masked_mean` to ignore the padded rows.  Batches that already
    divide take the exact pre-padding path (no mask key, bitwise-identical
    placement), and non-dict pytrees keep the strict divide-or-error
    contract (there is nowhere to attach a mask).
    """
    data_axis = mesh.shape.get("data", 1)
    if (
        pad_to_mesh
        and data_axis > 1
        and isinstance(batch, dict)
        and batch
        and VALID_MASK_KEY not in batch
    ):
        n = len(np.asarray(next(iter(batch.values()))))
        target = pad_to_multiple(n, data_axis)
        if target != n:
            pad = target - n

            def pad_rows(x):
                arr = np.asarray(x)
                return np.concatenate(
                    [arr, np.zeros((pad, *arr.shape[1:]), arr.dtype)]
                )

            batch = {k: pad_rows(v) for k, v in batch.items()}
            batch[VALID_MASK_KEY] = np.concatenate(
                [np.ones(n, np.float32), np.zeros(pad, np.float32)]
            )

    def put(x):
        arr = np.asarray(x)
        return jax.device_put(arr, data_parallel_sharding(mesh, arr.ndim))

    return jax.tree_util.tree_map(put, batch)


def masked_mean(values: Any, mask: Any = None) -> Any:
    """Mean of per-row ``values`` over valid rows.

    ``mask=None`` (the unpadded case) is exactly ``jnp.mean`` — same op,
    bitwise-identical to pre-mask code — so callers can unconditionally
    write ``masked_mean(per_row, batch.get(VALID_MASK_KEY))``.  With a
    mask, padded rows are weighted out of both numerator and denominator;
    ``values`` may carry trailing dims (per-row vectors), the mask
    broadcasts from the batch dim.
    """
    import jax.numpy as jnp

    values = jnp.asarray(values)
    if mask is None:
        return jnp.mean(values)
    mask = jnp.asarray(mask, values.dtype)
    weights = mask.reshape(mask.shape + (1,) * (values.ndim - mask.ndim))
    denom = jnp.sum(mask) * float(np.prod(values.shape[mask.ndim:], dtype=np.int64) or 1)
    return jnp.sum(values * weights) / jnp.maximum(denom, 1.0)


def pad_to_multiple(n: int, k: int) -> int:
    """Smallest multiple of k that is >= n (static batch padding helper)."""
    return ((n + k - 1) // k) * k
