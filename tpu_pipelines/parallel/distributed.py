"""Multi-host bootstrap: the TF_CONFIG / TFJob-operator equivalent.

The reference forms its worker mesh from ``TF_CONFIG`` injected by the
training operator (SURVEY.md §2b TFJob row, §5 comm backend).  The TPU-native
equivalent is JAX's coordination service: every process calls
``jax.distributed.initialize(coordinator, num_processes, process_id)`` and
XLA then sees one global device set; collectives ride ICI within a host's
slice and DCN across hosts — no NCCL, no user-level comms library.

The cluster runner (orchestration/cluster_runner.py) injects the TPP_* env
vars below into each JobSet worker pod; ``maybe_initialize_from_env`` is
called by the node entrypoint before any JAX computation.  Locally, tests
spawn N subprocesses with the same env vars over localhost (gloo CPU
collectives) — multi-host semantics without a cluster (SURVEY.md §4).
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Optional

log = logging.getLogger("tpu_pipelines.distributed")

ENV_COORDINATOR = "TPP_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "TPP_NUM_PROCESSES"
ENV_PROCESS_ID = "TPP_PROCESS_ID"
# JobSet injects the worker index here; used when TPP_PROCESS_ID is absent.
ENV_JOB_COMPLETION_INDEX = "JOB_COMPLETION_INDEX"
DEFAULT_PORT = 8476


@dataclasses.dataclass
class DistributedConfig:
    coordinator_address: str
    num_processes: int
    process_id: int

    @classmethod
    def from_env(cls, env=os.environ) -> Optional["DistributedConfig"]:
        """None when the env describes a single-process run."""
        n = int(env.get(ENV_NUM_PROCESSES, "1"))
        if n <= 1:
            return None
        coordinator = env.get(ENV_COORDINATOR, "")
        if not coordinator:
            raise ValueError(
                f"{ENV_NUM_PROCESSES}={n} but {ENV_COORDINATOR} is unset"
            )
        pid_s = env.get(ENV_PROCESS_ID, env.get(ENV_JOB_COMPLETION_INDEX))
        if pid_s is None:
            raise ValueError(
                f"{ENV_NUM_PROCESSES}={n} but neither {ENV_PROCESS_ID} nor "
                f"{ENV_JOB_COMPLETION_INDEX} is set"
            )
        return cls(coordinator, n, int(pid_s))

    def env_vars(self) -> dict:
        return {
            ENV_COORDINATOR: self.coordinator_address,
            ENV_NUM_PROCESSES: str(self.num_processes),
            ENV_PROCESS_ID: str(self.process_id),
        }


def survivor_configs(
    num_processes: int,
    lost_process_ids,
    coordinator_address: str = "",
) -> list:
    """Re-form the process topology after losing hosts: the elastic-resume
    bootstrap (PERFORMANCE.md "Multi-chip window").

    jax's coordination service cannot shrink in place — the driver
    restarts the job on the survivors with a re-derived topology.  This is
    that derivation: survivors keep their RELATIVE order but are
    re-indexed densely 0..n-1 (process 0 duties — metadata writes,
    TensorBoard — fall to the lowest surviving rank), and the coordinator
    moves to the new process 0's address unless one is passed explicitly.
    Each surviving worker then resumes from the last durable window with
    a per-host shard assignment re-derived from the NEW (index, count)
    (``per_host_input_config`` / ``assigned_shard_files``), so the
    surviving hosts cover the whole dataset again with no overlap.

    Returns ``[(old_process_id, DistributedConfig), ...]`` in new-rank
    order; raises when nothing survives.
    """
    lost = {int(p) for p in lost_process_ids}
    bad = lost - set(range(num_processes))
    if bad:
        raise ValueError(
            f"lost process ids {sorted(bad)} not in 0..{num_processes - 1}"
        )
    survivors = [p for p in range(num_processes) if p not in lost]
    if not survivors:
        raise ValueError(
            f"all {num_processes} processes lost: nothing to re-form"
        )
    return [
        (
            old_id,
            DistributedConfig(
                coordinator_address=coordinator_address,
                num_processes=len(survivors),
                process_id=new_id,
            ),
        )
        for new_id, old_id in enumerate(survivors)
    ]


def local_process_id(env=os.environ) -> int:
    """This host's process id in a multi-host run; 0 for single-process.

    Reads only the TPP_*/JobSet env vars — safe to call from code that must
    not import jax (e.g. the metadata-plane parts of the local runner).
    """
    cfg = DistributedConfig.from_env(env)
    return 0 if cfg is None else cfg.process_id


def maybe_initialize_from_env(
    *, cpu_devices_per_process: int = 0, env=os.environ
) -> Optional[DistributedConfig]:
    """Join the coordination service if the env asks for it; else no-op.

    Must run before any JAX backend is touched.  ``cpu_devices_per_process``
    > 0 switches to the CPU/gloo simulation path (tests, dry runs): each
    process contributes that many virtual CPU devices to the global mesh.
    """
    cfg = DistributedConfig.from_env(env)
    if cfg is None:
        return None
    import jax

    if cpu_devices_per_process:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        jax.config.update("jax_num_cpu_devices", cpu_devices_per_process)
    jax.distributed.initialize(
        coordinator_address=cfg.coordinator_address,
        num_processes=cfg.num_processes,
        process_id=cfg.process_id,
    )
    if jax.process_count() != cfg.num_processes:
        raise RuntimeError(
            f"distributed init: expected {cfg.num_processes} processes, "
            f"backend reports {jax.process_count()}"
        )
    log.info(
        "joined coordination service %s as process %d/%d; %d global devices",
        cfg.coordinator_address, cfg.process_id, cfg.num_processes,
        len(jax.devices()),
    )
    return cfg
