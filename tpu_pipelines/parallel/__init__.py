"""Parallelism layer: device meshes, shardings, distributed bootstrap.

TPU-native replacement for the reference's tf.distribute + NCCL stack
(SURVEY.md §2b/§2c): parallelism is expressed as a ``jax.sharding.Mesh`` plus
``NamedSharding`` annotations; ``jax.jit`` lowers them to XLA collectives over
ICI/DCN.  No user-level collective library exists or is needed.
"""

from tpu_pipelines.parallel.mesh import (  # noqa: F401
    MeshConfig,
    make_mesh,
    shard_batch,
    replicate,
    data_parallel_sharding,
)
