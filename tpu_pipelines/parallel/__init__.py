"""Parallelism layer: device meshes, shardings, distributed bootstrap.

TPU-native replacement for the reference's tf.distribute + NCCL stack
(SURVEY.md §2b/§2c): parallelism is expressed as a ``jax.sharding.Mesh`` plus
``NamedSharding`` annotations; ``jax.jit`` lowers them to XLA collectives over
ICI/DCN.  No user-level collective library exists or is needed.
"""

from tpu_pipelines.parallel.compat import shard_map  # noqa: F401
from tpu_pipelines.parallel.mesh import (  # noqa: F401
    VALID_MASK_KEY,
    MeshConfig,
    data_parallel_sharding,
    make_mesh,
    masked_mean,
    replicate,
    shard_batch,
)
