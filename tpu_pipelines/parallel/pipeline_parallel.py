"""Pipeline parallelism: GPipe-style microbatched stages over mesh ``pipe``.

The fifth parallelism axis (with data/model/seq/expert).  A network of S
identical-signature stages — e.g. groups of transformer layers — runs with
stage s's parameters resident only on pipe-device s; microbatches stream
through the pipeline, each device computing its stage every tick and
handing activations to the next stage with a single ``ppermute`` over ICI.

TPU-first mechanics (the scaling-book recipe):
  - per-stage parameters are STACKED on a leading stage dim and sharded
    ``P("pipe", ...)`` — each device holds 1/S of the model;
  - the schedule is one ``lax.scan`` over M + S - 1 ticks inside
    ``shard_map``; tick t has device s computing microbatch t - s (the
    GPipe fill/steady/drain diagonal), so the whole pipeline is ONE jitted
    computation, differentiable end-to-end (``ppermute`` is linear; its
    transpose is the reverse permute, giving the backward pipeline for
    free);
  - bubble fraction is the usual (S - 1) / (M + S - 1) — callers pick
    ``num_microbatches`` >> S to amortize.

Constraints: every stage must preserve the activation shape/dtype
(transformer blocks do), and the stage function must be identical across
stages (parameters differ, code does not) — the SPMD requirement that
makes one traced program serve every pipe device.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tpu_pipelines.parallel.compat import shard_map

# stage_fn(stage_params, activation [mb, ...]) -> activation [mb, ...]
StageFn = Callable[[Any, jax.Array], jax.Array]


def gpipe(
    stage_fn: StageFn,
    stage_params: Any,
    x: jax.Array,
    *,
    mesh: Mesh,
    num_microbatches: int,
    axis: str = "pipe",
    batch_axis: str = "data",
) -> jax.Array:
    """Apply S pipelined stages to ``x`` as if run sequentially.

    ``stage_params``: pytree whose leaves carry a leading stage dim of size
    S = ``mesh.shape[axis]``, sharded ``P(axis, ...)``.  ``x``: the full
    batch ``[batch, ...]``; it is split into ``num_microbatches`` equal
    microbatches along dim 0.  Returns ``stage_S-1(... stage_0(x))``.

    Call inside ``jit``.  S == 1 degrades to a plain scan over nothing —
    the stage applies once per microbatch with the single param slice.
    """
    s = mesh.shape[axis]
    m = num_microbatches
    b = x.shape[0]
    if b % m:
        raise ValueError(f"batch {b} not divisible by microbatches {m}")
    mb = b // m
    micro = x.reshape(m, mb, *x.shape[1:])

    if s == 1:
        params0 = jax.tree_util.tree_map(lambda p: p[0], stage_params)
        return jax.vmap(lambda xm: stage_fn(params0, xm))(micro).reshape(
            b, *x.shape[1:]
        )

    perm = [(i, i + 1) for i in range(s - 1)]   # non-cyclic shift forward

    def local_fn(params, micro):
        # params: this device's [1, ...] stage slice; micro replicated.
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        idx = jax.lax.axis_index(axis)
        ticks = m + s - 1

        def tick(carry, t):
            act, outs = carry
            # Stage 0 ingests microbatch t during the fill/steady phase
            # (clamped index; the drain-phase value is masked out of the
            # recorded outputs anyway); later stages consume the activation
            # handed to them last tick.
            inj = jax.lax.dynamic_index_in_dim(
                micro, jnp.clip(t, 0, m - 1), keepdims=False
            )
            x_in = jnp.where(idx == 0, inj, act)
            y = stage_fn(params, x_in)
            # The last stage finishes microbatch t - (s - 1) at tick t.
            out_t = jnp.clip(t - (s - 1), 0, m - 1)
            recorded = jax.lax.dynamic_update_index_in_dim(
                outs, y, out_t, 0
            )
            outs = jnp.where((t >= s - 1) & (idx == s - 1), recorded, outs)
            act_next = jax.lax.ppermute(y, axis, perm)
            return (act_next, outs), None

        act0 = jnp.zeros(micro.shape[1:], micro.dtype)
        outs0 = jnp.zeros_like(micro)
        (_, outs), _ = jax.lax.scan(
            tick, (act0, outs0), jnp.arange(ticks)
        )
        # Add a stage axis so out_specs can place each device's buffer;
        # only the last stage's holds real outputs.
        return outs[None]

    stage_spec = jax.tree_util.tree_map(
        lambda p: P(axis, *([None] * (p.ndim - 1))), stage_params
    )
    # Microbatch ROWS shard over `data`, so PP composes with DP: each
    # data-axis column pipelines its own 1/dp slice of every microbatch
    # instead of redundantly recomputing the full batch.  (Requires the
    # microbatch size to divide by the data axis, like any DP batch.)
    dp = mesh.shape.get(batch_axis, 1)
    if mb % dp:
        raise ValueError(
            f"microbatch size {mb} not divisible by mesh axis "
            f"{batch_axis}={dp}"
        )
    micro_spec = P(None, batch_axis)
    stacked = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(stage_spec, micro_spec),
        out_specs=P(axis, None, batch_axis),
        check_vma=False,
    )(stage_params, micro)
    return stacked[-1].reshape(b, *x.shape[1:])
