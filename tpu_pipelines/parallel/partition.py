"""Param-partition rules: regex path → PartitionSpec, applied to a pytree.

The train loop takes ``param_partition`` as a pytree of ``PartitionSpec``
matching the params (trainer/train_loop.py); models ship a rule list
(ordered, first match wins) and this module expands it against the actual
params tree — the moral equivalent of t5x/flaxformer logical-axis rules
without the extra annotation layer.
"""

from __future__ import annotations

import re
from typing import Any, List, Sequence, Tuple

import jax
from jax.sharding import PartitionSpec as P

Rules = Sequence[Tuple[str, P]]


def path_str(path) -> str:
    """'block_0/attn/q/kernel' style path string for a tree_flatten_with_path key."""
    parts = []
    for entry in path:
        for attr in ("key", "idx", "name"):
            if hasattr(entry, attr):
                parts.append(str(getattr(entry, attr)))
                break
        else:
            parts.append(str(entry))
    return "/".join(parts)


def make_param_partition(params: Any, rules: Rules) -> Any:
    """Pytree of PartitionSpec for ``params``; unmatched leaves replicate.

    ``params`` may be real arrays or ``jax.eval_shape`` output.  Each rule is
    ``(regex, PartitionSpec)``, matched with ``re.search`` against the
    '/'-joined path; first match wins.
    """
    compiled = [(re.compile(rx), spec) for rx, spec in rules]

    def spec_for(path, leaf):
        s = path_str(path)
        for rx, spec in compiled:
            if rx.search(s):
                return spec
        return P()

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    return jax.tree_util.tree_unflatten(
        treedef, [spec_for(path, leaf) for path, leaf in flat]
    )


def validate_partition(params: Any, partition: Any, mesh) -> List[str]:
    """Return human-readable problems (axis sizes not dividing dims)."""
    problems = []
    flat_p, _ = jax.tree_util.tree_flatten_with_path(params)
    flat_s = jax.tree_util.tree_leaves(
        partition, is_leaf=lambda x: isinstance(x, P)
    )
    for (path, leaf), spec in zip(flat_p, flat_s):
        shape = getattr(leaf, "shape", ())
        for dim, axis in enumerate(spec):
            if axis is None:
                continue
            axes = (axis,) if isinstance(axis, str) else tuple(axis)
            size = 1
            for a in axes:
                size *= mesh.shape[a]
            if dim >= len(shape) or shape[dim] % size:
                problems.append(
                    f"{path_str(path)}: dim {dim} of {shape} not divisible "
                    f"by mesh axes {axes} (size {size})"
                )
    return problems
