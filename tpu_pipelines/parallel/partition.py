"""Param-partition rules: regex path → PartitionSpec, applied to a pytree.

The train loop takes ``param_partition`` as a pytree of ``PartitionSpec``
matching the params (trainer/train_loop.py); models ship a rule list
(ordered, first match wins) and this module expands it against the actual
params tree — the moral equivalent of t5x/flaxformer logical-axis rules
without the extra annotation layer.
"""

from __future__ import annotations

import re
from typing import Any, List, Sequence, Tuple

import jax
from jax.sharding import PartitionSpec as P

Rules = Sequence[Tuple[str, P]]


def path_str(path) -> str:
    """'block_0/attn/q/kernel' style path string for a tree_flatten_with_path key."""
    parts = []
    for entry in path:
        for attr in ("key", "idx", "name"):
            if hasattr(entry, attr):
                parts.append(str(getattr(entry, attr)))
                break
        else:
            parts.append(str(entry))
    return "/".join(parts)


def make_param_partition(params: Any, rules: Rules) -> Any:
    """Pytree of PartitionSpec for ``params``; unmatched leaves replicate.

    ``params`` may be real arrays or ``jax.eval_shape`` output.  Each rule is
    ``(regex, PartitionSpec)``, matched with ``re.search`` against the
    '/'-joined path; first match wins.
    """
    compiled = [(re.compile(rx), spec) for rx, spec in rules]

    def spec_for(path, leaf):
        s = path_str(path)
        for rx, spec in compiled:
            if rx.search(s):
                return spec
        return P()

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    return jax.tree_util.tree_unflatten(
        treedef, [spec_for(path, leaf) for path, leaf in flat]
    )


def fsdp_param_partition(params: Any, mesh, *, axis: str = "data") -> Any:
    """Derive the default ZeRO-3 partition for ``dp_collective="fsdp"``:
    each leaf sharded over the mesh ``axis`` along its first dimension
    divisible by the axis size; leaves with no divisible dim replicate.

    ``params`` may be real arrays or ``jax.eval_shape`` output.  An
    explicit ``param_partition`` (from model rules) overrides this — the
    train loop only calls it when no rules are configured."""
    n = int(mesh.shape[axis])

    def spec_for(leaf):
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if n > 1:
            for dim, d in enumerate(shape):
                if d >= n and d % n == 0:
                    return P(*([None] * dim), axis)
        return P()

    return jax.tree_util.tree_map(spec_for, params)


def foreign_axis_paths(
    params: Any, partition: Any, *, axis: str = "data"
) -> List[str]:
    """Param paths whose spec names a mesh axis other than ``axis``.

    ``fsdp`` shards params over the data axis only (the gather/scatter
    collectives run inside a shard_map over ``data``); a spec naming
    ``model``/``seq``/... belongs to the implicit-GSPMD path instead, and
    the train loop turns these paths into an actionable error."""
    out: List[str] = []
    flat_p, _ = jax.tree_util.tree_flatten_with_path(params)
    flat_s = jax.tree_util.tree_leaves(
        partition, is_leaf=lambda x: isinstance(x, P)
    )
    for (path, _), spec in zip(flat_p, flat_s):
        for entry in spec:
            if entry is None:
                continue
            names = (entry,) if isinstance(entry, str) else tuple(entry)
            if any(a != axis for a in names):
                out.append(f"{path_str(path)}: {spec}")
                break
    return out


def gather_leaf(x, spec, *, axis: str = "data"):
    """All-gather one param leaf back to full size along the dim ``spec``
    shards over ``axis`` (``tiled=True`` — shards concatenate in place);
    identity for replicated leaves.  Must run inside a ``shard_map`` that
    binds ``axis``.

    This is the fsdp fast-path primitive: each leaf gets its OWN
    ``all_gather`` op, so the compiled scan body carries one collective
    per parameter — distinct ops the scheduler can start while earlier
    layers still compute, exactly like the PR 15 bucketed psums.  Under
    ``jax.value_and_grad`` the AD transpose of a tiled all-gather is
    ``psum_scatter``: the backward pass emits the reduce-scatter gradient
    exchange with no further code."""
    for dim, entry in enumerate(spec):
        if entry is None:
            continue
        names = (entry,) if isinstance(entry, str) else tuple(entry)
        if axis in names:
            return jax.lax.all_gather(x, axis, axis=dim, tiled=True)
    return x


def validate_partition(params: Any, partition: Any, mesh) -> List[str]:
    """Return human-readable problems (axis sizes not dividing dims)."""
    problems = []
    flat_p, _ = jax.tree_util.tree_flatten_with_path(params)
    flat_s = jax.tree_util.tree_leaves(
        partition, is_leaf=lambda x: isinstance(x, P)
    )
    for (path, leaf), spec in zip(flat_p, flat_s):
        shape = getattr(leaf, "shape", ())
        for dim, axis in enumerate(spec):
            if axis is None:
                continue
            axes = (axis,) if isinstance(axis, str) else tuple(axis)
            size = 1
            for a in axes:
                size *= mesh.shape[a]
            if dim >= len(shape) or shape[dim] % size:
                problems.append(
                    f"{path_str(path)}: dim {dim} of {shape} not divisible "
                    f"by mesh axes {axes} (size {size})"
                )
    return problems
