"""Version-portable ``shard_map``: one import site for every jax we support.

The public API moved twice across the jax versions this repo meets in the
wild: ``jax.experimental.shard_map.shard_map(..., check_rep=)`` (<= 0.4.x),
then top-level ``jax.shard_map(..., check_vma=)`` (the replication check was
renamed when it became the varying-manual-axes check).  Every in-repo caller
imports :func:`shard_map` from here and spells the knob ``check_vma`` — the
shim maps it onto whichever spelling the installed jax understands, so the
parallel layer (ring/ulysses attention, pipeline parallelism, the DP
windowed train step) runs unmodified on either side of the rename.
"""

from __future__ import annotations

import inspect
from typing import Any

import jax

try:  # jax >= 0.6: top-level public API
    _native = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map as _native

# The replication-check kwarg kept its meaning but changed its name
# (check_rep -> check_vma); detect which one the installed jax takes.
_PARAMS = set(inspect.signature(_native).parameters)
_CHECK_KW = "check_vma" if "check_vma" in _PARAMS else "check_rep"


def shard_map(
    f, *, mesh, in_specs, out_specs, check_vma: bool = True, **kwargs: Any
):
    """``jax.shard_map`` with the replication-check knob normalized to its
    modern ``check_vma`` spelling regardless of installed jax version."""
    kwargs[_CHECK_KW] = check_vma
    return _native(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )
