"""Sequence/context parallelism over the mesh ``seq`` axis: ring + Ulysses.

Long-context scaling the TPU way (SURVEY.md §5 long-context), two
complementary strategies over the same sharding layout:

  - :func:`ring_attention` — each device holds one Q block and streams K/V
    blocks around the ring with ``ppermute`` over ICI, accumulating softmax
    online (flash-attention style running max/denominator).  Peak memory
    per chip is O(L/n · L/n) score tiles instead of O(L²), and the K/V
    transfer overlaps with the block matmuls — XLA pipelines the
    ``ppermute`` against the einsums.  Scales to sequences that never fit
    one chip; n-1 pipelined hops.

  - :func:`ulysses_attention` — two ``all_to_all`` collectives re-shard
    from sequence-parallel to HEAD-parallel and back: each device then
    holds the FULL sequence for h/n heads and runs plain dense attention
    locally.  Lower latency at moderate sequence lengths (2 collectives vs
    n-1 hops) and exactly reproduces dense attention per head; requires
    local head count divisible by the ``seq`` axis, and per-chip memory is
    O(L²/n) scores — the full-sequence tile, so the ceiling is lower than
    ring's.

No NCCL/MPI equivalents: the collectives are single ``lax.ppermute`` /
``lax.all_to_all`` ops emitted inside ``shard_map``; the same code runs on
the CPU test mesh and a TPU slice.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tpu_pipelines.parallel.compat import shard_map

NEG_INF = -1e30  # finite mask value: exp underflows to 0, no NaN plumbing


def dense_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    kv_mask: Optional[jnp.ndarray] = None,
    bias: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Plain attention. q,k,v: [batch, len, heads, head_dim].

    ``kv_mask``: [batch, kv_len] 1/0 validity (padding) mask.
    ``bias``: additive [*, heads, q_len, kv_len] score term (e.g. T5
    relative positions).
    """
    s = _scores(q, k, causal=causal, kv_mask=kv_mask, bias=bias,
                q_offset=0, kv_offset=0)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v
    ).astype(q.dtype)


def _scores(q, k, *, causal, kv_mask, bias, q_offset, kv_offset):
    """Masked f32 score tensor [b, h, lq, lk]; offsets give global positions
    for causal masking when q/k are blocks of a longer sequence."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if bias is not None:
        s = s + bias
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = kv_offset + jnp.arange(k.shape[1])
        s = jnp.where(qpos[:, None] >= kpos[None, :], s, NEG_INF)
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :] > 0, s, NEG_INF)
    return s


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    mesh: Mesh,
    axis: str = "seq",
    causal: bool = False,
    kv_mask: Optional[jnp.ndarray] = None,
    batch_axis: str = "data",
    head_axis: str = "model",
) -> jnp.ndarray:
    """Sequence-parallel attention over mesh axis ``axis``.

    Global shapes: q,k,v [batch, seq, heads, head_dim], sharded
    batch→``batch_axis``, seq→``axis``, heads→``head_axis``; kv_mask
    [batch, seq].  Equals :func:`dense_attention` on the gathered arrays
    (up to rows whose whole causal∩valid key set is empty — dense softmax
    leaves them uniform, ring leaves them zero).

    Call inside jit; ``shard_map`` partitions per the specs below and the
    per-device function streams K/V blocks with ``ppermute``.
    """
    n = mesh.shape[axis]
    if n == 1:
        return dense_attention(q, k, v, causal=causal, kv_mask=kv_mask)

    blk_len = q.shape[1] // n
    if blk_len * n != q.shape[1]:
        raise ValueError(
            f"seq len {q.shape[1]} not divisible by mesh axis {axis}={n}"
        )
    perm = [(i, (i + 1) % n) for i in range(n)]
    has_mask = kv_mask is not None

    def local_fn(q, k, v, kmask):
        # q,k,v local: [b, blk, h, d]; kmask: [b, blk] or None
        idx = jax.lax.axis_index(axis)

        def body(carry, step):
            o, m, l, k, v, kmask = carry
            kv_blk = (idx - step) % n
            s = _scores(
                q, k, causal=causal, kv_mask=kmask, bias=None,
                q_offset=idx * blk_len, kv_offset=kv_blk * blk_len,
            )                                          # [b, h, lq, lk] f32
            s_max = jnp.max(s, axis=-1)                # [b, h, lq]
            m_new = jnp.maximum(m, s_max)
            corr = jnp.exp(m - m_new)                  # 0 on first real block
            p = jnp.exp(s - m_new[..., None])
            # Zero masked entries even when the whole block is masked
            # (there s == m_new == NEG_INF and the exp above gives 1).
            p = jnp.where(s > NEG_INF * 0.5, p, 0.0)
            l_new = l * corr + jnp.sum(p, axis=-1)
            pv = jnp.einsum(
                "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                preferred_element_type=jnp.float32,
            )
            o_new = o * corr.transpose(0, 2, 1)[..., None] + pv

            # Stream K/V (and padding mask, when present) to the next
            # device; the last block's rotation would only restore the
            # start state, so skip it.  `kmask` may be None — that's an
            # empty pytree, so it rides the carry/cond for free.
            def rotate(args):
                return jax.tree_util.tree_map(
                    lambda a: jax.lax.ppermute(a, axis, perm), args
                )

            k, v, kmask = jax.lax.cond(
                step < n - 1, rotate, lambda args: args, (k, v, kmask)
            )
            return (o_new, m_new, l_new, k, v, kmask), None

        b, lq, h, d = q.shape
        o0 = jnp.zeros((b, lq, h, d), jnp.float32)
        m0 = jnp.full((b, h, lq), NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, h, lq), jnp.float32)
        (o, m, l, *_), _ = jax.lax.scan(
            body, (o0, m0, l0, k, v, kmask), jnp.arange(n)
        )
        denom = l.transpose(0, 2, 1)[..., None]        # [b, lq, h, 1]
        return (o / jnp.maximum(denom, 1e-30)).astype(q.dtype)

    qkv_spec = P(batch_axis, axis, head_axis, None)
    mask_spec = P(batch_axis, axis)
    if has_mask:
        return shard_map(
            local_fn,
            mesh=mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
            out_specs=qkv_spec,
            check_vma=False,
        )(q, k, v, kv_mask)
    return shard_map(
        lambda q, k, v: local_fn(q, k, v, None),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )(q, k, v)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    mesh: Mesh,
    axis: str = "seq",
    causal: bool = False,
    kv_mask: Optional[jnp.ndarray] = None,
    batch_axis: str = "data",
    head_axis: str = "model",
) -> jnp.ndarray:
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism.

    Same global shapes/shardings as :func:`ring_attention`: q,k,v
    [batch, seq, heads, head_dim] sharded batch→``batch_axis``,
    seq→``axis``, heads→``head_axis``; kv_mask [batch, seq].

    Per device: ``all_to_all`` re-shards [b, L/n, h, d] → [b, L, h/n, d]
    (full sequence, a head slice), plain dense attention runs locally —
    bit-for-bit the dense math per head — and a second ``all_to_all``
    restores sequence sharding.  Requires the LOCAL head count (after any
    ``head_axis`` TP split) to divide by the ``seq`` axis size.
    """
    n = mesh.shape[axis]
    if n == 1:
        return dense_attention(q, k, v, causal=causal, kv_mask=kv_mask)
    if q.shape[1] % n:
        raise ValueError(
            f"seq len {q.shape[1]} not divisible by mesh axis {axis}={n}"
        )

    def local_fn(q, k, v, kmask):
        # q,k,v local: [b, L/n, h_local, d]
        if q.shape[2] % n:
            raise ValueError(
                f"local head count {q.shape[2]} not divisible by mesh axis "
                f"{axis}={n} (ulysses re-shards heads across the seq axis; "
                "use ring attention for head counts below the axis size)"
            )
        a2a = lambda x, split, concat: jax.lax.all_to_all(
            x, axis, split_axis=split, concat_axis=concat, tiled=True
        )
        qf = a2a(q, 2, 1)                 # [b, L, h_local/n, d]
        kf = a2a(k, 2, 1)
        vf = a2a(v, 2, 1)
        mask_f = (
            None if kmask is None
            else jax.lax.all_gather(kmask, axis, axis=1, tiled=True)
        )
        out = dense_attention(qf, kf, vf, causal=causal, kv_mask=mask_f)
        return a2a(out, 1, 2)             # back to [b, L/n, h_local, d]

    qkv_spec = P(batch_axis, axis, head_axis, None)
    mask_spec = P(batch_axis, axis)
    if kv_mask is not None:
        return shard_map(
            local_fn,
            mesh=mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
            out_specs=qkv_spec,
            check_vma=False,
        )(q, k, v, kv_mask)
    return shard_map(
        lambda q, k, v: local_fn(q, k, v, None),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )(q, k, v)

def long_context_batch_partition(sample_batch, mesh: Mesh, *, axis: str = "seq",
                                 batch_axis: str = "data"):
    """``TrainLoopConfig.batch_partition`` for a long-context run: shard
    every token-shaped input feature ``[batch, seq, ...]`` over
    ``(batch_axis, axis)`` so each device receives its own sequence slice
    at the infeed boundary and ring/ulysses attention never materialises a
    full-length activation.

    A feature counts as token-shaped when it has a second dimension
    divisible by the ``seq`` axis size; scalars-per-example (labels,
    weights) keep the plain data-parallel layout and are omitted from the
    returned dict (the train loop's default covers them).  Returns ``{}``
    on a mesh whose ``seq`` axis is unpopulated — safe to pass through
    unconditionally.
    """
    n = int(mesh.shape[axis])
    if n <= 1:
        return {}
    out = {}
    for key, v in sample_batch.items():
        shape = tuple(getattr(v, "shape", ()) or ())
        if len(shape) >= 2 and shape[1] >= n and shape[1] % n == 0:
            out[key] = P(batch_axis, axis)
    return out
