"""Flash attention: Pallas TPU kernel for the dense-attention hot path.

Blockwise online-softmax attention (the flash-attention recurrence): the
kernel streams K/V blocks through VMEM against one Q block, carrying the
running max/denominator/accumulator — the [L, L] score matrix never
materializes in HBM, so memory is O(block_q · block_k) instead of O(L²) and
the two matmuls per block land on the MXU back to back.

Scope: forward pass as a kernel; the backward pass recomputes attention with
the standard XLA ops (``jax.custom_vjp`` below) — activation memory still
drops because no O(L²) tensor is saved as a residual, which is where the
flash trick pays on TPU.  Used by models/transformer.py when
``attn_impl="flash"``; ring attention (parallel/ring_attention.py) handles
the sequence-parallel regime and composes the same math across chips.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *, block_k: int,
                  causal: bool, block_q: int, scale: float):
    """One (batch*head, q-block) grid cell: stream all K/V blocks."""
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale            # [bq, d]
    seq_len = k_ref.shape[1]
    n_kv = seq_len // block_k

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(                        # [bq, bk] on the MXU
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # mask is [b*h, 1, l]: the (1, 1, l) block equals the array's last
        # two dims, satisfying TPU tiling, with no dynamic sublane index.
        kmask = mask_ref[0, 0, pl.ds(j * block_k, block_k)]
        s = jnp.where(kmask[None, :] > 0, s, NEG_INF)
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        s_max = jnp.max(s, axis=1)                      # [bq]
        m_new = jnp.maximum(m, s_max)
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(s > NEG_INF * 0.5, p, 0.0)        # fully-masked blocks
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc_new, m_new, l_new

    d = q_ref.shape[-1]
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    if causal:
        # Stop after the KV block containing the last allowed key position,
        # key index (qi+1)*block_q - 1 — blocks past it are fully masked.
        n_used = jnp.minimum(n_kv, ((qi + 1) * block_q - 1) // block_k + 1)
    else:
        n_used = n_kv
    acc, m, l = jax.lax.fori_loop(0, n_used, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _flash_forward(q, k, v, kv_mask, *, causal, block_q, block_k, interpret):
    b, l, h, d = q.shape
    scale = d ** -0.5
    # [b, l, h, d] -> [b*h, l, d]: one grid row per (batch, head)
    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, l, d)

    qf, kf, vf = fold(q), fold(k), fold(v)
    maskf = jnp.repeat(kv_mask, h, axis=0)[:, None, :]  # [b*h, 1, l]

    grid = (b * h, l // block_q)
    out = pl.pallas_call(
        functools.partial(
            _flash_kernel, block_k=block_k, causal=causal,
            block_q=block_q, scale=scale,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, l, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, l, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, 1, l), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, l, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf, maskf)
    return out.reshape(b, h, l, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, kv_mask, causal, block_q, block_k, interpret):
    return _flash_forward(
        q, k, v, kv_mask, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )


def _flash_fwd(q, k, v, kv_mask, causal, block_q, block_k, interpret):
    out = _flash_forward(
        q, k, v, kv_mask, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    return out, (q, k, v, kv_mask)


def _flash_bwd(causal, block_q, block_k, interpret, residuals, g):
    # Recompute-based backward: XLA re-derives attention and differentiates;
    # nothing O(L²) was saved from the forward.
    from tpu_pipelines.parallel.ring_attention import dense_attention

    q, k, v, kv_mask = residuals

    def ref(q, k, v):
        return dense_attention(q, k, v, causal=causal, kv_mask=kv_mask)

    _, vjp = jax.vjp(ref, q, k, v)
    dq, dk, dv = vjp(g)
    # int mask gets a float0 cotangent (JAX's "no gradient" for int inputs)
    import numpy as np

    dmask = np.zeros(kv_mask.shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, dmask


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    kv_mask: Optional[jnp.ndarray] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Self-attention over [batch, len, heads, head_dim] via the kernel.

    Numerically equals ``dense_attention`` (same masking semantics, modulo
    rows whose whole allowed key set is empty: dense leaves them uniform,
    flash leaves them zero).  Falls back to dense when the sequence length
    doesn't tile into (block_q, block_k).  ``interpret=None`` auto-selects
    the Pallas interpreter off-TPU (CPU tests/dry runs).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, l, h, d = q.shape
    block_q = min(block_q, l)
    block_k = min(block_k, l)
    if l % block_q or l % block_k:
        from tpu_pipelines.parallel.ring_attention import dense_attention

        return dense_attention(q, k, v, causal=causal, kv_mask=kv_mask)
    if kv_mask is None:
        kv_mask = jnp.ones((b, l), jnp.int32)
    return _flash(
        q, k, v, jnp.asarray(kv_mask, jnp.int32), causal, block_q, block_k,
        interpret,
    )
