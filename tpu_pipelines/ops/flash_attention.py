"""Flash attention: Pallas TPU kernels for the dense-attention hot path.

Blockwise online-softmax attention (the flash-attention recurrence), forward
AND backward as Pallas kernels:

  - forward: grid (batch*heads, q-blocks, kv-blocks) streams K/V blocks from
    HBM through VMEM against one resident Q block, carrying the running
    max/denominator/accumulator in VMEM scratch across the sequential kv grid
    dimension — the [L, L] score matrix never materializes and VMEM holds
    O(block_q · block_k) regardless of L.  The forward also emits the
    per-row logsumexp (LSE) used by the backward.
  - backward: two kernels recompute scores blockwise from the saved
    (q, k, v, lse) — dQ over grid (bh, q-blocks, kv-blocks), dK/dV over
    grid (bh, kv-blocks, q-blocks) — so the backward is O(block²) memory
    too; nothing O(L²) is ever saved or rebuilt (the round-1 version
    recomputed a dense [b,h,L,L] attention inside the VJP).

Both matmuls per block land on the MXU back to back; row statistics are kept
as (block_q, 128) lane-replicated tiles to satisfy TPU tiling.  Used by
models/transformer.py when ``attn_impl="flash"``; ring attention
(parallel/ring_attention.py) handles the sequence-parallel regime and
composes the same math across chips.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30
LANES = 128


def _block_mask(kmask, qi, kj, block_q, block_k, causal):
    """[bq, bk] bool: allowed (key-visible and causal-visible) positions."""
    allowed = jnp.broadcast_to(kmask[None, :] > 0, (block_q, block_k))
    if causal:
        qpos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        kpos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        allowed = jnp.logical_and(allowed, qpos >= kpos)
    return allowed


def _causal_live(qi, kj, block_q, block_k):
    """False iff the whole KV block sits strictly above the causal diagonal."""
    return kj * block_k <= qi * block_q + block_q - 1


# --------------------------------------------------------------------- fwd

def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, causal, block_q, block_k, scale):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    live = _causal_live(qi, kj, block_q, block_k) if causal else True

    @pl.when(live)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale            # [bq, d]
        k = k_ref[0].astype(jnp.float32)                    # [bk, d]
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(                            # [bq, bk] on MXU
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        allowed = _block_mask(mask_ref[0, 0], qi, kj, block_q, block_k, causal)
        s = jnp.where(allowed, s, NEG_INF)
        m_prev = m_ref[:, 0:1]                              # [bq, 1]
        s_max = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, s_max)
        p = jnp.where(allowed, jnp.exp(s - m_new), 0.0)     # [bq, bk]
        corr = jnp.exp(m_prev - m_new)                      # [bq, 1]
        l_new = l_ref[:, 0:1] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kj == n_kv - 1)
    def _final():
        l_fin = l_ref[:, 0:1]
        o_ref[0] = (acc_ref[...] / jnp.maximum(l_fin, 1e-30)).astype(o_ref.dtype)
        # Rows with an empty allowed key set keep lse = NEG_INF-ish; the
        # backward's `allowed` guard zeroes them regardless.  lse is laid out
        # [bh, L, 1] (TPU block tiling wants the block's trailing dims to
        # divide (8, 128) or equal the array's).
        lse_ref[0] = m_ref[:, 0:1] + jnp.log(jnp.maximum(l_ref[:, 0:1], 1e-30))


def _flash_forward(q, k, v, kv_mask, *, causal, block_q, block_k, interpret):
    """Returns (out [b,l,h,d], lse [b*h, l]) from folded blockwise kernels."""
    from jax.experimental.pallas import tpu as pltpu

    b, l, h, d = q.shape
    scale = d ** -0.5

    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, l, d)

    qf, kf, vf = fold(q), fold(k), fold(v)
    maskf = jnp.repeat(kv_mask, h, axis=0)[:, None, :]      # [b*h, 1, l]

    grid = (b * h, l // block_q, l // block_k)
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, causal=causal, block_q=block_q, block_k=block_k,
            scale=scale,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, 1, block_k), lambda bh, i, j: (bh, 0, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, i, j: (bh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, l, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, l, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, maskf)
    return out.reshape(b, h, l, d).transpose(0, 2, 1, 3), lse


# --------------------------------------------------------------------- bwd

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dvec_ref, mask_ref,
               dq_ref, acc_ref, *, causal, block_q, block_k, scale):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    live = _causal_live(qi, kj, block_q, block_k) if causal else True

    @pl.when(live)
    def _step():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        allowed = _block_mask(mask_ref[0, 0], qi, kj, block_q, block_k, causal)
        p = jnp.where(allowed, jnp.exp(s - lse_ref[0]), 0.0)
        dp = jax.lax.dot_general(                            # dO V^T [bq, bk]
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - dvec_ref[0])                          # [bq, bk]
        acc_ref[...] += scale * jax.lax.dot_general(         # dS K [bq, d]
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kj == n_kv - 1)
    def _final():
        dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dvec_ref, mask_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, causal, block_q, block_k,
                scale):
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    live = _causal_live(qi, kj, block_q, block_k) if causal else True

    @pl.when(live)
    def _step():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        allowed = _block_mask(mask_ref[0, 0], qi, kj, block_q, block_k, causal)
        p = jnp.where(allowed, jnp.exp(s - lse_ref[0]), 0.0)
        dv_acc[...] += jax.lax.dot_general(                  # P^T dO [bk, d]
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - dvec_ref[0])
        dk_acc[...] += scale * jax.lax.dot_general(          # dS^T Q [bk, d]
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == n_q - 1)
    def _final():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_backward(q, k, v, kv_mask, o, lse, g, *, causal, block_q, block_k,
                    interpret):
    from jax.experimental.pallas import tpu as pltpu

    b, l, h, d = q.shape
    scale = d ** -0.5

    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, l, d)

    qf, kf, vf, of, gf = fold(q), fold(k), fold(v), fold(o), fold(g)
    maskf = jnp.repeat(kv_mask, h, axis=0)[:, None, :]
    # D_i = rowsum(dO · O): the softmax-jacobian correction term.
    # [bh, L, 1] column layout, matching lse (see _fwd_kernel final note).
    dvec = jnp.sum(
        gf.astype(jnp.float32) * of.astype(jnp.float32), axis=-1, keepdims=True
    )

    qkv_spec = lambda which: {
        "q": pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
        "k": pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
    }[which]
    row_spec = pl.BlockSpec((1, block_q, 1), lambda bh, i, j: (bh, i, 0))
    mask_spec = pl.BlockSpec((1, 1, block_k), lambda bh, i, j: (bh, 0, j))

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, causal=causal, block_q=block_q, block_k=block_k,
            scale=scale,
        ),
        grid=(b * h, l // block_q, l // block_k),
        in_specs=[
            qkv_spec("q"), qkv_spec("k"), qkv_spec("k"), qkv_spec("q"),
            row_spec, row_spec, mask_spec,
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, l, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, gf, lse, dvec, maskf)

    # dK/dV: kv blocks own the (sequential) second grid dim, q streams third.
    q_spec = pl.BlockSpec((1, block_q, d), lambda bh, j, i: (bh, i, 0))
    kv_spec = pl.BlockSpec((1, block_k, d), lambda bh, j, i: (bh, j, 0))
    row_spec2 = pl.BlockSpec((1, block_q, 1), lambda bh, j, i: (bh, i, 0))
    mask_spec2 = pl.BlockSpec((1, 1, block_k), lambda bh, j, i: (bh, 0, j))
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, causal=causal, block_q=block_q, block_k=block_k,
            scale=scale,
        ),
        grid=(b * h, l // block_k, l // block_q),
        in_specs=[
            q_spec, kv_spec, kv_spec, q_spec, row_spec2, row_spec2, mask_spec2,
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, j, i: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, j, i: (bh, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, l, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, l, d), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, gf, lse, dvec, maskf)

    def unfold(x):
        return x.reshape(b, h, l, d).transpose(0, 2, 1, 3)

    return unfold(dq), unfold(dk), unfold(dv)


# ----------------------------------------------------------------- decode

# The single query row is replicated to a full sublane tile so the [q, d]
# operand satisfies TPU tiling; all rows compute identical values and row 0
# is returned.  The waste is on the tiny q dimension only — the decode
# regime is bandwidth-bound on streaming the KV cache, which this kernel
# reads exactly once (that is the point; real flash-decode does the same).
_DECODE_QROWS = {4: 8, 2: 16, 1: 32}


def _decode_kernel(q_ref, k_ref, v_ref, mask_ref, bias_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, block_k, scale):
    kj = pl.program_id(1)
    n_kv = pl.num_programs(1)

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[0].astype(jnp.float32) * scale            # [qrows, d]
    k = k_ref[0].astype(jnp.float32)                    # [bk, d]
    v = v_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(                            # [qrows, bk] on MXU
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    s = s + bias_ref[0]                                 # [1, bk] broadcast
    allowed = mask_ref[0, 0] > 0                        # [bk]
    s = jnp.where(allowed[None, :], s, NEG_INF)
    m_prev = m_ref[:, 0:1]
    s_max = jnp.max(s, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, s_max)
    p = jnp.where(allowed[None, :], jnp.exp(s - m_new), 0.0)
    corr = jnp.exp(m_prev - m_new)
    l_new = l_ref[:, 0:1] * corr + jnp.sum(p, axis=1, keepdims=True)
    acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kj == n_kv - 1)
    def _final():
        o_ref[0] = (
            acc_ref[...] / jnp.maximum(l_ref[:, 0:1], 1e-30)
        ).astype(o_ref.dtype)


def flash_decode_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    kv_mask: Optional[jnp.ndarray] = None,
    bias: Optional[jnp.ndarray] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Single-query attention against a KV cache (the decode regime).

    ``q``: [batch, 1, heads, head_dim] — this step's one token per row.
    ``k``/``v``: [batch, kv_len, heads, head_dim] — the (padded) cache.
    ``kv_mask``: [batch, kv_len] validity (<= each row's decode position).
    ``bias``: additive [1|batch, heads, 1, kv_len] score term (T5
    relative positions); broadcast over batch when its leading dim is 1.

    One grid step per KV block streams the cache through VMEM exactly
    once with the online-softmax recurrence — no [1, L] score tensor in
    HBM and no O(L) repacking per decode step.  Inference-only (no VJP:
    nothing differentiates through serving decode).  ``block_k`` defaults
    to the autotune table's ``flash_decode`` entry for this shape
    (``TPP_AUTOTUNE`` semantics identical to ``flash_attention``), then
    to the hard-coded default.
    """
    from jax.experimental.pallas import tpu as pltpu

    from tpu_pipelines.ops import autotune

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, l, h, d = k.shape
    itemsize = jnp.dtype(q.dtype).itemsize
    concrete = not isinstance(q, jax.core.Tracer)
    if block_k is None:
        cfg = autotune.get_block_config(
            "flash_decode", b, h, l, d, q.dtype, False,
            interpret=interpret, allow_sweep=concrete,
        )
        if cfg is not None:
            block_k = cfg[1]
    block_k = autotune.DEFAULT_BLOCK_K if block_k is None else block_k
    block_k = autotune.clamp_block(l, block_k, itemsize, "block_k")
    qrows = _DECODE_QROWS.get(int(itemsize), 8)
    scale = d ** -0.5

    qf = jnp.broadcast_to(
        q[:, 0].reshape(b * h, 1, d), (b * h, qrows, d)
    )
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, l, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, l, d)
    if kv_mask is None:
        kv_mask = jnp.ones((b, l), jnp.int32)
    maskf = jnp.repeat(jnp.asarray(kv_mask, jnp.int32), h, axis=0)[:, None, :]
    if bias is None:
        biasf = jnp.zeros((b * h, 1, l), jnp.float32)
    else:
        biasf = jnp.broadcast_to(
            bias.astype(jnp.float32)[:, :, 0, :], (b, h, l)
        ).reshape(b * h, 1, l)

    out = pl.pallas_call(
        functools.partial(_decode_kernel, block_k=block_k, scale=scale),
        grid=(b * h, l // block_k),
        in_specs=[
            pl.BlockSpec((1, qrows, d), lambda bh, j: (bh, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, 1, block_k), lambda bh, j: (bh, 0, j)),
            pl.BlockSpec((1, 1, block_k), lambda bh, j: (bh, 0, j)),
        ],
        out_specs=pl.BlockSpec((1, qrows, d), lambda bh, j: (bh, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, qrows, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((qrows, d), jnp.float32),
            pltpu.VMEM((qrows, LANES), jnp.float32),
            pltpu.VMEM((qrows, LANES), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, maskf, biasf)
    return out[:, 0].reshape(b, h, d)[:, None]


# ------------------------------------------------------------------ custom_vjp

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash(q, k, v, kv_mask, causal, block_q, block_k, bwd_block_q,
           bwd_block_k, interpret):
    out, _ = _flash_forward(
        q, k, v, kv_mask, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    return out


def _flash_fwd(q, k, v, kv_mask, causal, block_q, block_k, bwd_block_q,
               bwd_block_k, interpret):
    out, lse = _flash_forward(
        q, k, v, kv_mask, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    return out, (q, k, v, kv_mask, out, lse)


def _flash_bwd(causal, block_q, block_k, bwd_block_q, bwd_block_k, interpret,
               residuals, g):
    q, k, v, kv_mask, o, lse = residuals
    dq, dk, dv = _flash_backward(
        q, k, v, kv_mask, o, lse, g, causal=causal, block_q=bwd_block_q,
        block_k=bwd_block_k, interpret=interpret,
    )
    # int mask gets a float0 cotangent (JAX's "no gradient" for int inputs)
    import numpy as np

    dmask = np.zeros(kv_mask.shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, dmask


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    kv_mask: Optional[jnp.ndarray] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    bwd_block_q: Optional[int] = None,
    bwd_block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Self-attention over [batch, len, heads, head_dim] via the kernels.

    Numerically equals ``dense_attention`` (same masking semantics, modulo
    rows whose whole allowed key set is empty: dense leaves them uniform,
    flash leaves them zero).  ``interpret=None`` auto-selects the Pallas
    interpreter off-TPU (CPU tests/dry runs).

    Block selection (ops/autotune.py): explicit ``block_q=``/``block_k=``
    (and ``bwd_block_q=``/``bwd_block_k=`` for the backward kernels, which
    tune independently) always win; otherwise the autotune table is
    consulted for this (shape, dtype, causal, device) on first trace, and
    the hard-coded defaults (128/128) apply on a miss.  ``TPP_AUTOTUNE``
    controls table behavior — cache-only by default, so jit tracing never
    times anything inside a trace.

    Every block is validated up front and auto-clamped to the largest
    L-divisible, TPU-tileable size <= the requested one (the kernels' grid
    is ``l // block``; an indivisible block used to mis-tile with an
    opaque Mosaic error).  A clear ``ValueError`` lists the valid choices
    when nothing <= the request works.
    """
    from tpu_pipelines.ops import autotune

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, l, h, d = q.shape
    itemsize = jnp.dtype(q.dtype).itemsize
    # Timing inside a jit trace would hang the trace on real device work:
    # sweeps only ever run from concrete call sites.
    concrete = not isinstance(q, jax.core.Tracer)

    def tuned(op):
        return autotune.get_block_config(
            op, b, h, l, d, q.dtype, causal,
            interpret=interpret, allow_sweep=concrete,
        )

    explicit = block_q is not None or block_k is not None
    if not explicit:
        cfg = tuned("flash_fwd")
        if cfg is not None:
            block_q, block_k = cfg
    block_q = autotune.DEFAULT_BLOCK_Q if block_q is None else block_q
    block_k = autotune.DEFAULT_BLOCK_K if block_k is None else block_k
    if not explicit and bwd_block_q is None and bwd_block_k is None:
        cfg = tuned("flash_bwd")
        if cfg is not None:
            bwd_block_q, bwd_block_k = cfg
    bwd_block_q = block_q if bwd_block_q is None else bwd_block_q
    bwd_block_k = block_k if bwd_block_k is None else bwd_block_k

    block_q = autotune.clamp_block(l, block_q, itemsize, "block_q")
    block_k = autotune.clamp_block(l, block_k, itemsize, "block_k")
    bwd_block_q = autotune.clamp_block(l, bwd_block_q, itemsize, "bwd_block_q")
    bwd_block_k = autotune.clamp_block(l, bwd_block_k, itemsize, "bwd_block_k")
    if kv_mask is None:
        kv_mask = jnp.ones((b, l), jnp.int32)
    return _flash(
        q, k, v, jnp.asarray(kv_mask, jnp.int32), causal, block_q, block_k,
        bwd_block_q, bwd_block_k, interpret,
    )
