"""Pallas TPU kernels for hot ops (SURVEY.md §7: the compute path).

XLA fuses most of the framework's elementwise/matmul work on its own; the
kernels here cover the cases where hand-tiling beats the compiler —
flash attention keeps the O(L²) score matrix out of HBM entirely by
accumulating the softmax online in VMEM.  Block sizes are not guessed:
``autotune.py`` sweeps candidate tilings per (shape, dtype, device),
persists winners in an on-disk + repo-committed table, and records the
measured flash-vs-dense crossover that ``attn_impl="auto"`` consults.
"""

from tpu_pipelines.ops.flash_attention import flash_attention  # noqa: F401
