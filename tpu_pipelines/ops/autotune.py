"""Kernel autotuning: measured block configs + the flash/dense crossover.

BENCH_R5's ``flash_probe`` showed the Pallas flash kernel *losing* to dense
attention at the workhorse shape (b=8 h=12 L=2048: 28.0 ms vs 24.6 ms)
because ``flash_attention``'s hard-coded ``block_q=128``/``block_k=128``
were never tuned per shape or device — and ``attn_impl="auto"`` picked
flash-vs-dense on memory feasibility alone, never consulting a
measurement.  This module closes both gaps:

  * per ``(op, shape-bucket, dtype, causal, device_kind)`` key, sweep a
    candidate grid of ``(block_q, block_k)`` configurations (constrained
    to TPU-valid tilings and L-divisibility; forward and backward tuned
    independently — their arithmetic-intensity profiles differ), time
    them with dispatch-overhead amortization (compile once, chain
    iterations through the device, one host read at the end), and
    persist the winner in an on-disk table;
  * per ``device_kind``, store the measured flash-vs-dense *crossover*
    sequence length, which upgrades ``attn_impl="auto"`` (see
    ``models/transformer.py choose_attn_impl``) from memory-fit-only to
    a measurement: dense below the crossover, flash at/above it, with
    ``dense_attn_fits`` demoted to the OOM guard it always really was.

Storage (multi-process safe — PR 7's ``atomic_write_json`` under a
``FileLock``, tolerant reads via ``load_json_tolerant``; keys via PR 6's
canonical ``fingerprint_json`` so two fresh processes derive the SAME key
for the same shape):

  * user cache:  ``~/.cache/tpu_pipelines/autotune/<device_kind>.json``
    (``TPP_AUTOTUNE_CACHE`` overrides the directory), written by sweeps;
  * committed table: ``tpu_pipelines/ops/autotune_table.json`` — winners
    promoted into the repo so fresh checkouts start tuned (commit
    workflow in PERFORMANCE.md §"Attention crossover").  User-cache
    entries shadow committed ones.

``TPP_AUTOTUNE`` controls behavior:

  * ``cache-only`` (default) — consult the table, NEVER time anything.
    ``flash_attention`` is consulted at jit-trace time, and timing inside
    a trace would hang the trace on real work; cache-only makes the
    trace-time path a pure dict lookup.
  * ``sweep`` — on a table miss (and only outside a trace), run the sweep
    and persist the winner.
  * ``0`` / ``off`` — bypass the table entirely (hard-coded defaults).

Cache traffic is counted in the PR 5 metrics registry:
``autotune_cache_hits_total`` / ``autotune_cache_misses_total`` /
``autotune_sweeps_total`` (all labeled by op) and the
``autotune_sweep_latency_seconds`` histogram.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from tpu_pipelines.robustness.atomic import (
    FileLock,
    atomic_write_json,
    load_json_tolerant,
)
from tpu_pipelines.utils.fingerprint import fingerprint_json

ENV_MODE = "TPP_AUTOTUNE"
ENV_CACHE_DIR = "TPP_AUTOTUNE_CACHE"
ENV_BLOCKS = "TPP_AUTOTUNE_BLOCKS"      # "128x128,256x256" candidate override
ENV_ITERS = "TPP_AUTOTUNE_ITERS"

MODE_OFF = "off"
MODE_CACHE_ONLY = "cache-only"
MODE_SWEEP = "sweep"

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128

# Candidate block edges (before L-divisibility / tiling / VMEM filters).
# 64 is below one MXU tile but wins at short L where fewer, fuller grid
# steps beat pipeline depth; 512 amortizes per-block overhead at long L.
_CANDIDATE_EDGES = (64, 128, 256, 512)

# VMEM working-set budget for a candidate: the fwd kernel holds one Q
# block, one K and one V block, the [bq, bk] score tile and the f32
# accumulator/rowstat scratch.  16 MB/core on current TPUs; leave half
# for the compiler's own double-buffering.
_VMEM_BUDGET_BYTES = 8 * 1024 * 1024

_TABLE_VERSION = 1
_COMMITTED_TABLE = os.path.join(os.path.dirname(__file__), "autotune_table.json")

# Minimum second-to-last-dim tile per dtype (pallas_guide.md): f32 tiles
# (8, 128), bf16 (16, 128), int8/fp8 (32, 128).
_MIN_SUBLANE = {2: 16, 4: 8, 1: 32}


def _min_sublane(itemsize: int) -> int:
    return _MIN_SUBLANE.get(int(itemsize), 8)


# ------------------------------------------------------------------- keys


def autotune_mode() -> str:
    """Effective mode from ``TPP_AUTOTUNE`` (unset => cache-only)."""
    raw = os.environ.get(ENV_MODE, MODE_CACHE_ONLY).strip().lower()
    if raw in ("0", "off", "false", "none"):
        return MODE_OFF
    if raw == MODE_SWEEP:
        return MODE_SWEEP
    return MODE_CACHE_ONLY


def current_device_kind() -> str:
    """The accelerator kind tables are keyed by ("TPU v5 lite", "cpu"...)."""
    try:
        import jax

        return str(jax.devices()[0].device_kind)
    except Exception:
        return "unknown"


def _next_pow2(n: int) -> int:
    n = max(1, int(n))
    return 1 << (n - 1).bit_length()


def make_key(
    op: str,
    batch: int,
    heads: int,
    seq_len: int,
    head_dim: int,
    dtype: str,
    causal: bool,
    device_kind: Optional[str] = None,
) -> Dict[str, Any]:
    """Canonical lookup key for one tuned kernel instance.

    ``batch*heads`` is bucketed to the next power of two: it only sets the
    embarrassingly-parallel first grid dimension, so nearby sizes share a
    winner — while ``seq_len`` stays exact because block validity
    (L-divisibility) and the compute/bandwidth balance both hinge on it.
    """
    return {
        "op": str(op),
        "bh_bucket": _next_pow2(batch * heads),
        "seq_len": int(seq_len),
        "head_dim": int(head_dim),
        "dtype": str(dtype),
        "causal": bool(causal),
        "device_kind": device_kind or current_device_kind(),
    }


def key_id(key: Dict[str, Any]) -> str:
    """Process-stable table key — PR 6's canonical JSON encoding hashed,
    so two fresh interpreters derive byte-identical ids for one shape."""
    return fingerprint_json(key)[:16]


# ------------------------------------------------------------------ tables


def cache_dir() -> str:
    env = os.environ.get(ENV_CACHE_DIR)
    if env:
        return env
    return os.path.join(
        os.path.expanduser("~"), ".cache", "tpu_pipelines", "autotune"
    )


def cache_path(device_kind: Optional[str] = None) -> str:
    kind = (device_kind or current_device_kind()).replace(" ", "_")
    return os.path.join(cache_dir(), f"{kind}.json")


_table_memo: Dict[str, Tuple[Tuple[float, int], Dict[str, Any]]] = {}


def _load_table(path: str) -> Dict[str, Any]:
    """Tolerant, mtime-memoized table read ({} for absent/corrupt/torn —
    a damaged cache must never take down a training run)."""
    try:
        st = os.stat(path)
        stamp = (st.st_mtime, st.st_size)
    except OSError:
        return {}
    memo = _table_memo.get(path)
    if memo is not None and memo[0] == stamp:
        return memo[1]
    data = load_json_tolerant(path)
    if not isinstance(data, dict):
        data = {}
    _table_memo[path] = (stamp, data)
    return data


def clear_memo() -> None:
    """Drop in-process table memos (tests repoint the cache dir)."""
    _table_memo.clear()


def _lookup_entry(
    kid: str, device_kind: Optional[str] = None
) -> Optional[Dict[str, Any]]:
    """User cache first (freshly swept winners shadow the committed table),
    then the repo-committed table."""
    for path in (cache_path(device_kind), _COMMITTED_TABLE):
        entry = (_load_table(path).get("entries") or {}).get(kid)
        if isinstance(entry, dict):
            return entry
    return None


def _update_table(path: str, mutate) -> None:
    """Read-modify-write under the cross-process lock, atomically."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with FileLock(path + ".lock"):
        table = load_json_tolerant(path)
        if not isinstance(table, dict):
            table = {}
        table.setdefault("version", _TABLE_VERSION)
        table.setdefault("entries", {})
        table.setdefault("crossover", {})
        mutate(table)
        atomic_write_json(path, table)
    _table_memo.pop(path, None)


def record_entry(
    key: Dict[str, Any],
    block_q: int,
    block_k: int,
    ms: float,
    swept: Optional[Sequence[Dict[str, Any]]] = None,
    source: str = "sweep",
) -> str:
    """Persist one winner into the user cache; returns its table id."""
    kid = key_id(key)

    def mutate(table):
        table["entries"][kid] = {
            "key": key,
            "block_q": int(block_q),
            "block_k": int(block_k),
            "ms": round(float(ms), 4),
            "swept": list(swept or []),
            "source": source,
        }

    _update_table(cache_path(key.get("device_kind")), mutate)
    return kid


# -------------------------------------------------------------- crossover


def record_crossover(
    device_kind: str,
    crossover_seq_len: Optional[int],
    geometry: Optional[Dict[str, Any]] = None,
    source: str = "measured",
) -> None:
    """Store the measured flash-vs-dense crossover for one device kind.

    ``None`` means "dense won at every measured length where it fits" —
    recorded explicitly so ``auto`` can distinguish *measured-no-crossover*
    from *never measured*.
    """

    def mutate(table):
        table["crossover"][device_kind] = {
            "crossover_seq_len": (
                int(crossover_seq_len)
                if crossover_seq_len is not None else None
            ),
            "geometry": geometry or {},
            "source": source,
        }

    _update_table(cache_path(device_kind), mutate)


def lookup_crossover(device_kind: Optional[str] = None) -> Optional[int]:
    """Measured crossover seq length for this device, or None when no
    measurement exists (or dense won everywhere measured)."""
    kind = device_kind or current_device_kind()
    for path in (cache_path(kind), _COMMITTED_TABLE):
        rec = (_load_table(path).get("crossover") or {}).get(kind)
        if isinstance(rec, dict):
            v = rec.get("crossover_seq_len")
            return int(v) if v is not None else None
    return None


def record_decode_crossover(
    device_kind: str,
    crossover_kv_len: Optional[int],
    geometry: Optional[Dict[str, Any]] = None,
    source: str = "measured",
) -> None:
    """Store the measured flash-decode-vs-dense crossover CACHE length.

    The decode regime (single-query attention against the KV cache during
    autoregressive generation) is bandwidth-bound on streaming the cache,
    a different balance from the training shapes — so it carries its own
    crossover, recorded by the bench ``t5_decode`` leg and consulted by
    ``models/transformer.py choose_decode_impl``.  ``None`` means "dense
    won at every measured cache length" (measured-no-crossover, distinct
    from never-measured)."""

    def mutate(table):
        table.setdefault("decode_crossover", {})[device_kind] = {
            "crossover_kv_len": (
                int(crossover_kv_len)
                if crossover_kv_len is not None else None
            ),
            "geometry": geometry or {},
            "source": source,
        }

    _update_table(cache_path(device_kind), mutate)


def lookup_decode_crossover(device_kind: Optional[str] = None) -> Optional[int]:
    """Measured decode-regime crossover KV length for this device, or
    None when no measurement exists (or dense won everywhere measured)."""
    kind = device_kind or current_device_kind()
    for path in (cache_path(kind), _COMMITTED_TABLE):
        rec = (_load_table(path).get("decode_crossover") or {}).get(kind)
        if isinstance(rec, dict):
            v = rec.get("crossover_kv_len")
            return int(v) if v is not None else None
    return None


def committed_crossovers() -> Dict[str, int]:
    """device_kind -> crossover from the REPO-COMMITTED table only (what
    the TPP208 lint rule consults: reviewable, versioned evidence)."""
    out: Dict[str, int] = {}
    for kind, rec in (_load_table(_COMMITTED_TABLE).get("crossover") or {}).items():
        if isinstance(rec, dict) and rec.get("crossover_seq_len") is not None:
            out[str(kind)] = int(rec["crossover_seq_len"])
    return out


# ----------------------------------------------------------------- metrics


def _metrics():
    from tpu_pipelines.observability.metrics import default_registry

    reg = default_registry()
    return (
        reg.counter(
            "autotune_cache_hits_total",
            "Autotune table lookups answered from cache", ("op",),
        ),
        reg.counter(
            "autotune_cache_misses_total",
            "Autotune table lookups with no stored winner", ("op",),
        ),
        reg.counter(
            "autotune_sweeps_total",
            "Candidate-grid sweeps executed (timed on device)", ("op",),
        ),
        reg.histogram(
            "autotune_sweep_latency_seconds",
            "Wall-clock cost of one candidate-grid sweep", ("op",),
        ),
    )


# -------------------------------------------------------------- candidates


def valid_blocks(seq_len: int, itemsize: int) -> List[int]:
    """Block sizes a [seq_len] axis can tile into on TPU: must divide L
    (the kernels' grid is ``L // block``) and be a multiple of the dtype's
    minimum sublane tile — or be L itself (a single whole-axis block is
    always exactly the array's own shape)."""
    sub = _min_sublane(itemsize)
    out = [
        c for c in _CANDIDATE_EDGES
        if c <= seq_len and seq_len % c == 0 and c % sub == 0
    ]
    if seq_len not in out and seq_len <= max(_CANDIDATE_EDGES):
        out.append(seq_len)
    return sorted(set(out))


def clamp_block(
    seq_len: int, requested: int, itemsize: int, what: str = "block"
) -> int:
    """Largest valid block <= ``requested`` for this axis.

    ``flash_attention`` used to require ``L % block == 0`` implicitly (the
    grid was ``l // block``) and mis-tiled opaquely otherwise; this
    validates up front.  Raises with the valid choices listed when nothing
    <= ``requested`` works (rather than an inscrutable Mosaic error).
    """
    requested = int(requested)
    sub = _min_sublane(itemsize)
    best = 0
    for c in range(min(requested, seq_len), 0, -1):
        if seq_len % c == 0 and (c % sub == 0 or c == seq_len):
            best = c
            break
    if best <= 0:
        valid = sorted(
            {
                c for c in range(1, seq_len + 1)
                if seq_len % c == 0 and (c % sub == 0 or c == seq_len)
            }
        )
        raise ValueError(
            f"flash_attention: no valid {what} <= {requested} for "
            f"seq_len={seq_len} (blocks must divide the sequence and tile "
            f"to a multiple of {sub} for this dtype; valid: {valid})"
        )
    return best


def candidate_pairs(
    seq_len: int, head_dim: int, itemsize: int
) -> List[Tuple[int, int]]:
    """(block_q, block_k) grid for one shape: TPU-valid, L-divisible, and
    within the VMEM working-set budget.  ``TPP_AUTOTUNE_BLOCKS`` (e.g.
    ``"128x128,256x128"``) overrides — tests and constrained sweeps."""
    env = os.environ.get(ENV_BLOCKS)
    if env:
        pairs = []
        for tok in env.split(","):
            tok = tok.strip().lower()
            if not tok:
                continue
            bq_s, _, bk_s = tok.partition("x")
            pairs.append((int(bq_s), int(bk_s or bq_s)))
        return pairs
    blocks = valid_blocks(seq_len, itemsize)
    out = []
    for bq in blocks:
        for bk in blocks:
            # fwd working set: Q + K + V blocks at itemsize, score tile +
            # accumulator + rowstats in f32.
            vmem = (
                (bq + 2 * bk) * head_dim * itemsize
                + (bq * bk + bq * head_dim + 2 * bq * 128) * 4
            )
            if vmem <= _VMEM_BUDGET_BYTES:
                out.append((bq, bk))
    return out or [(min(blocks), min(blocks))] if blocks else []


# ------------------------------------------------------------------ timing


def time_compiled(compiled, args, iters: int) -> float:
    """ms per call with dispatch overhead amortized: the compiled
    executable is warmed, then ``iters`` calls are chained by feeding the
    first output back in (same shape/dtype => executable reused), with ONE
    device->host read at the end proving every call executed."""
    import numpy as np

    out = compiled(*args)
    first = out[0] if isinstance(out, (tuple, list)) else out
    np.asarray(first).ravel()[:1]  # warm-up fence
    cur = list(args)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = compiled(*cur)
        first = out[0] if isinstance(out, (tuple, list)) else out
        if first.shape == cur[0].shape and first.dtype == cur[0].dtype:
            cur[0] = first
    np.asarray(first).ravel()[:1]
    return (time.perf_counter() - t0) / max(1, iters) * 1e3


def _sweep_iters() -> int:
    try:
        return max(1, int(os.environ.get(ENV_ITERS, "10")))
    except ValueError:
        return 10


def sweep_flash(
    batch: int,
    heads: int,
    seq_len: int,
    head_dim: int,
    dtype: Any,
    causal: bool,
    interpret: bool,
    pairs: Optional[Sequence[Tuple[int, int]]] = None,
    iters: Optional[int] = None,
) -> Dict[str, Dict[str, Any]]:
    """Time every candidate (block_q, block_k) for the flash forward AND
    backward independently; returns ``{"flash_fwd": {...}, "flash_bwd":
    {...}}`` with the winner and the full swept grid in each.

    Forward and backward are tuned separately because their balance
    differs: the backward runs two extra matmuls per block and streams dO,
    so its best tile is routinely smaller than the forward's.
    """
    import importlib

    import jax
    import jax.numpy as jnp

    # sys.modules lookup: the package __init__ re-exports a same-named
    # function that shadows attribute-style module imports.
    fa = importlib.import_module("tpu_pipelines.ops.flash_attention")

    jdt = jnp.dtype(dtype)
    itemsize = jdt.itemsize
    if pairs is None:
        pairs = candidate_pairs(seq_len, head_dim, itemsize)
    iters = iters if iters is not None else _sweep_iters()

    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    shape = (batch, seq_len, heads, head_dim)
    q = jax.random.normal(kq, shape, jdt)
    k = jax.random.normal(kk, shape, jdt)
    v = jax.random.normal(kv, shape, jdt)

    def fwd_fn(bq, bk):
        def f(q, k, v):
            return fa.flash_attention(
                q, k, v, causal=causal, block_q=bq, block_k=bk,
                interpret=interpret,
            )
        return f

    def bwd_fn(bq, bk):
        def loss(q, k, v):
            # Fixed fwd blocks: only the bwd tiling varies across this leg.
            return fa.flash_attention(
                q, k, v, causal=causal,
                block_q=min(DEFAULT_BLOCK_Q, seq_len),
                block_k=min(DEFAULT_BLOCK_K, seq_len),
                bwd_block_q=bq, bwd_block_k=bk, interpret=interpret,
            ).astype(jnp.float32).sum()
        return jax.grad(loss, argnums=(0, 1, 2))

    results: Dict[str, Dict[str, Any]] = {}
    for op, make in (("flash_fwd", fwd_fn), ("flash_bwd", bwd_fn)):
        swept = []
        for bq, bk in pairs:
            row: Dict[str, Any] = {"block_q": bq, "block_k": bk}
            try:
                compiled = jax.jit(make(bq, bk)).lower(q, k, v).compile()
                row["ms"] = round(time_compiled(compiled, (q, k, v), iters), 4)
            except Exception as e:  # invalid tiling for this backend
                row["error"] = str(e).splitlines()[0][:160]
            swept.append(row)
        timed = [r for r in swept if "ms" in r]
        best = min(timed, key=lambda r: r["ms"]) if timed else None
        results[op] = {"best": best, "swept": swept}
    return results


def sweep_decode(
    batch: int,
    heads: int,
    kv_len: int,
    head_dim: int,
    dtype: Any,
    interpret: bool,
    pairs: Optional[Sequence[Tuple[int, int]]] = None,
    iters: Optional[int] = None,
) -> Dict[str, Dict[str, Any]]:
    """Time candidate ``block_k`` values for the single-query flash-decode
    kernel (ops/flash_attention.py ``flash_decode_attention``); returns
    ``{"flash_decode": {best, swept}}``.

    ``block_q`` is not tuned — the one query row is replicated to the
    dtype's sublane tile, a constant — so the grid here is 1-D over
    ``block_k``: the knob that sets how the KV cache streams through
    VMEM, which is everything in the bandwidth-bound decode regime.
    """
    import importlib

    import jax
    import jax.numpy as jnp

    fa = importlib.import_module("tpu_pipelines.ops.flash_attention")

    jdt = jnp.dtype(dtype)
    itemsize = jdt.itemsize
    qrows = fa._DECODE_QROWS.get(int(itemsize), 8)
    if pairs is None:
        env = os.environ.get(ENV_BLOCKS)
        if env:
            pairs = [(qrows, bk) for _, bk in candidate_pairs(
                kv_len, head_dim, itemsize
            )]
        else:
            pairs = [(qrows, bk) for bk in valid_blocks(kv_len, itemsize)]
    iters = iters if iters is not None else _sweep_iters()

    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, (batch, 1, heads, head_dim), jdt)
    k = jax.random.normal(kk, (batch, kv_len, heads, head_dim), jdt)
    v = jax.random.normal(kv, (batch, kv_len, heads, head_dim), jdt)

    swept: List[Dict[str, Any]] = []
    for _, bk in pairs:
        row: Dict[str, Any] = {"block_q": qrows, "block_k": bk}
        try:
            def f(q, k, v, _bk=bk):
                return fa.flash_decode_attention(
                    q, k, v, block_k=_bk, interpret=interpret
                )

            compiled = jax.jit(f).lower(q, k, v).compile()
            row["ms"] = round(time_compiled(compiled, (q, k, v), iters), 4)
        except Exception as e:  # invalid tiling for this backend
            row["error"] = str(e).splitlines()[0][:160]
        swept.append(row)
    timed = [r for r in swept if "ms" in r]
    best = min(timed, key=lambda r: r["ms"]) if timed else None
    return {"flash_decode": {"best": best, "swept": swept}}


# ---------------------------------------------------------------- dispatch


def get_block_config(
    op: str,
    batch: int,
    heads: int,
    seq_len: int,
    head_dim: int,
    dtype: Any,
    causal: bool,
    interpret: bool = False,
    allow_sweep: bool = True,
) -> Optional[Tuple[int, int]]:
    """The tuned (block_q, block_k) for one kernel instance, or None when
    the caller should fall back to its defaults.

    Consulted by ``flash_attention`` on first trace.  ``allow_sweep=False``
    (set under a jit trace) means a miss can never time anything — in
    sweep mode the sweep only runs from concrete (non-traced) call sites.
    """
    mode = autotune_mode()
    if mode == MODE_OFF:
        return None
    hits, misses, sweeps, latency = _metrics()
    key = make_key(
        op, batch, heads, seq_len, head_dim, str(dtype), causal
    )
    entry = _lookup_entry(key_id(key), key["device_kind"])
    if entry is not None:
        hits.labels(op).inc()
        return int(entry["block_q"]), int(entry["block_k"])
    misses.labels(op).inc()
    if mode != MODE_SWEEP or not allow_sweep:
        return None
    t0 = time.perf_counter()
    if op == "flash_decode":
        swept = sweep_decode(
            batch, heads, seq_len, head_dim, dtype, interpret
        )
    else:
        swept = sweep_flash(
            batch, heads, seq_len, head_dim, dtype, causal, interpret
        )
    elapsed = time.perf_counter() - t0
    out: Optional[Tuple[int, int]] = None
    for swept_op, res in swept.items():
        best = res.get("best")
        if best is None:
            continue
        swept_key = make_key(
            swept_op, batch, heads, seq_len, head_dim, str(dtype), causal
        )
        record_entry(
            swept_key, best["block_q"], best["block_k"], best["ms"],
            swept=res["swept"],
        )
        sweeps.labels(swept_op).inc()
        latency.labels(swept_op).observe(elapsed)
        if swept_op == op:
            out = (int(best["block_q"]), int(best["block_k"]))
    return out
