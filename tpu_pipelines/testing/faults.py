"""Fault-injection harness: prove the runner's failure semantics.

PR 1 *claims* fail-fast drain, no orphans, clean retry slates; the resume
layer claims crash-safe adoption and fencing.  This module makes those
claims testable by injecting the exact failure modes a preemptible TPU
fleet produces, at the exact runner phase where they occur:

  ==================== =====================================================
  kind                 fires at
  ==================== =====================================================
  RAISE                inside the executor attempt (transient executor bug)
  HANG                 inside the executor attempt; blocks on the runner's
                       cancel event (stuck ``urlopen``, deadlocked
                       collective) — released by the deadline watchdog, so
                       a hang test leaves no orphan thread behind
  CRASH_BEFORE_PUBLISH after the executor succeeded, before the publisher's
                       store write (RUNNING execution + written payload
                       dirs left behind — the state a resume must fence)
  CRASH_AFTER_PUBLISH  right after the COMPLETE publish landed (the state a
                       resume must adopt as-is)
  KILL_ORCHESTRATOR    at node dispatch, in the scheduler thread (pod
                       eviction / OOM / Ctrl-C mid-run)
  TRANSIENT_EXECUTOR_  inside the executor attempt: an explicitly-
  ERROR                classified TransientError, ``times`` times, then
                       clean — the classified-retry-with-backoff bait
  KILL_SHARD_WORKER    inside a ShardPlan fork child (key ``SHARD_KEY``):
                       os._exit, the preempted-worker shape the pool's
                       replacement-worker path must absorb
  STORE_CONTENTION     inside a store write transaction (key
                       ``STORE_KEY``): transient StoreUnavailableError,
                       ``times`` times — multi-writer SQLITE_BUSY shape
  RELOAD_DURING_HAMMER per serving request (key ``SERVING_KEY``): after
                       the ``after``-th request, hot-reload the model in
                       a background thread mid-storm
  ==================== =====================================================

The crash kinds raise :class:`SimulatedCrash` — a ``BaseException`` so no
``except Exception`` along the way can swallow it, mimicking a process
death: the metadata store is left exactly as a SIGKILL would leave it
(committed rows only, nothing finalized).  Each fault fires ONCE per plan,
so the node runs clean on resume.

Usage::

    plan = FaultPlan({"Trainer": NodeFault(CRASH_BEFORE_PUBLISH)})
    with plan.activate():
        with pytest.raises(SimulatedCrash):
            LocalDagRunner().run(pipeline)
    LocalDagRunner().run(pipeline, resume_from="latest")

The runner's hook calls cost one module-global read when no plan is
active; production runs never pay more than that.
"""

from __future__ import annotations

import dataclasses
import threading
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

RAISE = "raise"
HANG = "hang"
CRASH_BEFORE_PUBLISH = "crash_before_publish"
CRASH_AFTER_PUBLISH = "crash_after_publish"
KILL_ORCHESTRATOR = "kill_orchestrator"
# Robustness-layer kinds (ISSUE 7): the failure modes the unified
# fault-tolerance layer must absorb rather than surface.
TRANSIENT_EXECUTOR_ERROR = "transient_executor_error"  # classified-retry bait
KILL_SHARD_WORKER = "kill_shard_worker"    # SIGKILL-equivalent in a fork child
STORE_CONTENTION = "store_contention"      # transient StoreUnavailableError
RELOAD_DURING_HAMMER = "reload_during_hammer"  # hot-swap mid-request-storm
# Fleet-supervision kinds (ISSUE 17): the per-replica failure modes the
# ReplicaSupervisor/failover layer must absorb (plan key ``REPLICA_KEY``).
KILL_REPLICA = "kill_replica"      # latched death until rebuild (generation)
WEDGE_PREDICT = "wedge_predict"    # predict parks, queue age grows
DEVICE_ERROR = "device_error"      # transient device fault, `times` times

# Sentinel plan keys for faults that are not tied to a pipeline node.
STORE_KEY = "__store__"
SHARD_KEY = "__shards__"
SERVING_KEY = "__serving__"
REPLICA_KEY = "__replica__"

# kind -> the runner phase whose hook triggers it.
_KIND_TO_POINT = {
    RAISE: "in_executor",
    HANG: "in_executor",
    TRANSIENT_EXECUTOR_ERROR: "in_executor",
    CRASH_BEFORE_PUBLISH: "before_publish",
    CRASH_AFTER_PUBLISH: "after_publish",
    KILL_ORCHESTRATOR: "at_dispatch",
    KILL_SHARD_WORKER: "in_shard",
    STORE_CONTENTION: "store_op",
    RELOAD_DURING_HAMMER: "serving_request",
    KILL_REPLICA: "replica_predict",
    WEDGE_PREDICT: "replica_predict",
    DEVICE_ERROR: "replica_predict",
}


class SimulatedCrash(BaseException):
    """Stand-in for orchestrator/process death at a precise runner phase.

    BaseException on purpose: a real SIGKILL is not catchable, so no
    ``except Exception`` in an executor, worker, or retry loop may
    convert this into an ordinary node failure.
    """

    def __init__(self, node_id: str, point: str):
        super().__init__(f"simulated crash at {point} of node {node_id!r}")
        self.node_id = node_id
        self.point = point


class InjectedFault(RuntimeError):
    """The exception RAISE/HANG faults surface inside the executor."""


@dataclasses.dataclass
class NodeFault:
    kind: str
    message: str = "injected fault"
    # HANG safety ceiling: the hang waits on the runner's cancel event and
    # gives up after this long regardless, so a missing/misconfigured
    # watchdog can never wedge a test run forever.
    max_hang_s: float = 60.0
    # How many times the fault fires before going inert (RAISE /
    # TRANSIENT_EXECUTOR_ERROR / STORE_CONTENTION: fail N attempts, then
    # succeed — the shape a classified retry policy must absorb).
    times: int = 1
    # KILL_SHARD_WORKER: which shard index of the fanned-out pool dies.
    shard: int = 0
    # RELOAD_DURING_HAMMER / KILL_REPLICA: fire once the Nth request has
    # arrived (so the hammer is demonstrably in flight when the swap or
    # kill happens).
    after: int = 1
    # Replica-fault targeting (KILL_REPLICA / WEDGE_PREDICT /
    # DEVICE_ERROR): which replica name the fault applies to; "" = the
    # first replica the fault observes (then latched to it).
    replica: str = ""
    # WEDGE_PREDICT release valve: tests set() it to un-wedge early;
    # otherwise the wedge parks for max_hang_s.
    release: threading.Event = dataclasses.field(
        default_factory=threading.Event, compare=False
    )
    # KILL_SHARD_WORKER cross-process once-token: fork children inherit a
    # COPY of the plan's fired-set, so in-memory once-semantics cannot
    # span the pool — the first child to atomically create this file is
    # the one that dies.  Auto-assigned at activate() when left empty.
    once_file: str = ""

    def __post_init__(self):
        if self.kind not in _KIND_TO_POINT:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; "
                f"expected one of {sorted(_KIND_TO_POINT)}"
            )
        if self.times < 1:
            raise ValueError(f"times must be >= 1, got {self.times}")


class FaultPlan:
    """Per-node faults, each fired at most once (so resumed runs succeed).

    ``log`` records ``(node_id, event)`` tuples — tests assert on it to
    prove e.g. that a hang was released by the watchdog's cancel event
    rather than by its own safety ceiling (no orphan threads).
    """

    def __init__(self, faults: Dict[str, NodeFault]):
        self.faults = dict(faults)
        self._fired: Dict[str, int] = {}
        self._requests = 0  # serving_request arrivals (RELOAD_DURING_HAMMER)
        self._replica_calls = 0   # replica_predict arrivals (KILL_REPLICA)
        # KILL_REPLICA latch: replica name -> the generation that died.
        # Every call from that (replica, generation) fails; the rebuild
        # bumps the generation, so the rebuilt incarnation runs clean.
        self._killed: Dict[str, int] = {}
        self._pid = None    # set at activate(): detects fork children
        self._lock = threading.Lock()
        self.log: List[Tuple[str, str]] = []

    def _take(self, node_id: str, point: str) -> Optional[NodeFault]:
        """Claim one firing of the fault keyed by ``node_id`` at runner
        phase ``point``; None once its ``times`` budget is spent."""
        fault = self.faults.get(node_id)
        if fault is None or _KIND_TO_POINT[fault.kind] != point:
            return None
        with self._lock:
            fired = self._fired.get(node_id, 0)
            if fired >= fault.times:
                return None
            self._fired[node_id] = fired + 1
        return fault

    def record(self, node_id: str, event: str) -> None:
        with self._lock:
            self.log.append((node_id, event))

    @contextmanager
    def activate(self):
        """Install this plan for the duration of the block (test-only)."""
        import os
        import tempfile

        global _ACTIVE
        prev = _ACTIVE
        self._pid = os.getpid()
        tokens: List[str] = []
        for fault in self.faults.values():
            if fault.kind == KILL_SHARD_WORKER and not fault.once_file:
                # Reserve a name only — the first shard child to O_EXCL-
                # create it wins the kill; parent cleans up afterwards.
                fault.once_file = os.path.join(
                    tempfile.gettempdir(),
                    f"tpp-fault-{os.getpid()}-{id(fault)}.token",
                )
                tokens.append(fault.once_file)
        _ACTIVE = self
        try:
            yield self
        finally:
            _ACTIVE = prev
            for token in tokens:
                try:
                    os.unlink(token)
                except OSError:
                    pass


_ACTIVE: Optional[FaultPlan] = None


# ------------------------------------------------------------ runner hooks


def at_dispatch(node_id: str) -> None:
    """Scheduler thread, before the node's driver phase runs."""
    plan = _ACTIVE
    if plan is None:
        return
    fault = plan._take(node_id, "at_dispatch")
    if fault is not None:
        plan.record(node_id, "kill_orchestrator")
        raise SimulatedCrash(node_id, "at_dispatch")


def in_executor(
    node_id: str, cancel_event: Optional[threading.Event]
) -> None:
    """Worker thread, inside the executor attempt (before the real fn)."""
    plan = _ACTIVE
    if plan is None:
        return
    fault = plan._take(node_id, "in_executor")
    if fault is None:
        return
    if fault.kind == TRANSIENT_EXECUTOR_ERROR:
        # Explicitly-classified transient failure: the robustness layer's
        # RetryPolicy must absorb `times` of these and then succeed.
        from tpu_pipelines.robustness.errors import TransientError

        plan.record(node_id, "transient_executor_error")
        raise TransientError(fault.message)
    if fault.kind == RAISE:
        plan.record(node_id, "raise")
        raise InjectedFault(fault.message)
    # HANG: cooperative stuck-executor — parks until the deadline
    # watchdog's cancel event (or the safety ceiling) releases it.
    plan.record(node_id, "hang_start")
    event = cancel_event or threading.Event()
    released = event.wait(fault.max_hang_s)
    plan.record(node_id, "hang_released" if released else "hang_ceiling")
    raise InjectedFault(
        f"{fault.message} (hang "
        f"{'cancelled by watchdog' if released else 'hit safety ceiling'})"
    )


def before_publish(node_id: str) -> None:
    """Worker thread, executor succeeded, publisher not yet written."""
    plan = _ACTIVE
    if plan is None:
        return
    if plan._take(node_id, "before_publish") is not None:
        plan.record(node_id, "crash_before_publish")
        raise SimulatedCrash(node_id, "before_publish")


def after_publish(node_id: str) -> None:
    """Worker thread, COMPLETE publish committed."""
    plan = _ACTIVE
    if plan is None:
        return
    if plan._take(node_id, "after_publish") is not None:
        plan.record(node_id, "crash_after_publish")
        raise SimulatedCrash(node_id, "after_publish")


def in_shard(shard_index: int) -> None:
    """Inside a ShardPlan pool worker, before the real per-shard fn.

    KILL_SHARD_WORKER (plan key ``SHARD_KEY``): the matching shard's
    worker dies with ``os._exit`` — a SIGKILL-equivalent the pool
    observes as BrokenProcessPool, forcing the replacement-worker path.
    Cross-process once-semantics ride the fault's ``once_file`` token
    (fork children inherit plan COPIES, so in-memory state cannot span
    the pool).  In a same-process fallback pool (threads/sequential) the
    fault degrades to a TransientError raise: killing the interpreter
    would take the whole run (and the test) with it.
    """
    import os

    plan = _ACTIVE
    if plan is None:
        return
    fault = plan.faults.get(SHARD_KEY)
    if fault is None or fault.kind != KILL_SHARD_WORKER:
        return
    if shard_index != fault.shard or not fault.once_file:
        return
    try:
        fd = os.open(fault.once_file, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        os.close(fd)
    except OSError:
        return  # another worker (or a prior attempt) already fired
    if plan._pid is not None and os.getpid() != plan._pid:
        # Fork child: die the way a preempted/OOM-killed worker does.
        os._exit(3)
    from tpu_pipelines.robustness.errors import TransientError

    plan.record(SHARD_KEY, "kill_shard_worker_inline")
    raise TransientError(
        f"{fault.message} (same-process pool: raised instead of killed)"
    )


def store_op(op: str) -> None:
    """Inside a MetadataStore write transaction, before the commit.

    STORE_CONTENTION (plan key ``STORE_KEY``): raises a transient
    ``StoreUnavailableError`` ``times`` times — the shape a contended
    multi-writer store produces (SQLITE_BUSY under N concurrent
    publishers) — which the store-level publish retry must absorb.
    """
    plan = _ACTIVE
    if plan is None:
        return
    fault = plan._take(STORE_KEY, "store_op")
    if fault is None:
        return
    plan.record(STORE_KEY, f"store_contention:{op}")
    from tpu_pipelines.metadata.store import StoreUnavailableError

    raise StoreUnavailableError(fault.message)


def serving_request(server, endpoint: str) -> None:
    """Per request on the ModelServer's hot endpoints.

    RELOAD_DURING_HAMMER (plan key ``SERVING_KEY``): once the ``after``-th
    request has arrived — i.e. the hammer is demonstrably in flight — a
    background thread calls ``server.reload()``, so the zero-5xx
    reload-under-load guarantee is exercised mid-storm rather than
    between requests.
    """
    plan = _ACTIVE
    if plan is None:
        return
    fault = plan.faults.get(SERVING_KEY)
    if fault is None or fault.kind != RELOAD_DURING_HAMMER:
        return
    with plan._lock:
        plan._requests += 1
        n = plan._requests
    if n < max(1, fault.after):
        return
    if plan._take(SERVING_KEY, "serving_request") is None:
        return
    plan.record(SERVING_KEY, f"reload_during_hammer:{endpoint}")
    threading.Thread(
        target=server.reload, name="tpp-fault-reload", daemon=True
    ).start()


def replica_predict(replica_name: str, generation: int = 0) -> None:
    """Per call on a fleet replica's hot paths (batched predict, the
    supervisor heartbeat, the generative engine's worker loop), keyed by
    ``REPLICA_KEY``.

    KILL_REPLICA: after the ``after``-th call fleet-wide, the targeted
    replica's CURRENT generation is latched dead — every subsequent call
    from that (replica, generation) raises, exactly like a device that
    fell off the bus.  A rebuild bumps the generation, so the rebuilt
    incarnation runs clean: the recovery proof needs the death to be
    *persistent until healed*, not a one-shot blip.

    WEDGE_PREDICT: ``times`` calls park on the fault's ``release`` event
    (bounded by ``max_hang_s``) — the wedged-device shape the
    supervisor's queue-age probe must catch.

    DEVICE_ERROR: ``times`` calls raise a transient device-runtime error
    (the transfer-failure shape ``classify_error`` marks retriable), so
    request failover engages without any replica being declared dead.
    """
    plan = _ACTIVE
    if plan is None:
        return
    fault = plan.faults.get(REPLICA_KEY)
    if fault is None or _KIND_TO_POINT.get(fault.kind) != "replica_predict":
        return
    if fault.kind == KILL_REPLICA:
        with plan._lock:
            latched = plan._killed.get(replica_name)
            if latched is not None:
                if latched == generation:
                    pass  # still the dead incarnation: fall through, raise
                else:
                    return  # rebuilt: the new generation runs clean
            else:
                if fault.replica and fault.replica != replica_name:
                    return
                plan._replica_calls += 1
                if plan._replica_calls < max(1, fault.after):
                    return
                if plan._fired.get(REPLICA_KEY, 0) >= 1:
                    return  # only one replica dies per plan
                plan._fired[REPLICA_KEY] = 1
                plan._killed[replica_name] = generation
                plan.log.append(
                    (REPLICA_KEY, f"kill_replica:{replica_name}")
                )
        raise InjectedFault(f"{fault.message} (replica {replica_name} dead)")
    if fault.replica and fault.replica != replica_name:
        return
    claimed = plan._take(REPLICA_KEY, "replica_predict")
    if claimed is None:
        return
    if fault.kind == WEDGE_PREDICT:
        plan.record(REPLICA_KEY, f"wedge_predict:{replica_name}")
        released = fault.release.wait(fault.max_hang_s)
        plan.record(
            REPLICA_KEY, "wedge_released" if released else "wedge_ceiling"
        )
        raise InjectedFault(f"{fault.message} (predict wedged)")
    plan.record(REPLICA_KEY, f"device_error:{replica_name}")
    raise RuntimeError(
        f"{fault.message}: failed to transfer buffer to device "
        f"(injected device error on replica {replica_name})"
    )
