"""Fault-injection harness: prove the runner's failure semantics.

PR 1 *claims* fail-fast drain, no orphans, clean retry slates; the resume
layer claims crash-safe adoption and fencing.  This module makes those
claims testable by injecting the exact failure modes a preemptible TPU
fleet produces, at the exact runner phase where they occur:

  ==================== =====================================================
  kind                 fires at
  ==================== =====================================================
  RAISE                inside the executor attempt (transient executor bug)
  HANG                 inside the executor attempt; blocks on the runner's
                       cancel event (stuck ``urlopen``, deadlocked
                       collective) — released by the deadline watchdog, so
                       a hang test leaves no orphan thread behind
  CRASH_BEFORE_PUBLISH after the executor succeeded, before the publisher's
                       store write (RUNNING execution + written payload
                       dirs left behind — the state a resume must fence)
  CRASH_AFTER_PUBLISH  right after the COMPLETE publish landed (the state a
                       resume must adopt as-is)
  KILL_ORCHESTRATOR    at node dispatch, in the scheduler thread (pod
                       eviction / OOM / Ctrl-C mid-run)
  ==================== =====================================================

The crash kinds raise :class:`SimulatedCrash` — a ``BaseException`` so no
``except Exception`` along the way can swallow it, mimicking a process
death: the metadata store is left exactly as a SIGKILL would leave it
(committed rows only, nothing finalized).  Each fault fires ONCE per plan,
so the node runs clean on resume.

Usage::

    plan = FaultPlan({"Trainer": NodeFault(CRASH_BEFORE_PUBLISH)})
    with plan.activate():
        with pytest.raises(SimulatedCrash):
            LocalDagRunner().run(pipeline)
    LocalDagRunner().run(pipeline, resume_from="latest")

The runner's hook calls cost one module-global read when no plan is
active; production runs never pay more than that.
"""

from __future__ import annotations

import dataclasses
import threading
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

RAISE = "raise"
HANG = "hang"
CRASH_BEFORE_PUBLISH = "crash_before_publish"
CRASH_AFTER_PUBLISH = "crash_after_publish"
KILL_ORCHESTRATOR = "kill_orchestrator"

# kind -> the runner phase whose hook triggers it.
_KIND_TO_POINT = {
    RAISE: "in_executor",
    HANG: "in_executor",
    CRASH_BEFORE_PUBLISH: "before_publish",
    CRASH_AFTER_PUBLISH: "after_publish",
    KILL_ORCHESTRATOR: "at_dispatch",
}


class SimulatedCrash(BaseException):
    """Stand-in for orchestrator/process death at a precise runner phase.

    BaseException on purpose: a real SIGKILL is not catchable, so no
    ``except Exception`` in an executor, worker, or retry loop may
    convert this into an ordinary node failure.
    """

    def __init__(self, node_id: str, point: str):
        super().__init__(f"simulated crash at {point} of node {node_id!r}")
        self.node_id = node_id
        self.point = point


class InjectedFault(RuntimeError):
    """The exception RAISE/HANG faults surface inside the executor."""


@dataclasses.dataclass
class NodeFault:
    kind: str
    message: str = "injected fault"
    # HANG safety ceiling: the hang waits on the runner's cancel event and
    # gives up after this long regardless, so a missing/misconfigured
    # watchdog can never wedge a test run forever.
    max_hang_s: float = 60.0

    def __post_init__(self):
        if self.kind not in _KIND_TO_POINT:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; "
                f"expected one of {sorted(_KIND_TO_POINT)}"
            )


class FaultPlan:
    """Per-node faults, each fired at most once (so resumed runs succeed).

    ``log`` records ``(node_id, event)`` tuples — tests assert on it to
    prove e.g. that a hang was released by the watchdog's cancel event
    rather than by its own safety ceiling (no orphan threads).
    """

    def __init__(self, faults: Dict[str, NodeFault]):
        self.faults = dict(faults)
        self._fired: set = set()
        self._lock = threading.Lock()
        self.log: List[Tuple[str, str]] = []

    def _take(self, node_id: str, point: str) -> Optional[NodeFault]:
        fault = self.faults.get(node_id)
        if fault is None or _KIND_TO_POINT[fault.kind] != point:
            return None
        with self._lock:
            if node_id in self._fired:
                return None
            self._fired.add(node_id)
        return fault

    def record(self, node_id: str, event: str) -> None:
        with self._lock:
            self.log.append((node_id, event))

    @contextmanager
    def activate(self):
        """Install this plan for the duration of the block (test-only)."""
        global _ACTIVE
        prev = _ACTIVE
        _ACTIVE = self
        try:
            yield self
        finally:
            _ACTIVE = prev


_ACTIVE: Optional[FaultPlan] = None


# ------------------------------------------------------------ runner hooks


def at_dispatch(node_id: str) -> None:
    """Scheduler thread, before the node's driver phase runs."""
    plan = _ACTIVE
    if plan is None:
        return
    fault = plan._take(node_id, "at_dispatch")
    if fault is not None:
        plan.record(node_id, "kill_orchestrator")
        raise SimulatedCrash(node_id, "at_dispatch")


def in_executor(
    node_id: str, cancel_event: Optional[threading.Event]
) -> None:
    """Worker thread, inside the executor attempt (before the real fn)."""
    plan = _ACTIVE
    if plan is None:
        return
    fault = plan._take(node_id, "in_executor")
    if fault is None:
        return
    if fault.kind == RAISE:
        plan.record(node_id, "raise")
        raise InjectedFault(fault.message)
    # HANG: cooperative stuck-executor — parks until the deadline
    # watchdog's cancel event (or the safety ceiling) releases it.
    plan.record(node_id, "hang_start")
    event = cancel_event or threading.Event()
    released = event.wait(fault.max_hang_s)
    plan.record(node_id, "hang_released" if released else "hang_ceiling")
    raise InjectedFault(
        f"{fault.message} (hang "
        f"{'cancelled by watchdog' if released else 'hit safety ceiling'})"
    )


def before_publish(node_id: str) -> None:
    """Worker thread, executor succeeded, publisher not yet written."""
    plan = _ACTIVE
    if plan is None:
        return
    if plan._take(node_id, "before_publish") is not None:
        plan.record(node_id, "crash_before_publish")
        raise SimulatedCrash(node_id, "before_publish")


def after_publish(node_id: str) -> None:
    """Worker thread, COMPLETE publish committed."""
    plan = _ACTIVE
    if plan is None:
        return
    if plan._take(node_id, "after_publish") is not None:
        plan.record(node_id, "crash_after_publish")
        raise SimulatedCrash(node_id, "after_publish")
