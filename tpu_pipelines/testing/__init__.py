"""Test-only harnesses: fault injection for crash-safety verification.

Nothing here runs in a production pipeline unless explicitly activated;
the runner's hook calls are no-ops while no plan is installed.
"""

from tpu_pipelines.testing.faults import (  # noqa: F401
    FaultPlan,
    NodeFault,
    SimulatedCrash,
)
