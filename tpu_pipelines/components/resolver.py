"""Resolver: driver-level artifact resolution across prior runs.

Capability match for TFX's ``tfx.dsl.Resolver`` with
``LatestBlessedModelStrategy`` / ``LatestArtifactStrategy`` (SURVEY.md:133:
the Evaluator's model-diff/blessing gate compares the candidate against the
*previously blessed* model pulled from metadata, not just an in-pipeline
channel).  A Resolver node runs in the runner's DRIVER against the metadata
store — no executor, never cached (its answer changes as runs accumulate) —
and re-emits EXISTING artifacts: downstream consumers see the same artifact
ids, so lineage records reuse, not copies.

Canonical continuous-training wiring::

    baseline = Resolver(strategy="latest_blessed_model")
    evaluator = Evaluator(
        examples=..., model=trainer.outputs["model"],
        baseline_model=baseline.outputs["model"],
        change_thresholds={"accuracy": {"min_improvement": 0.0}},
    )

Run 1: no blessed model exists, the resolver yields nothing, and Evaluator
(whose ``baseline_model`` is optional) gates on value thresholds only.
Run N: the newest blessed model from any prior run becomes the baseline, so
change thresholds gate against production exactly like TFX.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from tpu_pipelines.dsl.component import Component, ComponentSpec, Parameter
from tpu_pipelines.metadata.store import MetadataStore
from tpu_pipelines.metadata.types import Artifact, ArtifactState, EventType

STRATEGY_LATEST_BLESSED = "latest_blessed_model"
STRATEGY_LATEST = "latest_created"

STRATEGIES = (STRATEGY_LATEST_BLESSED, STRATEGY_LATEST)


class Resolver(Component):
    """Driver-level node resolving a Model artifact from prior runs."""

    SPEC = ComponentSpec(
        inputs={},
        outputs={"model": "Model"},
        parameters={
            # latest_blessed_model: newest Model that has a blessed=True
            #   ModelBlessing produced by an execution that consumed it.
            # latest_created: newest LIVE Model regardless of blessing
            #   (TFX LatestArtifactStrategy — warm-start wiring).
            "strategy": Parameter(type=str, default=STRATEGY_LATEST_BLESSED),
            # Restrict to artifacts attributed to THIS pipeline's context;
            # False searches every pipeline sharing the metadata store.
            "within_pipeline": Parameter(type=bool, default=True),
        },
    )
    EXECUTOR = None
    IS_RESOLVER = True


def resolve_artifacts(
    store: MetadataStore,
    *,
    strategy: str,
    pipeline_name: str,
    within_pipeline: bool = True,
) -> Dict[str, List[Artifact]]:
    """Run a resolver strategy against the store; returns {"model": [...]}
    with zero or one artifact — the runner publishes this as the node's
    outputs."""
    if strategy not in STRATEGIES:
        raise ValueError(
            f"unknown resolver strategy {strategy!r}; expected one of "
            f"{STRATEGIES}"
        )
    scope: Optional[set] = None
    if within_pipeline:
        ctx = store.get_context("pipeline", pipeline_name)
        if ctx is None:
            return {"model": []}
        scope = {a.id for a in store.get_artifacts_by_context(ctx.id)}

    if strategy == STRATEGY_LATEST:
        models = [
            a for a in store.get_artifacts(
                type_name="Model", state=ArtifactState.LIVE
            )
            if scope is None or a.id in scope
        ]
        models.sort(key=lambda a: a.id, reverse=True)
        return {"model": models[:1]}

    # latest_blessed_model: walk from blessing artifacts (newest first) to
    # the Model the blessing execution consumed at input path "model".
    blessings = [
        b for b in store.get_artifacts(
            type_name="ModelBlessing", state=ArtifactState.LIVE
        )
        if b.properties.get("blessed") and (scope is None or b.id in scope)
    ]
    blessings.sort(key=lambda a: a.id, reverse=True)
    for blessing in blessings:
        producer_ids = [
            ev.execution_id
            for ev in store.get_events_by_artifact(blessing.id)
            if ev.type == EventType.OUTPUT
        ]
        for ex_id in producer_ids:
            for ev in store.get_events_by_execution(ex_id):
                if ev.type != EventType.INPUT or ev.path != "model":
                    continue
                model = store.get_artifact(ev.artifact_id)
                if model is not None and model.state == ArtifactState.LIVE:
                    return {"model": [model]}
    return {"model": []}
