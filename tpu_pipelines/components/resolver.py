"""Resolver: driver-level artifact resolution across prior runs.

Capability match for TFX's ``tfx.dsl.Resolver`` with
``LatestBlessedModelStrategy`` / ``LatestArtifactStrategy`` (SURVEY.md:133:
the Evaluator's model-diff/blessing gate compares the candidate against the
*previously blessed* model pulled from metadata, not just an in-pipeline
channel).  A Resolver node runs in the runner's DRIVER against the metadata
store — no executor, never cached (its answer changes as runs accumulate) —
and re-emits EXISTING artifacts: downstream consumers see the same artifact
ids, so lineage records reuse, not copies.

Canonical continuous-training wiring::

    baseline = Resolver(strategy="latest_blessed_model")
    evaluator = Evaluator(
        examples=..., model=trainer.outputs["model"],
        baseline_model=baseline.outputs["model"],
        change_thresholds={"accuracy": {"min_improvement": 0.0}},
    )

Run 1: no blessed model exists, the resolver yields nothing, and Evaluator
(whose ``baseline_model`` is optional) gates on value thresholds only.
Run N: the newest blessed model from any prior run becomes the baseline, so
change thresholds gate against production exactly like TFX.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from tpu_pipelines.dsl.component import Component, ComponentSpec, Parameter
from tpu_pipelines.metadata.store import MetadataStore
from tpu_pipelines.metadata.types import Artifact, ArtifactState, EventType

STRATEGY_LATEST_BLESSED = "latest_blessed_model"
STRATEGY_LATEST = "latest_created"
STRATEGY_ROLLING_WINDOW = "rolling_window"

STRATEGIES = (
    STRATEGY_LATEST_BLESSED, STRATEGY_LATEST, STRATEGY_ROLLING_WINDOW,
)


class Resolver(Component):
    """Driver-level node resolving a Model artifact from prior runs."""

    SPEC = ComponentSpec(
        inputs={},
        outputs={"model": "Model"},
        parameters={
            # latest_blessed_model: newest Model that has a blessed=True
            #   ModelBlessing produced by an execution that consumed it.
            # latest_created: newest LIVE Model regardless of blessing
            #   (TFX LatestArtifactStrategy — warm-start wiring).
            "strategy": Parameter(type=str, default=STRATEGY_LATEST_BLESSED),
            # Restrict to artifacts attributed to THIS pipeline's context;
            # False searches every pipeline sharing the metadata store.
            "within_pipeline": Parameter(type=bool, default=True),
        },
    )
    EXECUTOR = None
    IS_RESOLVER = True


class RollingWindowResolver(Component):
    """Rolling last-K-spans window over per-span artifacts (docs/CONTINUOUS.md).

    The continuous-training resolver (TFX's RollingRange/SpanRangeStrategy
    analog): selects the newest delivery of each of the last ``window_spans``
    spans — Examples and their matching per-span ExampleStatistics — plus
    the latest blessed baseline Model, so Trainer/Evaluator retrain over a
    sliding window instead of all history.  Artifacts are matched by their
    ``span``/``version`` properties (stamped by ExampleGen and propagated
    by StatisticsGen/Transform); a re-delivered span (higher ``version``,
    or simply a newer artifact for the same span) replaces the old delivery
    in the window.

    Outputs are span-ascending (oldest -> newest), so a downstream
    ``SpanWindow`` union and a cold full run over the same data fold in
    the identical order.  ``source_pipeline`` scopes the span artifacts to
    the per-span ingest pipeline's context (the continuous controller runs
    ingest and training as separate pipelines against one shared store);
    the baseline model is always resolved within THIS pipeline's context.
    """

    SPEC = ComponentSpec(
        inputs={},
        outputs={
            "examples": "Examples",
            "statistics": "ExampleStatistics",
            "model": "Model",
        },
        parameters={
            "strategy": Parameter(type=str, default=STRATEGY_ROLLING_WINDOW),
            # How many trailing spans the window covers (K).
            "window_spans": Parameter(type=int, default=3),
            # Node ids (in the source pipeline) whose outputs are the
            # span artifacts; "" accepts any producer.  Distinguishes raw
            # from transformed Examples when both carry span properties.
            "examples_producer": Parameter(type=str, default=""),
            "statistics_producer": Parameter(type=str, default=""),
            # Pipeline context the span artifacts live in ("" = no scope:
            # any pipeline sharing the store).
            "source_pipeline": Parameter(type=str, default=""),
            "within_pipeline": Parameter(type=bool, default=False),
        },
    )
    EXECUTOR = None
    IS_RESOLVER = True


def resolve_artifacts(
    store: MetadataStore,
    *,
    strategy: str,
    pipeline_name: str,
    within_pipeline: bool = True,
    extra: Optional[Dict] = None,
) -> Dict[str, List[Artifact]]:
    """Run a resolver strategy against the store; returns the node's
    output dict ({"model": [...]} for the model strategies, the full
    window mapping for ``rolling_window``) — the runner publishes this
    as the node's outputs.  ``extra`` carries strategy-specific exec
    properties (window size, producer filters) verbatim."""
    if strategy not in STRATEGIES:
        raise ValueError(
            f"unknown resolver strategy {strategy!r}; expected one of "
            f"{STRATEGIES}"
        )
    if strategy == STRATEGY_ROLLING_WINDOW:
        return _resolve_rolling_window(
            store, pipeline_name=pipeline_name,
            within_pipeline=within_pipeline, extra=dict(extra or {}),
        )
    scope: Optional[set] = None
    if within_pipeline:
        ctx = store.get_context("pipeline", pipeline_name)
        if ctx is None:
            return {"model": []}
        scope = {a.id for a in store.get_artifacts_by_context(ctx.id)}

    if strategy == STRATEGY_LATEST:
        models = [
            a for a in store.get_artifacts(
                type_name="Model", state=ArtifactState.LIVE
            )
            if scope is None or a.id in scope
        ]
        models.sort(key=lambda a: a.id, reverse=True)
        return {"model": models[:1]}

    # latest_blessed_model: walk from blessing artifacts (newest first) to
    # the Model the blessing execution consumed at input path "model".
    blessings = [
        b for b in store.get_artifacts(
            type_name="ModelBlessing", state=ArtifactState.LIVE
        )
        if b.properties.get("blessed") and (scope is None or b.id in scope)
    ]
    blessings.sort(key=lambda a: a.id, reverse=True)
    for blessing in blessings:
        producer_ids = [
            ev.execution_id
            for ev in store.get_events_by_artifact(blessing.id)
            if ev.type == EventType.OUTPUT
        ]
        for ex_id in producer_ids:
            for ev in store.get_events_by_execution(ex_id):
                if ev.type != EventType.INPUT or ev.path != "model":
                    continue
                model = store.get_artifact(ev.artifact_id)
                if model is not None and model.state == ArtifactState.LIVE:
                    return {"model": [model]}
    return {"model": []}


def _producer_node_id(store: MetadataStore, artifact_id: int) -> str:
    """Node id of the execution that OUTPUT this artifact ("" if unknown)."""
    for ev in store.get_events_by_artifact(artifact_id):
        if ev.type != EventType.OUTPUT:
            continue
        ex = store.get_execution(ev.execution_id)
        if ex is not None:
            return ex.node_id
    return ""


def _latest_per_span(
    store: MetadataStore,
    type_name: str,
    producer: str,
    scope: Optional[set],
) -> Dict[int, Artifact]:
    """Newest LIVE artifact per ``span`` property.  Re-delivery ordering:
    the highest ``version`` property wins (an out-of-order re-delivery of
    version 2 after version 3 must NOT displace 3); artifact id — publish
    order — breaks ties and orders unversioned layouts."""
    by_span: Dict[int, Artifact] = {}

    def rank(a: Artifact):
        v = a.properties.get("version")
        return (v if isinstance(v, int) else -1, a.id)

    for art in store.get_artifacts(
        type_name=type_name, state=ArtifactState.LIVE
    ):
        span = art.properties.get("span")
        if not isinstance(span, int):
            continue
        if scope is not None and art.id not in scope:
            continue
        if producer and _producer_node_id(store, art.id) != producer:
            continue
        cur = by_span.get(span)
        if cur is None or rank(art) > rank(cur):
            by_span[span] = art
    return by_span


def _resolve_rolling_window(
    store: MetadataStore,
    *,
    pipeline_name: str,
    within_pipeline: bool,
    extra: Dict,
) -> Dict[str, List[Artifact]]:
    """The ``rolling_window`` strategy (RollingWindowResolver docstring):
    last-K spans' Examples + matching per-span statistics, span-ascending,
    plus the latest blessed Model from THIS pipeline as baseline."""
    window = max(1, int(extra.get("window_spans") or 3))
    source = str(extra.get("source_pipeline") or "")
    scope: Optional[set] = None
    if source:
        ctx = store.get_context("pipeline", source)
        if ctx is None:
            # Source pipeline has published nothing yet: empty window.
            scope = set()
        else:
            scope = {a.id for a in store.get_artifacts_by_context(ctx.id)}
    elif within_pipeline:
        ctx = store.get_context("pipeline", pipeline_name)
        scope = (
            set() if ctx is None
            else {a.id for a in store.get_artifacts_by_context(ctx.id)}
        )
    examples = _latest_per_span(
        store, "Examples", str(extra.get("examples_producer") or ""), scope
    )
    stats = _latest_per_span(
        store, "ExampleStatistics",
        str(extra.get("statistics_producer") or ""), scope,
    )
    spans = sorted(examples)[-window:]
    # Baseline: the newest blessed model of the TRAINING pipeline (the
    # one this resolver node runs in), the LatestBlessedModelStrategy walk.
    model = resolve_artifacts(
        store, strategy=STRATEGY_LATEST_BLESSED,
        pipeline_name=pipeline_name, within_pipeline=True,
    )["model"]
    return {
        "examples": [examples[s] for s in spans],
        "statistics": [stats[s] for s in spans if s in stats],
        "model": model,
    }
