"""Rewriter: quantized serving variants with a self-applied quality gate.

Capability match for the reference architecture's Rewriter / TFLite-
converter stage (the ModelOptimizer seam between Trainer and Pusher):
consumes a trained Model payload and emits optimized serving variants of
it —

  ``float32``   the original payload, hardlinked (the reference and the
                always-safe fallback)
  ``bfloat16``  every float leaf cast to bf16 (half the resident bytes;
                the loader casts once at load, never per request)
  ``aqt_int8``  AQT-style symmetric int8 weight quantization
                (trainer/quantize.py): large weight tensors stored as
                int8 qvalues + per-channel scales, dequantized INSIDE the
                jitted step so gathers/matmuls read a quarter of the
                weight bytes

each a fully self-contained payload under ``<uri>/variants/<name>/``,
with the SELECTED variant's payload hardlinked at the artifact root so
every existing Model consumer (Pusher, InfraValidator, serving fleet,
BulkInferrer) loads the optimized model with zero wiring changes.

**Gate 1 — quality (here).**  With an eval ``examples`` input wired, the
component re-runs the Evaluator metric surface
(``evaluator.evaluate_payload``) on an eval slice for the float payload
and every variant; a variant whose worst relative metric delta exceeds
``quality_tolerance`` is marked NOT_BLESSED — recorded in the variant's
``model_spec.json`` (``rewriter.blessed = false``) plus a
``REWRITE_NOT_BLESSED`` marker — and is never selected or pushed.
Without eval examples the gate fails closed: only ``float32`` is
blessed.

**Gate 2 — canary (fleet).**  The serving fleet's hot-swap gate refuses
any payload whose spec carries ``rewriter.blessed = false``
(HTTP 409 / CanaryRefused), so an unblessed variant cannot reach
traffic even if pushed by hand — the double-gated deploy.

Per-variant measured device-step latency, resident params bytes, and
quality deltas are recorded on the execution (and on the output
artifact), so ``selection="auto"`` picks the fastest *blessed* variant
on this host's measured numbers, not on dtype folklore.  With
``aot_warm_buckets > 0`` the selected payload's padded bucket shapes are
AOT-compiled into the serialized-executable cache at export time
(serving/aot.py), so the fleet's canary later deserializes instead of
compiling.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import time
from typing import Any, Dict, List, Optional

import numpy as np

from tpu_pipelines.dsl.component import Parameter, component

log = logging.getLogger("tpu_pipelines.components.rewriter")

VARIANTS_DIR = "variants"
REPORT_FILE = "rewrite_report.json"
NOT_BLESSED_MARKER = "REWRITE_NOT_BLESSED"

# Canonical variant names = payload dtype strings; common aliases accepted
# at the parameter surface.
_ALIASES = {
    "bf16": "bfloat16",
    "int8": "aqt_int8",
    "f32": "float32",
    "fp32": "float32",
}
KNOWN_VARIANTS = ("float32", "bfloat16", "aqt_int8")

# Spec keys export_model owns; everything else in the source spec is
# carried over onto each variant payload verbatim.
_SPEC_OWNED = (
    "format", "hyperparameters", "has_transform", "dtype",
    "params_bytes",  # tpp: disable=TPP214 (payload key)
)


def canonical_variant(name: str) -> str:
    name = str(name).strip().lower()
    name = _ALIASES.get(name, name)
    if name not in KNOWN_VARIANTS:
        raise ValueError(
            f"unknown rewriter variant {name!r}; known: "
            f"{list(KNOWN_VARIANTS)} (aliases: {sorted(_ALIASES)})"
        )
    return name


def _copy_payload(src: str, dst: str) -> None:
    """Hardlink-copy the payload files of ``src`` into ``dst`` (falls back
    to byte copies across filesystems).  Only payload entries move — a
    Rewriter artifact root never recursively swallows its own
    ``variants/`` tree."""
    from tpu_pipelines.trainer.export import (
        CHECKPOINT_DIR, MODULE_COPY, SPEC_FILE, TRANSFORM_DIR,
    )

    os.makedirs(dst, exist_ok=True)
    for entry in (SPEC_FILE, MODULE_COPY, CHECKPOINT_DIR, TRANSFORM_DIR,
                  NOT_BLESSED_MARKER):
        s = os.path.join(src, entry)
        d = os.path.join(dst, entry)
        if not os.path.exists(s):
            continue
        if os.path.isdir(d):
            shutil.rmtree(d)
        elif os.path.exists(d):
            os.unlink(d)
        if os.path.isdir(s):
            shutil.copytree(s, d, copy_function=_link_or_copy)
        else:
            _link_or_copy(s, d)


def _link_or_copy(src: str, dst: str) -> None:
    try:
        os.link(src, dst)
    except OSError:
        shutil.copy2(src, dst)


def _annotate_spec(payload_dir: str, verdict: Dict[str, Any]) -> None:
    """Record the rewrite verdict in the payload's own spec — what the
    fleet's canary gate reads (gate 2), travelling WITH the payload
    through any Pusher copy."""
    from tpu_pipelines.trainer.export import SPEC_FILE

    path = os.path.join(payload_dir, SPEC_FILE)
    with open(path) as f:
        spec = json.load(f)
    spec["rewriter"] = verdict
    with open(path, "w") as f:
        json.dump(spec, f, indent=2, sort_keys=True, default=str)
    marker = os.path.join(payload_dir, NOT_BLESSED_MARKER)
    if verdict.get("blessed") is False:
        with open(marker, "w") as f:
            json.dump({"reason": verdict.get("reason", "")}, f)
    elif os.path.exists(marker):
        os.unlink(marker)


def variant_blessed(payload_dir: str) -> bool:
    """False only when the payload carries an explicit refused verdict
    (plain payloads without a rewriter block are not gated here)."""
    from tpu_pipelines.trainer.export import SPEC_FILE

    if os.path.exists(os.path.join(payload_dir, NOT_BLESSED_MARKER)):
        return False
    try:
        with open(os.path.join(payload_dir, SPEC_FILE)) as f:
            spec = json.load(f)
    except (OSError, ValueError):
        return True
    rewrite = spec.get("rewriter")
    return not (isinstance(rewrite, dict) and rewrite.get("blessed") is False)


def _measure_latency_ms(
    predict, batch: Dict[str, np.ndarray], iters: int
) -> float:
    """Mean wall of one device step at the measurement batch (host fetch
    included — that is what a serving request pays)."""
    np.asarray(predict(batch))  # compile
    np.asarray(predict(batch))  # and once warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = predict(batch)
    np.asarray(out)
    return (time.perf_counter() - t0) / max(1, iters) * 1e3


def _emit_variant(
    name: str,
    model_uri: str,
    vdir: str,
    spec: Dict[str, Any],
    min_quant_size: int,
) -> Dict[str, Any]:
    """Write one variant payload; returns JSON-native emission info."""
    import jax.numpy as jnp

    from tpu_pipelines.trainer import quantize as qz
    from tpu_pipelines.trainer.export import (
        MODULE_COPY, TRANSFORM_DIR, export_model, restore_exported_params,
    )

    if name == "float32":
        _copy_payload(model_uri, vdir)
        return {}
    params = restore_exported_params(model_uri)
    quant_report: Dict[str, Any] = {}
    if name == "bfloat16":
        params = qz.cast_params(params, jnp.bfloat16)
    else:  # aqt_int8
        params, quant_report = qz.quantize_params(
            params, min_size=min_quant_size
        )
    extra = {
        k: v for k, v in spec.items() if k not in _SPEC_OWNED
    }
    export_model(
        serving_model_dir=vdir,
        params=params,
        module_file=os.path.join(model_uri, MODULE_COPY),
        hyperparameters=spec.get("hyperparameters") or {},
        transform_graph_uri=(
            os.path.join(model_uri, TRANSFORM_DIR)
            if spec.get("has_transform") else ""
        ),
        extra_spec=extra,
        serving_dtype=name,
    )
    return quant_report


@component(
    inputs={
        "model": "Model",
        "examples": "Examples",
        "transform_graph": "TransformGraph",
    },
    optional_inputs=("examples", "transform_graph"),
    outputs={"model": "Model"},
    parameters={
        # Variants to emit beyond the always-present float32 reference.
        "variants": Parameter(type=list, default=["bfloat16", "aqt_int8"]),
        # Gate 1: worst relative metric delta a variant may show vs the
        # float payload on the eval slice (evaluator.metric_deltas).
        "quality_tolerance": Parameter(type=float, default=0.02),
        # None = every metric the problem's surface emits; or a list of
        # metric names to gate on (e.g. ["accuracy", "auc"]).
        "quality_metrics": Parameter(type=list, default=None),
        # Evaluator-surface knobs (required when `examples` is wired).
        "label_key": Parameter(type=str, default=""),
        "problem": Parameter(type=str, default="binary_classification"),
        "eval_split": Parameter(type=str, default="eval"),
        "batch_size": Parameter(type=int, default=512),
        # Eval-slice cap: the gate needs a stable metric estimate, not a
        # full eval pass (0 = whole split).
        "max_eval_examples": Parameter(type=int, default=4096),
        # "auto" = fastest blessed variant by measured latency; or pin a
        # canonical/alias variant name.
        "selection": Parameter(type=str, default="auto"),
        "min_quant_size": Parameter(type=int, default=4096),
        "latency_batch_size": Parameter(type=int, default=8),
        "latency_iters": Parameter(type=int, default=20),
        # > 0: AOT-compile the selected payload's padded buckets up to
        # this max batch size into the serialized-executable cache NOW,
        # so the fleet's canary deserializes instead of compiling.
        "aot_warm_buckets": Parameter(type=int, default=0),
    },
    resource_class="tpu",
)
def Rewriter(ctx):
    from tpu_pipelines.components.evaluator import (
        evaluate_payload,
        max_metric_delta,
        metric_deltas,
    )
    from tpu_pipelines.data.input_pipeline import BatchIterator, InputConfig
    from tpu_pipelines.trainer.export import load_exported_model

    props = ctx.exec_properties
    model_uri = ctx.input("model").uri
    out_art = ctx.output("model")
    os.makedirs(out_art.uri, exist_ok=True)
    tolerance = float(props["quality_tolerance"])
    names = ["float32"]
    for v in props["variants"] or []:
        v = canonical_variant(v)
        if v not in names:
            names.append(v)
    selection = str(props["selection"] or "auto").strip().lower()
    if selection != "auto":
        selection = canonical_variant(selection)
        if selection not in names:
            raise ValueError(
                f"selection={selection!r} is not among emitted variants "
                f"{names}"
            )

    examples = ctx.inputs.get("examples")
    examples_uri = examples[0].uri if examples else ""
    if examples_uri and not props["label_key"]:
        raise ValueError(
            "Rewriter: label_key is required when examples are wired "
            "(the quality gate runs the Evaluator metric surface)"
        )
    eval_props = {
        "label_key": props["label_key"],
        "problem": props["problem"],
        "eval_split": props["eval_split"],
        "batch_size": props["batch_size"],
        "slice_columns": (),
        "max_eval_examples": props["max_eval_examples"],
    }

    with open(os.path.join(
        model_uri, "model_spec.json"
    )) as f:
        src_spec = json.load(f)

    # Latency/warmup batch: one eval batch MINUS the label column — the
    # serving request surface.  Keeping the label out matters beyond
    # hygiene: the AOT executable table keys on the exact batch
    # signature, so prewarming with an extra column would compile
    # programs no serving request can ever dispatch.
    latency_batch = None
    if examples_uri:
        it = BatchIterator(
            examples_uri, props["eval_split"],
            InputConfig(
                batch_size=int(props["latency_batch_size"]),
                shuffle=False, num_epochs=1, drop_remainder=False,
            ),
        )
        first = next(iter(it), None)
        if first is not None:
            latency_batch = {
                k: v for k, v in first.items() if k != props["label_key"]
            }

    base_metrics: Optional[Dict[str, float]] = None
    if examples_uri:
        base_metrics = evaluate_payload(
            model_uri, examples_uri, eval_props
        ).overall().metrics

    variants: Dict[str, Dict[str, Any]] = {}
    quality_keys = props["quality_metrics"]
    for name in names:
        vdir = os.path.join(out_art.uri, VARIANTS_DIR, name)
        quant_report = _emit_variant(
            name, model_uri, vdir, src_spec, int(props["min_quant_size"])
        )
        loaded = load_exported_model(vdir)
        info: Dict[str, Any] = {
            "dtype": loaded.dtype,
            "params_bytes": int(loaded.params_bytes),  # tpp: disable=TPP214 (payload key)
        }
        if quant_report:
            info["num_quantized_leaves"] = quant_report.get(
                "num_quantized", 0
            )
        if latency_batch is not None:
            info["latency_ms"] = round(_measure_latency_ms(
                loaded.predict_transformed, latency_batch,
                int(props["latency_iters"]),
            ), 4)
        if name == "float32":
            blessed, reason, deltas = True, "", {}
            info["metrics"] = base_metrics
        elif base_metrics is None:
            blessed = False
            reason = (
                "no eval examples wired: the quality gate fails closed"
            )
            deltas = {}
        else:
            outcome = evaluate_payload(vdir, examples_uri, eval_props)
            metrics = outcome.overall().metrics
            deltas = metric_deltas(base_metrics, metrics, quality_keys)
            worst = max_metric_delta(deltas)
            blessed = worst <= tolerance
            reason = (
                "" if blessed else
                f"max metric delta {worst:.4f} > quality_tolerance "
                f"{tolerance}"
            )
            info["metrics"] = metrics
        info.update({
            "blessed": blessed,
            "quality_deltas": {
                k: round(v, 6) for k, v in sorted(deltas.items())
            },
            "max_quality_delta": round(max_metric_delta(deltas), 6),
        })
        if reason:
            info["reason"] = reason
        _annotate_spec(vdir, {
            "variant": name,
            "blessed": blessed,
            "reason": reason,
            "quality_deltas": info["quality_deltas"],
            "max_quality_delta": info["max_quality_delta"],
            "quality_tolerance": tolerance,
            "base_model_uri": model_uri,
        })
        variants[name] = info
        if not blessed:
            log.warning(
                "rewriter: variant %s NOT_BLESSED (%s)", name, reason
            )

    if selection == "auto":
        blessed_names = [n for n in names if variants[n]["blessed"]]
        if all(
            variants[n].get("latency_ms") is not None
            for n in blessed_names
        ):
            selected = min(
                blessed_names, key=lambda n: variants[n]["latency_ms"]
            )
        else:
            selected = "float32"
    else:
        selected = selection
        if not variants[selected]["blessed"]:
            raise ValueError(
                f"selection={selected!r} failed the quality gate: "
                f"{variants[selected].get('reason', '')}"
            )
    _copy_payload(
        os.path.join(out_art.uri, VARIANTS_DIR, selected), out_art.uri
    )

    speedup = None
    if (
        variants[selected].get("latency_ms")
        and variants["float32"].get("latency_ms")
    ):
        speedup = round(
            variants["float32"]["latency_ms"]
            / variants[selected]["latency_ms"], 4,
        )

    aot_stats = None
    if int(props["aot_warm_buckets"] or 0) > 0 and latency_batch is not None:
        from tpu_pipelines.serving import aot

        selected_loaded = load_exported_model(out_art.uri)
        aot_stats = aot.warm_loaded(
            selected_loaded, latency_batch,
            int(props["aot_warm_buckets"]), raw=False,
        )

    report = {
        "selected_variant": selected,
        "quality_tolerance": tolerance,
        "variants": variants,
        "speedup_vs_float": speedup,
        "base_model_uri": model_uri,
    }
    if aot_stats is not None:
        report["aot_warm"] = aot_stats
    with open(os.path.join(out_art.uri, REPORT_FILE), "w") as f:
        json.dump(report, f, indent=2, sort_keys=True, default=str)
    out_art.properties.update({
        "selected_variant": selected,
        "dtype": variants[selected]["dtype"],
        "params_bytes": variants[selected]["params_bytes"],  # tpp: disable=TPP214 (payload key)
        "blessed_variants": [
            n for n in names if variants[n]["blessed"]
        ],
    })
    return report


def variant_dirs(model_uri: str) -> Dict[str, str]:
    """Variant-name -> payload-dir map of a Rewriter output artifact
    (empty for plain Model payloads)."""
    root = os.path.join(model_uri, VARIANTS_DIR)
    if not os.path.isdir(root):
        return {}
    return {
        name: os.path.join(root, name)
        for name in sorted(os.listdir(root))
        if os.path.isdir(os.path.join(root, name))
    }
