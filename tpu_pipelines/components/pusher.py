"""Pusher: atomically publish a blessed model to the serving destination.

Capability match for TFX Pusher (SURVEY.md §2a row 10): checks the
Evaluator's (and optionally InfraValidator's) blessing, then copies the model
payload into a monotonically-versioned directory under ``push_destination``
— staged to a temp dir and renamed, so a serving binary watching the
directory never sees a partial version (the TF Serving version-dir
convention).

Push-is-deploy (ROADMAP item 4 seam): with ``serving_push_url`` set (or env
``TPP_SERVING_PUSH_URL``), a successful push also POSTs the serving tier's
``:reload`` route, so a live ModelServer/fleet hot-swaps to the new version
immediately instead of waiting out its poll interval.  The notify is
best-effort by design — the version is already durably on disk and the
server's file watcher WILL pick it up, so a notify failure (or a fleet
canary refusing the version: HTTP 409) is recorded on the execution, never
a push failure.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import time

from tpu_pipelines.dsl.component import Parameter, component

log = logging.getLogger("tpu_pipelines.components.pusher")

# "push-URL" env rung: the serving tier's model endpoint, e.g.
# http://serving:8501/v1/models/taxi — the component parameter wins.
ENV_PUSH_URL = "TPP_SERVING_PUSH_URL"


def notify_serving(push_url: str, timeout: float = 120.0) -> dict:
    """POST ``<push_url>:reload`` and return the notify verdict dict.

    Returns ``{"notified": bool, "version" | "error": ...}``; transient
    connection faults retry with backoff (the InfraValidator urlopen
    policy), an HTTP verdict (including a 409 canary refusal) is final.
    """
    import urllib.error
    import urllib.request

    from tpu_pipelines.components.infra_validator import _urlopen_backoff

    url = push_url.rstrip("/")
    if not url.endswith(":reload"):
        url += ":reload"
    req = urllib.request.Request(url, data=b"{}", method="POST")
    try:
        with _urlopen_backoff(req, timeout=timeout) as r:
            payload = json.load(r)
        return {"notified": True, "version": payload.get("version")}
    except urllib.error.HTTPError as e:
        body = ""
        try:
            body = e.read().decode("utf-8", "replace")[:500]
        except Exception:  # noqa: BLE001
            pass
        return {"notified": False, "error": f"HTTP {e.code}: {body}"}
    except Exception as e:  # noqa: BLE001 — server down/unreachable
        return {"notified": False, "error": f"{type(e).__name__}: {e}"}


@component(
    inputs={
        "model": "Model",
        "blessing": "ModelBlessing",
        "infra_blessing": "InfraBlessing",
        # Training-data lineage (ISSUE 20): wire the training run's
        # statistics/schema and the Pusher stamps their URIs onto the
        # pushed payload's model_spec.json — the serving fleet's live
        # drift baseline, resolved with zero metadata-store walks.
        "statistics": "ExampleStatistics",
        "schema": "Schema",
    },
    optional_inputs=("blessing", "infra_blessing", "statistics", "schema"),
    is_sink=True,
    outputs={"pushed_model": "PushedModel"},
    parameters={
        "push_destination": Parameter(type=str, required=True),
        # Live-fleet reload hook: "" = env TPP_SERVING_PUSH_URL, else off.
        "serving_push_url": Parameter(type=str, default=""),
        # Rewriter variant selection: "" pushes the model payload root
        # (a Rewriter artifact's root IS its selected variant); a
        # variant name ("aqt_int8" / "bfloat16" / "float32", aliases ok)
        # pushes that payload from the artifact's variants/ tree — and
        # honors the Rewriter's quality gate: an unblessed variant is a
        # skipped push, never a served model.
        "variant": Parameter(type=str, default=""),
    },
)
def Pusher(ctx):
    from tpu_pipelines.components.evaluator import is_blessed

    pushed_art = ctx.output("pushed_model")
    os.makedirs(pushed_art.uri, exist_ok=True)

    for key in ("blessing", "infra_blessing"):
        if ctx.inputs.get(key) and not is_blessed(ctx.input(key).uri):
            pushed_art.properties["pushed"] = False
            pushed_art.properties["skip_reason"] = f"{key} = NOT_BLESSED"
            return {"pushed": False, "skip_reason": f"{key} = NOT_BLESSED"}

    model_uri = ctx.input("model").uri
    variant = str(ctx.exec_properties.get("variant") or "").strip()
    if variant:
        from tpu_pipelines.components.rewriter import (
            canonical_variant,
            variant_blessed,
            variant_dirs,
        )

        variant = canonical_variant(variant)
        dirs = variant_dirs(model_uri)
        if variant not in dirs:
            raise ValueError(
                f"Pusher: variant {variant!r} not found under "
                f"{model_uri!r} (have {sorted(dirs) or 'no variants/'}); "
                "wire the Pusher to a Rewriter output"
            )
        if not variant_blessed(dirs[variant]):
            skip = f"variant {variant} = NOT_BLESSED"
            pushed_art.properties["pushed"] = False
            pushed_art.properties["skip_reason"] = skip
            return {"pushed": False, "skip_reason": skip}
        model_uri = dirs[variant]
        pushed_art.properties["variant"] = variant

    dest = ctx.exec_properties["push_destination"]
    os.makedirs(dest, exist_ok=True)
    existing = [int(d) for d in os.listdir(dest) if d.isdigit()]
    version = max(existing, default=int(time.time()) - 1) + 1

    staging = os.path.join(dest, f".staging-{version}")
    if os.path.exists(staging):
        shutil.rmtree(staging)
    shutil.copytree(model_uri, staging)
    # Stamp training-data lineage into the STAGING copy, before the atomic
    # rename — a watcher never sees a half-stamped payload.  The export-time
    # spec keys (trainer modules calling export_model(training_*_uri=...))
    # survive when the Pusher has nothing wired.
    stamped = {}
    if ctx.inputs.get("statistics"):
        stamped["training_statistics_uri"] = ctx.input("statistics").uri
    if ctx.inputs.get("schema"):
        stamped["training_schema_uri"] = ctx.input("schema").uri
    if stamped:
        from tpu_pipelines.trainer.export import SPEC_FILE

        spec_path = os.path.join(staging, SPEC_FILE)
        try:
            with open(spec_path) as f:
                spec = json.load(f)
            spec.update(stamped)
            with open(spec_path, "w") as f:
                json.dump(spec, f, indent=2, sort_keys=True, default=str)
            pushed_art.properties.update(stamped)
        except (OSError, ValueError) as e:
            # A payload without a readable spec isn't loadable by the
            # fleet anyway; surface the miss, don't fail the push.
            log.warning(
                "could not stamp training lineage onto %s: %s", spec_path, e
            )
    final = os.path.join(dest, str(version))
    os.rename(staging, final)  # atomic within a filesystem

    with open(os.path.join(pushed_art.uri, "pushed_version.txt"), "w") as f:
        f.write(f"{final}\n")
    pushed_art.properties.update(
        {"pushed": True, "pushed_version": version, "pushed_destination": final}
    )
    result = {"pushed": True, "pushed_version": version, "destination": final}

    push_url = (
        ctx.exec_properties.get("serving_push_url")
        or os.environ.get(ENV_PUSH_URL, "")
    ).strip()
    if push_url:
        notify = notify_serving(push_url)
        if notify["notified"]:
            result["reload_notified"] = True
            result["reload_version"] = notify.get("version")
            # On the artifact too: the continuous controller's deploy
            # observation matches THIS id against the fleet's quarantine
            # list without re-deriving it from the destination path.
            pushed_art.properties["reload_version"] = notify.get("version")
        else:
            # Best-effort: the push is durable and the server's poll will
            # converge on it; surface the miss, don't fail the node.
            log.warning(
                "pushed version %s but serving notify to %r failed: %s",
                version, push_url, notify.get("error"),
            )
            result["reload_notified"] = False
            result["reload_error"] = notify.get("error")
        pushed_art.properties["reload_notified"] = result["reload_notified"]
    return result
