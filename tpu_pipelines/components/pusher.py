"""Pusher: atomically publish a blessed model to the serving destination.

Capability match for TFX Pusher (SURVEY.md §2a row 10): checks the
Evaluator's (and optionally InfraValidator's) blessing, then copies the model
payload into a monotonically-versioned directory under ``push_destination``
— staged to a temp dir and renamed, so a serving binary watching the
directory never sees a partial version (the TF Serving version-dir
convention).
"""

from __future__ import annotations

import os
import shutil
import time

from tpu_pipelines.dsl.component import Parameter, component


@component(
    inputs={
        "model": "Model",
        "blessing": "ModelBlessing",
        "infra_blessing": "InfraBlessing",
    },
    optional_inputs=("blessing", "infra_blessing"),
    is_sink=True,
    outputs={"pushed_model": "PushedModel"},
    parameters={
        "push_destination": Parameter(type=str, required=True),
    },
)
def Pusher(ctx):
    from tpu_pipelines.components.evaluator import is_blessed

    pushed_art = ctx.output("pushed_model")
    os.makedirs(pushed_art.uri, exist_ok=True)

    for key in ("blessing", "infra_blessing"):
        if ctx.inputs.get(key) and not is_blessed(ctx.input(key).uri):
            pushed_art.properties["pushed"] = False
            pushed_art.properties["skip_reason"] = f"{key} = NOT_BLESSED"
            return {"pushed": False, "skip_reason": f"{key} = NOT_BLESSED"}

    dest = ctx.exec_properties["push_destination"]
    os.makedirs(dest, exist_ok=True)
    existing = [int(d) for d in os.listdir(dest) if d.isdigit()]
    version = max(existing, default=int(time.time()) - 1) + 1

    staging = os.path.join(dest, f".staging-{version}")
    if os.path.exists(staging):
        shutil.rmtree(staging)
    shutil.copytree(ctx.input("model").uri, staging)
    final = os.path.join(dest, str(version))
    os.rename(staging, final)  # atomic within a filesystem

    with open(os.path.join(pushed_art.uri, "pushed_version.txt"), "w") as f:
        f.write(f"{final}\n")
    pushed_art.properties.update(
        {"pushed": True, "pushed_version": version, "pushed_destination": final}
    )
    return {"pushed": True, "pushed_version": version, "destination": final}
