"""Importer: register external data as a typed artifact (TFX ImporterNode).

Capability match for ``tfx.dsl.Importer`` (the workshop's notebooks use it
to feed a hand-curated Schema or pre-existing Examples into a pipeline).
The node's executor does NOT copy: it re-points its output artifact's uri
at ``source_uri``, so downstream components consume the external payload in
place while metadata gains a first-class artifact for lineage.

Freshness beats TFX's ``reimport`` flag: ``source_uri`` is an external
input parameter, so its CONTENT is fingerprinted into the execution cache
key — editing the external data re-imports automatically; unchanged data
is a cache hit.

::

    schema = Importer(
        source_uri="/data/curated_schema",
        artifact_type="Schema",
    )
    transform = Transform(..., schema=schema.outputs["result"])
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Type

from tpu_pipelines.dsl.component import Component, Parameter, component

_CLASS_CACHE: Dict[str, Type[Component]] = {}


def _importer_class(artifact_type: str) -> Type[Component]:
    cls = _CLASS_CACHE.get(artifact_type)
    if cls is not None:
        return cls
    # Importing is exactly where types outside the standard taxonomy enter
    # a pipeline; unknown names register as custom artifact types.
    from tpu_pipelines.dsl.artifact_types import register_artifact_type

    register_artifact_type(
        artifact_type, f"External data imported as {artifact_type}."
    )

    @component(
        outputs={"result": artifact_type},
        parameters={
            "source_uri": Parameter(type=str, required=True),
            # Extra artifact properties to publish (e.g. split_names when
            # importing an Examples layout).
            "properties": Parameter(type=dict, default=None),
        },
        name=f"Importer[{artifact_type}]",
        external_input_parameters=("source_uri",),
    )
    def _Importer(ctx):
        src = os.path.abspath(ctx.exec_properties["source_uri"])
        if not os.path.exists(src):
            raise FileNotFoundError(
                f"Importer source_uri {src!r} does not exist"
            )
        art = ctx.output("result")
        # Point the artifact at the external payload in place (no copy);
        # the publisher fingerprints THIS uri, so downstream cache keys
        # track the external content.
        art.uri = src
        art.properties.update(ctx.exec_properties["properties"] or {})
        return {"imported_uri": src}

    _CLASS_CACHE[artifact_type] = _Importer
    return _Importer


def Importer(
    *,
    source_uri: str,
    artifact_type: str,
    instance_name: str = "",
    properties: Optional[Dict[str, Any]] = None,
) -> Component:
    """Build an Importer node for ``artifact_type`` (output key: "result")."""
    cls = _importer_class(artifact_type)
    return cls(
        instance_name=instance_name or f"Importer.{artifact_type}",
        source_uri=source_uri,
        properties=properties,
    )
