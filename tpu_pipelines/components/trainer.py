"""Trainer component: runs user run_fn(FnArgs) and records throughput.

Capability match for TFX Trainer's GenericExecutor (SURVEY.md §2a row 6,
§3.3): imports ``module_file``, builds ``FnArgs`` from resolved artifacts,
invokes ``run_fn``, and records the measurement-harness numbers
(examples/sec, examples/sec/chip — the BASELINE headline metric) as execution
properties in the metadata store.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict

from tpu_pipelines.dsl.component import Parameter, component
from tpu_pipelines.trainer.fn_args import TrainResult, resolve_fn_args
from tpu_pipelines.utils.module_loader import load_fn


@component(
    inputs={
        "examples": "Examples",
        "transform_graph": "TransformGraph",
        "schema": "Schema",
        "hyperparameters": "HyperParameters",
        # Warm-start base model (TFX base_model input).
        "base_model": "Model",
    },
    optional_inputs=("transform_graph", "schema", "hyperparameters", "base_model"),
    outputs={"model": "Model", "model_run": "ModelRun"},
    parameters={
        "module_file": Parameter(type=str, required=True),
        "train_steps": Parameter(type=int, default=1000),
        "eval_steps": Parameter(type=int, default=0),
        "hyperparameters": Parameter(type=dict, default=None),
        "mesh": Parameter(type=dict, default=None),
        "custom_config": Parameter(type=dict, default=None),
    },
    external_input_parameters=("module_file",),
    resource_class="tpu",
    lint_module_fns=("run_fn",),
)
def Trainer(ctx):
    run_fn = load_fn(ctx.exec_properties["module_file"], "run_fn")

    hyperparameters: Dict[str, Any] = dict(
        ctx.exec_properties["hyperparameters"] or {}
    )
    if ctx.inputs.get("hyperparameters"):
        # Tuner-produced artifact overrides literal hyperparameters.
        hp_uri = ctx.input("hyperparameters").uri
        with open(os.path.join(hp_uri, "best_hyperparameters.json")) as f:
            hyperparameters.update(json.load(f))

    custom_config = dict(ctx.exec_properties["custom_config"] or {})
    if ctx.inputs.get("base_model"):
        custom_config["base_model_uri"] = ctx.input("base_model").uri

    fn_args = resolve_fn_args(
        ctx,
        serving_model_dir=ctx.output("model").uri,
        model_run_dir=ctx.output("model_run").uri,
        hyperparameters=hyperparameters,
        train_steps=ctx.exec_properties["train_steps"],
        eval_steps=ctx.exec_properties["eval_steps"],
        mesh=ctx.exec_properties["mesh"],
        custom_config=custom_config,
    )

    result = run_fn(fn_args)
    if result is None:
        result = TrainResult()
    if not isinstance(result, TrainResult):
        raise TypeError(
            f"run_fn must return TrainResult or None, got {type(result).__name__}"
        )

    model_art = ctx.output("model")
    model_art.properties["examples_per_sec_per_chip"] = (
        result.examples_per_sec_per_chip
    )
    props = {
        "examples_per_sec": result.examples_per_sec,
        "examples_per_sec_per_chip": result.examples_per_sec_per_chip,
        "steps_completed": result.steps_completed,
        "resumed_from_step": result.resumed_from_step,
        "goodput": result.goodput,
        "goodput_source": result.goodput_source,
    }
    props.update({f"badput_{k}": v for k, v in result.badput.items()})
    props.update(
        {f"final_{k}": v for k, v in result.final_metrics.items()}
    )
    return props
