"""SchemaGen: infer a Schema from computed statistics.

Capability match for TFX SchemaGen / TFDV ``infer_schema`` (SURVEY.md §2a
row 3).  Inference rules follow TFDV's spirit: feature types from observed
dtypes, presence from observed missing fraction (with slack), categorical
domains for low-cardinality string features, numeric ranges recorded but not
enforced by default.
"""

from __future__ import annotations

from tpu_pipelines.data.schema import Feature, FeatureType, Schema
from tpu_pipelines.data.statistics import load_statistics
from tpu_pipelines.dsl.component import Parameter, component

# A string feature whose distinct-value count is at or below this becomes a
# closed categorical domain.
_DOMAIN_MAX_CARDINALITY = 100


@component(
    inputs={"statistics": "ExampleStatistics"},
    outputs={"schema": "Schema"},
    parameters={
        # Which split to infer from; TFX infers from train.
        "split": Parameter(type=str, default="train"),
        "infer_domains": Parameter(type=bool, default=True),
        "infer_ranges": Parameter(type=bool, default=False),
        # Schema environments (TFDV parity): features listed here — labels,
        # typically — get not_in_environment=["SERVING"], and the schema
        # declares TRAINING/SERVING default environments, so serving-time
        # validation (ExampleValidator(environment="SERVING"), the
        # InfraValidator canary) accepts label-less batches.
        "exclude_at_serving": Parameter(type=list, default=None),
    },
)
def SchemaGen(ctx):
    stats = load_statistics(ctx.input("statistics").uri)
    split = ctx.exec_properties["split"]
    if split not in stats:
        raise ValueError(
            f"split {split!r} not in statistics (have {sorted(stats)})"
        )
    s = stats[split]
    schema = Schema()
    exclude_at_serving = set(
        ctx.exec_properties.get("exclude_at_serving") or ()
    )
    if exclude_at_serving:
        schema.default_environments = ["TRAINING", "SERVING"]
        missing = exclude_at_serving - set(s.features)
        if missing:
            raise ValueError(
                f"exclude_at_serving names unknown features {sorted(missing)}"
            )
    for name, fs in s.features.items():
        feat = Feature(name=name, type=FeatureType(fs.type))
        # Presence with slack: a feature fully present in train is required;
        # one partially present gets its observed presence floored slightly.
        feat.min_presence = 1.0 if fs.num_missing == 0 else max(
            0.0, round(fs.presence * 0.9, 4)
        )
        if (
            ctx.exec_properties["infer_domains"]
            and fs.string is not None
            and fs.string.unique <= _DOMAIN_MAX_CARDINALITY
            # top_values must cover every distinct value for a closed domain.
            and len(fs.string.top_values) >= fs.string.unique
        ):
            feat.domain = sorted(v for v, _ in fs.string.top_values)
        if ctx.exec_properties["infer_ranges"] and fs.numeric is not None:
            feat.min_value = fs.numeric.min
            feat.max_value = fs.numeric.max
        if name in exclude_at_serving:
            feat.not_in_environment = ["SERVING"]
        schema.features[name] = feat
    out = ctx.output("schema")
    schema.save(out.uri)
    out.properties["num_features"] = len(schema.features)
    return {"num_features": len(schema.features)}
