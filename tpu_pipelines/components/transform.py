"""Transform component: analyze once, materialize skew-free features.

Capability match for TFX Transform (SURVEY.md §2a row 5, §3.4): the user's
``preprocessing_fn(inputs, tft)`` (from ``module_file``) builds a column DAG;
a single full pass over the train split resolves analyzers (vocabularies,
moments, quantile boundaries); every split is then materialized through the
resolved graph, and the graph itself is emitted as the ``transform_graph``
artifact that Trainer/Evaluator/serving reuse — identical preprocessing in
training and serving, by construction.
"""

from __future__ import annotations

import os
import shutil

from tpu_pipelines.data import examples_io
from tpu_pipelines.data.schema import Schema
from tpu_pipelines.dsl.component import Parameter, component
from tpu_pipelines.transform.expr import OPS
from tpu_pipelines.transform.graph import TransformGraph
from tpu_pipelines.utils.module_loader import load_fn

MODULE_COPY = "module_file.py"


@component(
    inputs={"examples": "Examples", "schema": "Schema"},
    outputs={
        "transform_graph": "TransformGraph",
        "transformed_examples": "Examples",
    },
    parameters={
        "module_file": Parameter(type=str, required=True),
        # Split used for the analysis full pass (TFX analyzes train).
        "analyze_split": Parameter(type=str, default="train"),
        # Pass through untransformed columns (e.g. raw label) verbatim.
        "passthrough_columns": Parameter(type=list, default=None),
    },
    external_input_parameters=("module_file",),
)
def Transform(ctx):
    module_file = ctx.exec_properties["module_file"]
    preprocessing_fn = load_fn(module_file, "preprocessing_fn")
    schema = Schema.load(ctx.input("schema").uri)
    examples_uri = ctx.input("examples").uri

    graph = TransformGraph.build(preprocessing_fn, schema)

    analyze_split = ctx.exec_properties["analyze_split"]
    splits = examples_io.split_names(examples_uri)
    if analyze_split not in splits:
        raise ValueError(
            f"analyze_split {analyze_split!r} not in {splits}"
        )
    graph.analyze(examples_io.read_split(examples_uri, analyze_split))

    graph_out = ctx.output("transform_graph")
    graph.save(graph_out.uri)
    # Record the user's module source next to the graph for lineage/debugging
    # (the graph is self-contained; this copy is informational).
    shutil.copyfile(module_file, os.path.join(graph_out.uri, MODULE_COPY))
    graph_out.properties["output_features"] = graph.output_feature_names()

    passthrough = ctx.exec_properties["passthrough_columns"] or []
    transformed_out = ctx.output("transformed_examples")
    counts = {}
    for split in splits:
        raw = examples_io.read_split(examples_uri, split)
        cols = graph.apply_host(raw)
        for name in passthrough:
            if name in cols:
                raise ValueError(
                    f"passthrough column {name!r} collides with a transform output"
                )
            cols[name] = raw[name]
        examples_io.write_split(
            transformed_out.uri, split, examples_io.table_from_columns(cols)
        )
        counts[split] = len(next(iter(cols.values())))
    transformed_out.properties["split_names"] = sorted(counts)
    transformed_out.properties["split_counts"] = counts
    return {
        "num_analyzers": sum(
            1 for n in graph.nodes
            if n.op in OPS and OPS[n.op].is_analyzer
        ),
        "output_features": graph.output_feature_names(),
    }
