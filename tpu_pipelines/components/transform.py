"""Transform component: analyze once, materialize skew-free features.

Capability match for TFX Transform (SURVEY.md §2a row 5, §3.4): the user's
``preprocessing_fn(inputs, tft)`` (from ``module_file``) builds a column DAG;
a single full pass over the train split resolves analyzers (vocabularies,
moments, quantile boundaries); every split is then materialized through the
resolved graph, and the graph itself is emitted as the ``transform_graph``
artifact that Trainer/Evaluator/serving reuse — identical preprocessing in
training and serving, by construction.
"""

from __future__ import annotations

import logging
import os
import time
import shutil

from tpu_pipelines.data import examples_io
from tpu_pipelines.data.schema import Schema
from tpu_pipelines.data.shard_plan import thread_map
from tpu_pipelines.dsl.component import Parameter, component
from tpu_pipelines.transform.expr import OPS
from tpu_pipelines.transform.graph import TransformGraph
from tpu_pipelines.utils.module_loader import load_fn

MODULE_COPY = "module_file.py"

log = logging.getLogger(__name__)


@component(
    inputs={"examples": "Examples", "schema": "Schema"},
    outputs={
        "transform_graph": "TransformGraph",
        "transformed_examples": "Examples",
    },
    parameters={
        "module_file": Parameter(type=str, required=True),
        # Split used for the analysis full pass (TFX analyzes train).
        "analyze_split": Parameter(type=str, default="train"),
        # Pass through untransformed columns (e.g. raw label) verbatim.
        "passthrough_columns": Parameter(type=list, default=None),
        # Rows per streamed chunk for analysis + materialization; peak host
        # memory is O(chunk), never O(split).
        "chunk_rows": Parameter(type=int, default=0),  # 0 = row-group size
        # On-chip analyzer reductions: None/"auto" | True | False.
        "analyze_on_chip": Parameter(type=bool, default=None),
        # Materialize through the jitted numeric subgraph on the default
        # jax device (BASELINE: "Transform ... jit_compile=True on-chip").
        # None/"auto" = on when an accelerator is present; host numpy is
        # always the fallback (and the semantics reference).
        "materialize_on_device": Parameter(type=bool, default=None),
    },
    external_input_parameters=("module_file",),
    resource_class="tpu",
    lint_module_fns=("preprocessing_fn",),
)
def Transform(ctx):
    module_file = ctx.exec_properties["module_file"]
    preprocessing_fn = load_fn(module_file, "preprocessing_fn")
    schema = Schema.load(ctx.input("schema").uri)
    examples_uri = ctx.input("examples").uri

    graph = TransformGraph.build(preprocessing_fn, schema)

    analyze_split = ctx.exec_properties["analyze_split"]
    splits = examples_io.split_names(examples_uri)
    if analyze_split not in splits:
        raise ValueError(
            f"analyze_split {analyze_split!r} not in {splits}"
        )
    chunk_rows = (
        ctx.exec_properties["chunk_rows"] or examples_io.DEFAULT_ROW_GROUP
    )

    analyze_rows = 0

    def counted_chunks():
        nonlocal analyze_rows
        for chunk in examples_io.iter_column_chunks(
            examples_uri, analyze_split, rows=chunk_rows
        ):
            if chunk:
                analyze_rows += len(next(iter(chunk.values())))
            yield chunk

    t0 = time.perf_counter()
    graph.analyze_chunks(
        counted_chunks,
        on_chip=ctx.exec_properties["analyze_on_chip"],
    )
    analyze_s = time.perf_counter() - t0

    graph_out = ctx.output("transform_graph")
    graph.save(graph_out.uri)
    # Record the user's module source next to the graph for lineage/debugging
    # (the graph is self-contained; this copy is informational).
    shutil.copyfile(module_file, os.path.join(graph_out.uri, MODULE_COPY))
    graph_out.properties["output_features"] = graph.output_feature_names()

    passthrough = ctx.exec_properties["passthrough_columns"] or []
    transformed_out = ctx.output("transformed_examples")

    on_device = ctx.exec_properties.get("materialize_on_device")
    if on_device is None:
        import jax

        on_device = jax.default_backend() not in ("cpu",)

    def materialize_chunk(raw):
        nonlocal on_device
        if on_device:
            try:
                cols = graph.apply_device(raw)
            except Exception as e:  # noqa: BLE001 — host numpy is authoritative
                log.warning(
                    "device materialization failed (%s); using host numpy", e
                )
                on_device = False
            else:
                if graph.device_apply_active is False:
                    # apply_device decided the graph can't jit (string
                    # interface) and used the host path — record the truth.
                    on_device = False
                return cols
        return graph.apply_host(raw)

    def materialize_shard(task):
        """One shard in, one shard out: apply-fn over the shard's chunks
        into this shard's writer.  Returns (rows, output schema or None)."""
        split, shard, n_shards = task
        writer = None
        schema = None
        n_rows = 0
        try:
            for raw in examples_io.iter_column_chunks(
                examples_uri, split, rows=chunk_rows, shards=[shard]
            ):
                cols = materialize_chunk(raw)
                for name in passthrough:
                    if name in cols:
                        raise ValueError(
                            f"passthrough column {name!r} collides with a "
                            "transform output"
                        )
                    cols[name] = raw[name]
                table = examples_io.table_from_columns(cols)
                if writer is None:
                    schema = table.schema
                    writer = examples_io.open_split_writer(
                        transformed_out.uri, split, schema,
                        shard=shard, num_shards=n_shards,
                    )
                writer.write_table(table)
                n_rows += table.num_rows
        finally:
            if writer is not None:
                writer.close()
        return n_rows, schema

    counts = {}
    split_wall = {}
    shard_counts = {}
    t0 = time.perf_counter()
    for split in splits:
        n_shards = examples_io.num_split_shards(examples_uri, split)
        shard_counts[split] = n_shards
        t_split = time.perf_counter()
        # Output layout mirrors the input layout (shard i in -> shard i
        # out), so per-shard row order — and the concatenated split order —
        # is identical to the sequential single-writer materialization.
        results = thread_map(
            materialize_shard,
            [(split, shard, n_shards) for shard in range(n_shards)],
        )
        schemas = [s for _, s in results if s is not None]
        if schemas:
            # Backfill empty shards (schema-only Parquet) so the shard set
            # stays complete; a fully-empty split writes nothing, matching
            # the legacy single-writer behavior.
            for shard, (n, schema) in enumerate(results):
                if schema is None:
                    examples_io.open_split_writer(
                        transformed_out.uri, split, schemas[0],
                        shard=shard, num_shards=n_shards,
                    ).close()
        counts[split] = sum(n for n, _ in results)
        split_wall[split] = round(time.perf_counter() - t_split, 4)
    materialize_s = time.perf_counter() - t0
    total_rows = sum(counts.values())
    transformed_out.properties["split_names"] = sorted(counts)
    transformed_out.properties["split_counts"] = counts
    # Span lineage rides through (docs/CONTINUOUS.md): per-span transformed
    # examples keep their span identity so the rolling-window resolver can
    # window them exactly like raw Examples (output shard layout already
    # mirrors the input's shard-for-shard).
    for key in ("span", "version"):
        if key in ctx.input("examples").properties:
            transformed_out.properties[key] = (
                ctx.input("examples").properties[key]
            )
    return {
        "num_analyzers": sum(
            1 for n in graph.nodes
            if n.op in OPS and OPS[n.op].is_analyzer
        ),
        "output_features": graph.output_feature_names(),
        # Host data-plane throughput (the Beam-replacement measurement):
        # materialization covers tokenize/vocab/hash + Parquet write.
        "analyze_wall_s": round(analyze_s, 4),
        # Full-pass analysis throughput — the stage the native token-count
        # kernel + pool fan-out accelerate (SURVEY.md §2b Beam row).  The
        # pass may run multiple phases over the split for nested analyzers,
        # so rows here counts every streamed row, re-reads included.
        "analyze_rows_per_sec": (
            round(analyze_rows / analyze_s, 2) if analyze_s > 0 else 0.0
        ),
        "materialize_wall_s": round(materialize_s, 4),
        "materialize_split_wall_s": split_wall,
        "materialize_rows_per_sec": (
            round(total_rows / materialize_s, 2) if materialize_s > 0 else 0.0
        ),
        # Input shard layout per split == output layout (shard i -> shard i).
        "data_shards": shard_counts,
        # True = every chunk went through the jitted device path (a mid-run
        # fallback to host numpy flips this off).
        "materialize_on_device": bool(on_device),
    }
