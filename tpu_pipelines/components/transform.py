"""Transform component: analyze once, materialize skew-free features.

Capability match for TFX Transform (SURVEY.md §2a row 5, §3.4): the user's
``preprocessing_fn(inputs, tft)`` (from ``module_file``) builds a column DAG;
a single full pass over the train split resolves analyzers (vocabularies,
moments, quantile boundaries); every split is then materialized through the
resolved graph, and the graph itself is emitted as the ``transform_graph``
artifact that Trainer/Evaluator/serving reuse — identical preprocessing in
training and serving, by construction.
"""

from __future__ import annotations

import os
import time
import shutil

from tpu_pipelines.data import examples_io
from tpu_pipelines.data.schema import Schema
from tpu_pipelines.dsl.component import Parameter, component
from tpu_pipelines.transform.expr import OPS
from tpu_pipelines.transform.graph import TransformGraph
from tpu_pipelines.utils.module_loader import load_fn

MODULE_COPY = "module_file.py"


@component(
    inputs={"examples": "Examples", "schema": "Schema"},
    outputs={
        "transform_graph": "TransformGraph",
        "transformed_examples": "Examples",
    },
    parameters={
        "module_file": Parameter(type=str, required=True),
        # Split used for the analysis full pass (TFX analyzes train).
        "analyze_split": Parameter(type=str, default="train"),
        # Pass through untransformed columns (e.g. raw label) verbatim.
        "passthrough_columns": Parameter(type=list, default=None),
        # Rows per streamed chunk for analysis + materialization; peak host
        # memory is O(chunk), never O(split).
        "chunk_rows": Parameter(type=int, default=0),  # 0 = row-group size
        # On-chip analyzer reductions: None/"auto" | True | False.
        "analyze_on_chip": Parameter(type=bool, default=None),
    },
    external_input_parameters=("module_file",),
)
def Transform(ctx):
    module_file = ctx.exec_properties["module_file"]
    preprocessing_fn = load_fn(module_file, "preprocessing_fn")
    schema = Schema.load(ctx.input("schema").uri)
    examples_uri = ctx.input("examples").uri

    graph = TransformGraph.build(preprocessing_fn, schema)

    analyze_split = ctx.exec_properties["analyze_split"]
    splits = examples_io.split_names(examples_uri)
    if analyze_split not in splits:
        raise ValueError(
            f"analyze_split {analyze_split!r} not in {splits}"
        )
    chunk_rows = (
        ctx.exec_properties["chunk_rows"] or examples_io.DEFAULT_ROW_GROUP
    )

    t0 = time.perf_counter()
    graph.analyze_chunks(
        lambda: examples_io.iter_column_chunks(
            examples_uri, analyze_split, rows=chunk_rows
        ),
        on_chip=ctx.exec_properties["analyze_on_chip"],
    )
    analyze_s = time.perf_counter() - t0

    graph_out = ctx.output("transform_graph")
    graph.save(graph_out.uri)
    # Record the user's module source next to the graph for lineage/debugging
    # (the graph is self-contained; this copy is informational).
    shutil.copyfile(module_file, os.path.join(graph_out.uri, MODULE_COPY))
    graph_out.properties["output_features"] = graph.output_feature_names()

    passthrough = ctx.exec_properties["passthrough_columns"] or []
    transformed_out = ctx.output("transformed_examples")
    counts = {}
    t0 = time.perf_counter()
    for split in splits:
        writer = None
        n_rows = 0
        try:
            for raw in examples_io.iter_column_chunks(
                examples_uri, split, rows=chunk_rows
            ):
                cols = graph.apply_host(raw)
                for name in passthrough:
                    if name in cols:
                        raise ValueError(
                            f"passthrough column {name!r} collides with a "
                            "transform output"
                        )
                    cols[name] = raw[name]
                table = examples_io.table_from_columns(cols)
                if writer is None:
                    writer = examples_io.open_split_writer(
                        transformed_out.uri, split, table.schema
                    )
                writer.write_table(table)
                n_rows += table.num_rows
        finally:
            if writer is not None:
                writer.close()
        counts[split] = n_rows
    materialize_s = time.perf_counter() - t0
    total_rows = sum(counts.values())
    transformed_out.properties["split_names"] = sorted(counts)
    transformed_out.properties["split_counts"] = counts
    return {
        "num_analyzers": sum(
            1 for n in graph.nodes
            if n.op in OPS and OPS[n.op].is_analyzer
        ),
        "output_features": graph.output_feature_names(),
        # Host data-plane throughput (the Beam-replacement measurement):
        # materialization covers tokenize/vocab/hash + Parquet write.
        "analyze_wall_s": round(analyze_s, 4),
        "materialize_wall_s": round(materialize_s, 4),
        "materialize_rows_per_sec": (
            round(total_rows / materialize_s, 2) if materialize_s > 0 else 0.0
        ),
    }
