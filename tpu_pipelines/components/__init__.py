"""Built-in pipeline components — the capability surface of SURVEY.md §2a.

ExampleGen → StatisticsGen → SchemaGen → ExampleValidator → Transform →
Trainer (+Tuner) → Evaluator → Rewriter → InfraValidator → Pusher, plus
BulkInferrer.
"""

from tpu_pipelines.components.example_gen import (  # noqa: F401
    CsvExampleGen,
    ImportExampleGen,
)
from tpu_pipelines.components.statistics_gen import StatisticsGen  # noqa: F401
from tpu_pipelines.components.schema_gen import SchemaGen  # noqa: F401
from tpu_pipelines.components.example_validator import ExampleValidator  # noqa: F401
from tpu_pipelines.components.transform import Transform  # noqa: F401
from tpu_pipelines.components.trainer import Trainer  # noqa: F401
from tpu_pipelines.components.tuner import Tuner  # noqa: F401
from tpu_pipelines.components.evaluator import Evaluator  # noqa: F401
from tpu_pipelines.components.rewriter import Rewriter  # noqa: F401
from tpu_pipelines.components.pusher import Pusher  # noqa: F401
from tpu_pipelines.components.bulk_inferrer import BulkInferrer  # noqa: F401
from tpu_pipelines.components.infra_validator import InfraValidator  # noqa: F401
from tpu_pipelines.components.resolver import (  # noqa: F401
    Resolver,
    RollingWindowResolver,
)
from tpu_pipelines.components.importer import Importer  # noqa: F401
