"""Tuner component: hyperparameter search over the Trainer's run_fn.

Capability match for TFX Tuner + the workshop's Katib HPO (SURVEY.md §2a
row 7, §2b Katib row): trials run the same ``run_fn(FnArgs)`` contract the
Trainer uses — no separate tuning API — with grid or random candidate
generation, and the winner is emitted as a ``HyperParameters`` artifact whose
``best_hyperparameters.json`` the Trainer merges over its own defaults.

On-chip efficiency note: trials run sequentially in-process, each a fresh
jit; identical shapes across trials hit XLA's compilation cache, so later
trials pay only run time.  (Katib's parallel-pod fan-out belongs to the
cluster runner; the emitted spec can schedule trials as separate TPUJobs.)
"""

from __future__ import annotations

import itertools
import json
import os
import random
from typing import Any, Dict, List

from tpu_pipelines.dsl.component import Parameter, component
from tpu_pipelines.trainer.fn_args import TrainResult, resolve_fn_args
from tpu_pipelines.utils.module_loader import load_fn, load_module

BEST_FILE = "best_hyperparameters.json"
TRIALS_FILE = "trials.json"


def _grid(space: Dict[str, List[Any]]) -> List[Dict[str, Any]]:
    keys = sorted(space)
    return [
        dict(zip(keys, combo))
        for combo in itertools.product(*(space[k] for k in keys))
    ]


def _random(space: Dict[str, List[Any]], n: int, seed: int) -> List[Dict[str, Any]]:
    rng = random.Random(seed)
    keys = sorted(space)
    seen = set()
    out: List[Dict[str, Any]] = []
    # Bounded rejection sampling; falls back to duplicates-allowed if the
    # space is smaller than n.
    attempts = 0
    while len(out) < n and attempts < 50 * n:
        cand = {k: rng.choice(space[k]) for k in keys}
        key = json.dumps(cand, sort_keys=True, default=str)
        if key not in seen or len(seen) >= _space_size(space):
            seen.add(key)
            out.append(cand)
        attempts += 1
    return out


def _space_size(space: Dict[str, List[Any]]) -> int:
    size = 1
    for v in space.values():
        size *= max(1, len(v))
    return size


@component(
    inputs={
        "examples": "Examples",
        "transform_graph": "TransformGraph",
        "schema": "Schema",
    },
    optional_inputs=("transform_graph", "schema"),
    outputs={"best_hyperparameters": "HyperParameters"},
    parameters={
        "module_file": Parameter(type=str, required=True),
        # {name: [candidate values]}; falls back to module SEARCH_SPACE.
        "search_space": Parameter(type=dict, default=None),
        "algorithm": Parameter(type=str, default="grid"),  # grid | random
        "max_trials": Parameter(type=int, default=0),      # 0 = all (grid)
        "train_steps": Parameter(type=int, default=100),
        "eval_steps": Parameter(type=int, default=0),
        # Metric key from TrainResult.final_metrics; "" = eval_loss if
        # present else loss.
        "objective": Parameter(type=str, default=""),
        "direction": Parameter(type=str, default="min"),   # min | max
        "base_hyperparameters": Parameter(type=dict, default=None),
        "mesh": Parameter(type=dict, default=None),
        "custom_config": Parameter(type=dict, default=None),
        "seed": Parameter(type=int, default=0),
    },
    external_input_parameters=("module_file",),
)
def Tuner(ctx):
    module_file = ctx.exec_properties["module_file"]
    run_fn = load_fn(module_file, "run_fn")

    space = ctx.exec_properties["search_space"]
    if not space:
        space = getattr(load_module(module_file), "SEARCH_SPACE", None)
    if not space:
        raise ValueError(
            "Tuner needs a search_space parameter or a SEARCH_SPACE dict in "
            f"the module file {module_file!r}"
        )
    space = {k: list(v) for k, v in space.items()}
    empty = sorted(k for k, v in space.items() if not v)
    if empty:
        raise ValueError(f"search_space entries have no candidates: {empty}")

    algorithm = ctx.exec_properties["algorithm"]
    max_trials = ctx.exec_properties["max_trials"]
    if algorithm == "grid":
        candidates = _grid(space)
        if max_trials:
            candidates = candidates[:max_trials]
    elif algorithm == "random":
        n = max_trials or min(10, _space_size(space))
        candidates = _random(space, n, ctx.exec_properties["seed"])
    else:
        raise ValueError(f"unknown tuner algorithm {algorithm!r}")
    if not candidates:
        raise ValueError(
            f"tuner produced no candidates (space={space}, "
            f"max_trials={max_trials})"
        )

    direction = ctx.exec_properties["direction"]
    if direction not in ("min", "max"):
        raise ValueError(f"direction must be 'min' or 'max', got {direction!r}")
    objective = ctx.exec_properties["objective"]
    base_hp = dict(ctx.exec_properties["base_hyperparameters"] or {})
    out = ctx.output("best_hyperparameters")

    trials: List[Dict[str, Any]] = []
    best_idx = -1
    best_score = None
    obj = objective  # resolved from the first trial's metrics when unset
    for i, cand in enumerate(candidates):
        trial_dir = os.path.join(out.uri, "trials", str(i))
        fn_args = resolve_fn_args(
            ctx,
            serving_model_dir=os.path.join(trial_dir, "model"),
            model_run_dir=os.path.join(trial_dir, "model_run"),
            hyperparameters={**base_hp, **cand},
            train_steps=ctx.exec_properties["train_steps"],
            eval_steps=ctx.exec_properties["eval_steps"],
            mesh=ctx.exec_properties["mesh"],
            custom_config=ctx.exec_properties["custom_config"],
        )
        result = run_fn(fn_args)
        if not isinstance(result, TrainResult):
            raise TypeError(
                "run_fn must return TrainResult for tuning, got "
                f"{type(result).__name__}"
            )
        metrics = result.final_metrics
        if not obj:
            # One objective for ALL trials — never compare across metrics.
            obj = "eval_loss" if "eval_loss" in metrics else "loss"
        if obj not in metrics:
            raise KeyError(
                f"objective {obj!r} not in trial metrics {sorted(metrics)}"
            )
        score = float(metrics[obj])
        trials.append({
            "trial": i, "hyperparameters": cand, "objective": obj,
            "score": score, "metrics": metrics,
        })
        better = (
            best_score is None
            or (direction == "min" and score < best_score)
            or (direction == "max" and score > best_score)
        )
        if better:
            best_score, best_idx = score, i

    os.makedirs(out.uri, exist_ok=True)
    best = {**base_hp, **candidates[best_idx]}
    # Multi-host: every process ran the trials (SPMD), but these plain-file
    # writes land in the shared output dir — process 0 only.  jax is already
    # live here (the trials trained), so ask the backend, which also covers
    # users who initialized jax.distributed without the TPP_* env vars.
    import jax

    if jax.process_index() == 0:
        with open(os.path.join(out.uri, BEST_FILE), "w") as f:
            json.dump(best, f, indent=2, sort_keys=True, default=str)
        with open(os.path.join(out.uri, TRIALS_FILE), "w") as f:
            json.dump(trials, f, indent=2, sort_keys=True, default=str)
    out.properties["num_trials"] = len(trials)
    out.properties["best_trial"] = best_idx
    out.properties["best_score"] = best_score
    return {
        "num_trials": len(trials),
        "best_trial": best_idx,
        "best_score": best_score,
    }
