"""Tuner component: hyperparameter search over the Trainer's run_fn.

Capability match for TFX Tuner + the workshop's Katib HPO (SURVEY.md §2a
row 7, §2b Katib row): trials run the same ``run_fn(FnArgs)`` contract the
Trainer uses — no separate tuning API — with grid or random candidate
generation, and the winner is emitted as a ``HyperParameters`` artifact whose
``best_hyperparameters.json`` the Trainer merges over its own defaults.

Trial execution modes (the Katib parallel-pod equivalent):

  - in-process sequential (``parallel_trials=1``, default): each trial a
    fresh jit; identical shapes across trials hit XLA's compilation cache, so
    later trials pay only run time.
  - subprocess-isolated (``parallel_trials>1`` or ``isolate_trials=True``):
    each trial is ``python -m tpu_pipelines.components.tuner_trial`` on a
    JSON spec, up to ``parallel_trials`` concurrently.  A trial that OOMs or
    crashes fails *that trial* — the component keeps going and picks the best
    of the survivors (it only fails when every trial failed).  Concurrency is
    host-level: on a single TPU chip keep 1 (or give trials
    ``custom_config`` platform overrides); on CPU or across pods it overlaps.
  - cluster fan-out (``trial_shards=k``): the TPUJobRunner emits one pod per
    shard running ``tuner_trial shard --shard i/k`` (candidates[i::k]) into a
    shared ``--shard-dir``, then the Tuner node itself runs with
    ``TPP_TUNER_SHARD_DIR`` set, reuses every shard-computed score, runs any
    stragglers locally, and publishes the merged result — so the metadata
    store sees exactly one Tuner execution, Katib-style fan-out included.
"""

from __future__ import annotations

import dataclasses
import glob
import itertools
import json
import logging
import os
import random
import subprocess
import sys
from typing import Any, Dict, List, Optional

from tpu_pipelines.dsl.component import Parameter, component
from tpu_pipelines.trainer.fn_args import (
    FnArgs,
    TrainResult,
    ctx_data_uris,
    make_fn_args,
)
from tpu_pipelines.utils.module_loader import load_fn, load_module

logger = logging.getLogger(__name__)

BEST_FILE = "best_hyperparameters.json"
TRIALS_FILE = "trials.json"
ENV_SHARD_DIR = "TPP_TUNER_SHARD_DIR"

SPEC_FILE = "spec.json"
RESULT_FILE = "result.json"
ERROR_FILE = "error.log"


def _grid(space: Dict[str, List[Any]]) -> List[Dict[str, Any]]:
    keys = sorted(space)
    return [
        dict(zip(keys, combo))
        for combo in itertools.product(*(space[k] for k in keys))
    ]


def _random(space: Dict[str, List[Any]], n: int, seed: int) -> List[Dict[str, Any]]:
    rng = random.Random(seed)
    keys = sorted(space)
    seen = set()
    out: List[Dict[str, Any]] = []
    # Bounded rejection sampling; falls back to duplicates-allowed if the
    # space is smaller than n.
    attempts = 0
    while len(out) < n and attempts < 50 * n:
        cand = {k: rng.choice(space[k]) for k in keys}
        key = json.dumps(cand, sort_keys=True, default=str)
        if key not in seen or len(seen) >= _space_size(space):
            seen.add(key)
            out.append(cand)
        attempts += 1
    return out


def _space_size(space: Dict[str, List[Any]]) -> int:
    size = 1
    for v in space.values():
        size *= max(1, len(v))
    return size


def candidate_key(cand: Dict[str, Any]) -> str:
    return json.dumps(cand, sort_keys=True, default=str)


def trial_config_key(exec_properties: Dict[str, Any]) -> str:
    """Canonical key over everything (besides the candidate hyperparameters,
    which the merged candidate_key covers) that changes what a shard trial
    trains: budgets, mesh, custom_config, module file.  Shard pods resolve
    runtime parameters to their *defaults*, so a run with runtime-overridden
    budgets must not silently reuse shard scores trained under the defaults
    — the merge validates this key against each shard file."""
    return json.dumps(
        {
            "train_steps": exec_properties.get("train_steps", 100),
            "eval_steps": exec_properties.get("eval_steps", 0),
            "mesh": exec_properties.get("mesh"),
            "custom_config": exec_properties.get("custom_config"),
            "module_file": exec_properties.get("module_file"),
        },
        sort_keys=True, default=str,
    )


def resolve_search_space(
    exec_properties: Dict[str, Any], module_file: str
) -> Dict[str, List[Any]]:
    space = exec_properties.get("search_space")
    if not space:
        space = getattr(load_module(module_file), "SEARCH_SPACE", None)
    if not space:
        raise ValueError(
            "Tuner needs a search_space parameter or a SEARCH_SPACE dict in "
            f"the module file {module_file!r}"
        )
    space = {k: list(v) for k, v in space.items()}
    empty = sorted(k for k, v in space.items() if not v)
    if empty:
        raise ValueError(f"search_space entries have no candidates: {empty}")
    return space


def enumerate_candidates(
    exec_properties: Dict[str, Any], module_file: str
) -> List[Dict[str, Any]]:
    """Deterministic candidate list — identical in every shard/merge process."""
    space = resolve_search_space(exec_properties, module_file)
    algorithm = exec_properties.get("algorithm", "grid")
    max_trials = exec_properties.get("max_trials", 0)
    if algorithm == "grid":
        candidates = _grid(space)
        if max_trials:
            candidates = candidates[:max_trials]
    elif algorithm == "random":
        n = max_trials or min(10, _space_size(space))
        candidates = _random(space, n, exec_properties.get("seed", 0))
    else:
        raise ValueError(
            f"unknown enumerable tuner algorithm {algorithm!r} "
            "(adaptive algorithms 'halving'/'tpe' are handled by the "
            "component, not by candidate enumeration)"
        )
    if not candidates:
        raise ValueError(
            f"tuner produced no candidates (space={space}, "
            f"max_trials={max_trials})"
        )
    return candidates


def build_trial_fn_args(
    *,
    examples_uri: str,
    transform_graph_uri: str,
    schema_uri: str,
    trial_dir: str,
    hyperparameters: Dict[str, Any],
    exec_properties: Dict[str, Any],
) -> FnArgs:
    """One trial's FnArgs — shared by the executor and the shard CLI so the
    run_fn contract cannot drift between local and fanned-out trials."""
    return make_fn_args(
        examples_uri=examples_uri,
        transform_graph_uri=transform_graph_uri,
        schema_uri=schema_uri,
        serving_model_dir=os.path.join(trial_dir, "model"),
        model_run_dir=os.path.join(trial_dir, "model_run"),
        train_steps=exec_properties.get("train_steps", 100),
        eval_steps=exec_properties.get("eval_steps", 0),
        hyperparameters=hyperparameters,
        mesh=exec_properties.get("mesh"),
        custom_config=exec_properties.get("custom_config"),
    )


def run_trial(module_file: str, fn_args: FnArgs) -> Dict[str, float]:
    """Execute one trial in the current process; returns final metrics."""
    run_fn = load_fn(module_file, "run_fn")
    result = run_fn(fn_args)
    if not isinstance(result, TrainResult):
        raise TypeError(
            "run_fn must return TrainResult for tuning, got "
            f"{type(result).__name__}"
        )
    return {k: float(v) for k, v in result.final_metrics.items()}


# ------------------------------------------------------------ trial outcomes

def _outcome(trial: int, cand: Dict[str, Any], *, metrics=None, error=None):
    out: Dict[str, Any] = {
        "trial": trial,
        "hyperparameters": cand,
        "status": "ok" if error is None else "failed",
    }
    if metrics is not None:
        out["metrics"] = metrics
    if error is not None:
        out["error"] = str(error)[:2000]
    return out


def _run_trials_inprocess(
    todo: List[int], candidates, module_file, make_fn_args, isolate: bool,
) -> Dict[int, Dict[str, Any]]:
    outcomes: Dict[int, Dict[str, Any]] = {}
    for i in todo:
        fn_args = make_fn_args(i)
        if isolate:
            outcomes[i] = _run_trial_subprocess(
                i, candidates[i], module_file, fn_args
            )
            continue
        # In-process: a trial crash propagates (legacy strict mode) — the
        # isolation story lives in the subprocess path.
        metrics = run_trial(module_file, fn_args)
        outcomes[i] = _outcome(i, candidates[i], metrics=metrics)
    return outcomes


def _run_trial_subprocess(
    trial: int, cand: Dict[str, Any], module_file: str, fn_args: FnArgs
) -> Dict[str, Any]:
    trial_dir = os.path.dirname(fn_args.serving_model_dir)
    os.makedirs(trial_dir, exist_ok=True)
    spec_path = os.path.join(trial_dir, SPEC_FILE)
    result_path = os.path.join(trial_dir, RESULT_FILE)
    spec = {
        "module_file": module_file,
        "fn_args": dataclasses.asdict(fn_args),
        "trial": trial,
        "result_path": result_path,
    }
    try:
        # Strict (no default=str): silently stringifying a tuple/ndarray in
        # custom_config would hand subprocess trials different inputs than
        # in-process trials get — the contract drift make_fn_args exists to
        # prevent.
        spec_json = json.dumps(spec, indent=2)
    except TypeError as e:
        raise ValueError(
            "subprocess trial modes (parallel_trials/isolate_trials/"
            "trial_shards) need JSON-serializable hyperparameters and "
            f"custom_config; trial {trial} spec is not: {e}"
        ) from e
    with open(spec_path, "w") as f:
        f.write(spec_json)
    with open(os.path.join(trial_dir, ERROR_FILE), "w") as errf:
        proc = subprocess.run(
            [sys.executable, "-m", "tpu_pipelines.components.tuner_trial",
             "trial", "--spec", spec_path],
            stdout=errf, stderr=subprocess.STDOUT,
        )
    if proc.returncode != 0 or not os.path.exists(result_path):
        tail = ""
        try:
            with open(os.path.join(trial_dir, ERROR_FILE)) as f:
                tail = f.read()[-2000:]
        except OSError:
            pass
        logger.warning("tuner trial %d failed (rc=%d)", trial, proc.returncode)
        return _outcome(
            trial, cand,
            error=f"subprocess rc={proc.returncode}: {tail or 'no output'}",
        )
    with open(result_path) as f:
        metrics = json.load(f)["final_metrics"]
    return _outcome(trial, cand, metrics=metrics)


def _run_trials_parallel(
    todo: List[int], candidates, module_file, make_fn_args, parallel: int
) -> Dict[int, Dict[str, Any]]:
    """Up to ``parallel`` concurrent subprocess trials (threads just babysit
    the subprocesses, so the GIL is irrelevant here)."""
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=parallel) as pool:
        futs = {
            i: pool.submit(
                _run_trial_subprocess, i, candidates[i], module_file,
                make_fn_args(i),
            )
            for i in todo
        }
        return {i: fut.result() for i, fut in futs.items()}


# ------------------------------------------------------------ shard files

def shard_file_path(shard_dir: str, shard: int, num_shards: int) -> str:
    return os.path.join(shard_dir, f"shard_{shard}_of_{num_shards}.json")


def write_shard_results(
    shard_dir: str, shard: int, num_shards: int,
    outcomes: List[Dict[str, Any]], *, examples_uri: str = "",
    trial_config: str = "",
) -> str:
    os.makedirs(shard_dir, exist_ok=True)
    path = shard_file_path(shard_dir, shard, num_shards)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"shard": shard, "num_shards": num_shards,
                   "examples_uri": examples_uri,
                   "trial_config": trial_config,
                   "outcomes": outcomes}, f, indent=2, default=str)
    os.replace(tmp, path)  # atomic: mergers never see half a shard
    return path


def load_shard_results(
    shard_dir: str, *, examples_uri: str = "", num_shards: int = 0,
    trial_config: str = "",
) -> Dict[str, Dict[str, Any]]:
    """{candidate_key: outcome} from every *matching* shard file.  Keyed by
    hyperparameter content, not index, so a shard/merge enumeration mismatch
    degrades to re-running a trial instead of mis-scoring it.

    The shard dir is a fixed path under pipeline_root, so files from earlier
    runs (different data, different fan-out degree) can survive there: a
    shard is reused only when its recorded examples_uri matches this run's
    resolved Examples artifact (output uris are execution-unique, so changed
    data means a changed uri) and, when ``num_shards`` is given, its fan-out
    degree matches.  Mismatches are skipped with a warning — the trials
    simply re-run locally."""
    merged: Dict[str, Dict[str, Any]] = {}
    for path in sorted(glob.glob(os.path.join(shard_dir, "shard_*.json"))):
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            logger.warning("ignoring unreadable tuner shard %s: %s", path, e)
            continue
        if num_shards and payload.get("num_shards") != num_shards:
            logger.warning(
                "ignoring stale tuner shard %s (fan-out %s, want %d)",
                path, payload.get("num_shards"), num_shards,
            )
            continue
        if examples_uri and payload.get("examples_uri") != examples_uri:
            logger.warning(
                "ignoring stale tuner shard %s (examples %r, want %r)",
                path, payload.get("examples_uri"), examples_uri,
            )
            continue
        if trial_config and payload.get("trial_config") != trial_config:
            # Shards trained under different budgets/mesh/custom_config (e.g.
            # runtime-parameter overrides the shard pods resolved to
            # defaults) — their scores answer a different question.
            logger.warning(
                "ignoring stale tuner shard %s (trial config mismatch)", path,
            )
            continue
        for outcome in payload.get("outcomes", []):
            merged[candidate_key(outcome["hyperparameters"])] = outcome
    return merged


# ------------------------------------------------------------ component

@component(
    inputs={
        "examples": "Examples",
        "transform_graph": "TransformGraph",
        "schema": "Schema",
    },
    optional_inputs=("transform_graph", "schema"),
    outputs={"best_hyperparameters": "HyperParameters"},
    parameters={
        "module_file": Parameter(type=str, required=True),
        # {name: [candidate values]}; falls back to module SEARCH_SPACE.
        "search_space": Parameter(type=dict, default=None),
        # grid | random | halving (successive halving, the Hyperband inner
        # loop) | tpe (Tree-structured Parzen Estimator) — the latter two
        # are the KerasTuner/Katib adaptive equivalents (tuner_algorithms.py)
        "algorithm": Parameter(type=str, default="grid"),
        "max_trials": Parameter(type=int, default=0),      # 0 = all (grid)
        # halving: initial candidate count (defaults to max_trials or 9),
        # reduction factor, and the smallest rung budget (0 = derived).
        "halving_eta": Parameter(type=int, default=3),
        "min_train_steps": Parameter(type=int, default=0),
        # tpe: proposal batch size, good-fraction, random startup trials.
        "tpe_batch": Parameter(type=int, default=4),
        "tpe_gamma": Parameter(type=float, default=0.25),
        "tpe_startup": Parameter(type=int, default=0),
        "train_steps": Parameter(type=int, default=100),
        "eval_steps": Parameter(type=int, default=0),
        # Metric key from TrainResult.final_metrics; "" = eval_loss if
        # present else loss.
        "objective": Parameter(type=str, default=""),
        "direction": Parameter(type=str, default="min"),   # min | max
        "base_hyperparameters": Parameter(type=dict, default=None),
        "mesh": Parameter(type=dict, default=None),
        "custom_config": Parameter(type=dict, default=None),
        "seed": Parameter(type=int, default=0),
        # Concurrent subprocess trials (1 = in-process sequential).
        "parallel_trials": Parameter(type=int, default=1),
        # Subprocess-isolate even when sequential (crash tolerance).
        "isolate_trials": Parameter(type=bool, default=False),
        # Cluster fan-out hint: TPUJobRunner emits this many shard pods
        # (0 = none).  The executor itself only consumes their results.
        "trial_shards": Parameter(type=int, default=0),
    },
    external_input_parameters=("module_file",),
    resource_class="tpu",
    lint_module_fns=("run_fn",),
)
def Tuner(ctx):
    module_file = ctx.exec_properties["module_file"]

    direction = ctx.exec_properties["direction"]
    if direction not in ("min", "max"):
        raise ValueError(f"direction must be 'min' or 'max', got {direction!r}")
    objective = ctx.exec_properties["objective"]
    base_hp = dict(ctx.exec_properties["base_hyperparameters"] or {})
    out = ctx.output("best_hyperparameters")

    uris = ctx_data_uris(ctx)

    algorithm = ctx.exec_properties.get("algorithm", "grid")
    if algorithm in ("halving", "hyperband", "tpe"):
        return _adaptive_tuner(
            ctx, algorithm, module_file, uris, out, base_hp, objective,
            direction,
        )

    candidates = enumerate_candidates(ctx.exec_properties, module_file)

    def trial_fn_args(i: int) -> FnArgs:
        return build_trial_fn_args(
            **uris,
            trial_dir=os.path.join(out.uri, "trials", str(i)),
            hyperparameters={**base_hp, **candidates[i]},
            exec_properties=ctx.exec_properties,
        )

    # Results precomputed by cluster shard pods (Katib-style fan-out),
    # validated against this run's data and fan-out degree.
    shard_dir = os.environ.get(ENV_SHARD_DIR, "")
    precomputed = load_shard_results(
        shard_dir,
        examples_uri=uris["examples_uri"],
        num_shards=int(ctx.exec_properties["trial_shards"] or 0),
        trial_config=trial_config_key(ctx.exec_properties),
    ) if shard_dir else {}
    outcomes: Dict[int, Dict[str, Any]] = {}
    todo: List[int] = []
    for i, cand in enumerate(candidates):
        # Merged-key lookup only: shards write {**base_hp, **cand} keys, so a
        # raw-cand fallback could silently resurrect a shard score computed
        # under DIFFERENT base_hyperparameters (shard files live at a fixed
        # path and survive base_hp changes).  A miss degrades to a local
        # re-run, which is always correct.
        pre = precomputed.get(candidate_key({**base_hp, **cand}))
        if pre is not None:
            outcomes[i] = {**pre, "trial": i}
        else:
            todo.append(i)
    if precomputed:
        logger.info(
            "tuner: %d/%d trials reused from shards in %s",
            len(outcomes), len(candidates), shard_dir,
        )

    parallel, isolate = _trial_exec_mode(ctx)
    if todo and parallel > 1:
        outcomes.update(_run_trials_parallel(
            todo, candidates, module_file, trial_fn_args, parallel
        ))
    elif todo:
        outcomes.update(_run_trials_inprocess(
            todo, candidates, module_file, trial_fn_args, isolate,
        ))

    # One objective for ALL trials — resolved from the first success when
    # unset; never compare across metrics.
    obj = objective
    trials: List[Dict[str, Any]] = []
    best_idx = -1
    best_score: Optional[float] = None
    for i in range(len(candidates)):
        o = outcomes[i]
        if o["status"] != "ok":
            trials.append(o)
            continue
        metrics = o["metrics"]
        if not obj:
            obj = "eval_loss" if "eval_loss" in metrics else "loss"
        if obj not in metrics:
            raise KeyError(
                f"objective {obj!r} not in trial metrics {sorted(metrics)}"
            )
        score = float(metrics[obj])
        trials.append({**o, "objective": obj, "score": score})
        better = (
            best_score is None
            or (direction == "min" and score < best_score)
            or (direction == "max" and score > best_score)
        )
        if better:
            best_score, best_idx = score, i

    n_failed = sum(1 for t in trials if t["status"] != "ok")
    if best_idx < 0:
        raise RuntimeError(
            f"all {len(trials)} tuner trials failed; see trial error logs "
            f"under {out.uri}/trials/"
        )
    if n_failed:
        logger.warning(
            "tuner: %d/%d trials failed; best of the %d survivors wins",
            n_failed, len(trials), len(trials) - n_failed,
        )

    best = {**base_hp, **candidates[best_idx]}
    return _publish_results(out, best, trials, best_idx, best_score, n_failed)


def _trial_exec_mode(ctx) -> "tuple[int, bool]":
    parallel = max(1, int(ctx.exec_properties["parallel_trials"]))
    isolate = bool(ctx.exec_properties["isolate_trials"]) or parallel > 1
    if isolate:
        # Subprocess trials are a single-controller mechanism: under
        # multi-host SPMD every host process would race on the same spec/
        # result files and the subprocesses would never join the coordination
        # service.  Multi-host fan-out is what trial_shards is for.
        # Detected from the bootstrap env (parallel/distributed.py), NOT via
        # jax.process_count(): touching jax here would initialize the TPU
        # backend in the parent and lock the chips away from every trial
        # subprocess this mode exists to spawn.
        from tpu_pipelines.parallel.distributed import ENV_NUM_PROCESSES

        if int(os.environ.get(ENV_NUM_PROCESSES, "1") or 1) > 1:
            raise ValueError(
                "parallel_trials/isolate_trials cannot run under multi-host "
                "SPMD (every host would spawn colliding trial subprocesses); "
                "use trial_shards for cluster fan-out instead"
            )
    return parallel, isolate


def _publish_results(out, best, trials, best_idx, best_score, n_failed):
    os.makedirs(out.uri, exist_ok=True)
    # Multi-host: every process ran the trials (SPMD), but these plain-file
    # writes land in the shared output dir — process 0 only.  jax is already
    # live here (the trials trained), so ask the backend, which also covers
    # users who initialized jax.distributed without the TPP_* env vars.
    import jax

    if jax.process_index() == 0:
        with open(os.path.join(out.uri, BEST_FILE), "w") as f:
            json.dump(best, f, indent=2, sort_keys=True, default=str)
        with open(os.path.join(out.uri, TRIALS_FILE), "w") as f:
            json.dump(trials, f, indent=2, sort_keys=True, default=str)
    out.properties["num_trials"] = len(trials)
    out.properties["failed_trials"] = n_failed
    out.properties["best_trial"] = best_idx
    out.properties["best_score"] = best_score
    return {
        "num_trials": len(trials),
        "failed_trials": n_failed,
        "best_trial": best_idx,
        "best_score": best_score,
    }


def _adaptive_tuner(ctx, algorithm, module_file, uris, out, base_hp,
                    objective, direction):
    """Successive-halving / TPE flow: rounds of trials through the same
    subprocess/parallel machinery, budgets and proposals driven by earlier
    scores (tuner_algorithms.py)."""
    from tpu_pipelines.components import tuner_algorithms as ta

    if int(ctx.exec_properties["trial_shards"] or 0):
        raise ValueError(
            f"algorithm {algorithm!r} is sequential-by-round and cannot use "
            "trial_shards fan-out; use parallel_trials for within-round "
            "concurrency"
        )
    space = resolve_search_space(ctx.exec_properties, module_file)
    parallel, isolate = _trial_exec_mode(ctx)
    train_steps = int(ctx.exec_properties.get("train_steps", 100))
    max_trials = int(ctx.exec_properties["max_trials"] or 0)

    def run_batch(cands, steps, first_id):
        overlaid = {**ctx.exec_properties, "train_steps": steps}

        def fn_args(i: int) -> FnArgs:
            return build_trial_fn_args(
                **uris,
                trial_dir=os.path.join(out.uri, "trials", str(first_id + i)),
                hyperparameters={**base_hp, **cands[i]},
                exec_properties=overlaid,
            )

        todo = list(range(len(cands)))
        if parallel > 1:
            outcomes = _run_trials_parallel(
                todo, cands, module_file, fn_args, parallel
            )
        else:
            outcomes = _run_trials_inprocess(
                todo, cands, module_file, fn_args, isolate
            )
        ordered = []
        for i in todo:
            o = outcomes[i]
            o["trial"] = first_id + i
            ordered.append(o)
        return ordered

    if algorithm in ("halving", "hyperband"):
        n0 = max_trials or 9
        trials, best = ta.successive_halving(
            space,
            run_batch=run_batch,
            max_steps=train_steps,
            n0=n0,
            eta=int(ctx.exec_properties["halving_eta"]),
            min_steps=int(ctx.exec_properties["min_train_steps"]),
            objective=objective,
            direction=direction,
            seed=int(ctx.exec_properties["seed"]),
        )
    else:
        trials, best = ta.tpe(
            space,
            run_batch=run_batch,
            train_steps=train_steps,
            max_trials=max_trials or 16,
            batch_size=int(ctx.exec_properties["tpe_batch"]),
            startup=int(ctx.exec_properties["tpe_startup"]),
            gamma=float(ctx.exec_properties["tpe_gamma"]),
            objective=objective,
            direction=direction,
            seed=int(ctx.exec_properties["seed"]),
        )

    n_failed = sum(1 for t in trials if t["status"] != "ok")
    if best is None:
        raise RuntimeError(
            f"all {len(trials)} tuner trials failed; see trial error logs "
            f"under {out.uri}/trials/"
        )
    if n_failed:
        logger.warning(
            "tuner: %d/%d trials failed; best of the %d survivors wins",
            n_failed, len(trials), len(trials) - n_failed,
        )
    best_hp = {**base_hp, **best["hyperparameters"]}
    return _publish_results(
        out, best_hp, trials, best["trial"], best.get("score"), n_failed
    )
