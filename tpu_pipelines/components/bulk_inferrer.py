"""BulkInferrer: jit-compiled batch inference over an Examples artifact.

Capability match for TFX BulkInferrer (SURVEY.md §2a row 11), with the
BASELINE on-chip story: raw examples stream host-side through the embedded
TransformGraph string stage, and one jitted computation (numeric transform
fused with model forward) runs per batch on the accelerator.  Predictions are
written as an InferenceResult artifact (Parquet), joined with any requested
passthrough columns.
"""

from __future__ import annotations

import logging

import numpy as np

from tpu_pipelines.data import examples_io
from tpu_pipelines.data.shard_plan import thread_map
from tpu_pipelines.dsl.component import Parameter, component
from tpu_pipelines.trainer.export import (
    load_exported_model,
    model_input_columns,
)

PREDICTIONS_FILE = "predictions"


def _shard_batches(uri, split, shard, batch_size, columns):
    """Fixed-size dict-of-numpy batches over one shard, order preserved,
    remainder kept (the shuffle-free single-epoch read BulkInferrer needs,
    without materializing the shard)."""
    pending = None
    for chunk in examples_io.iter_column_chunks(
        uri, split, columns=columns, shards=[shard]
    ):
        pending = chunk if pending is None else {
            k: np.concatenate([pending[k], chunk[k]]) for k in pending
        }
        n = len(next(iter(pending.values())))
        start = 0
        while n - start >= batch_size:
            yield {k: v[start:start + batch_size] for k, v in pending.items()}
            start += batch_size
        if start:
            pending = {k: v[start:] for k, v in pending.items()}
    if pending is not None and len(next(iter(pending.values()))):
        yield pending


@component(
    inputs={
        "examples": "Examples",
        "model": "Model",
        "model_blessing": "ModelBlessing",
    },
    optional_inputs=("model_blessing",),
    outputs={"inference_result": "InferenceResult"},
    parameters={
        "data_splits": Parameter(type=list, default=None),  # None = all
        "batch_size": Parameter(type=int, default=1024),
        # Raw columns copied next to predictions (join keys, ids).
        "passthrough_columns": Parameter(type=list, default=None),
        # Examples are raw (apply embedded transform) vs pre-transformed.
        "raw_examples": Parameter(type=bool, default=True),
        # "forward": the model's forward pass (classification/regression).
        # "generate": autoregressive decoding for seq2seq models — requires
        # the exported module to define make_generate_step (or the legacy
        # make_generate_fn; models/t5.py make_greedy_generate /
        # make_beam_generate build the decode fn).
        "predict_method": Parameter(type=str, default="forward"),
    },
    resource_class="tpu",
    is_sink=True,
)
def BulkInferrer(ctx):
    from tpu_pipelines.components.evaluator import is_blessed

    out = ctx.output("inference_result")
    if ctx.inputs.get("model_blessing") and not is_blessed(
        ctx.input("model_blessing").uri
    ):
        out.properties["skipped"] = True
        return {"skipped": True, "reason": "model not blessed"}

    loaded = load_exported_model(ctx.input("model").uri)
    method = ctx.exec_properties["predict_method"]
    if method == "generate":
        if loaded.generate is None:
            raise ValueError(
                "predict_method='generate' but the exported module defines "
                "no make_generate_step(model, hyperparameters) (or legacy "
                "make_generate_fn)"
            )
        if not ctx.exec_properties["raw_examples"] and loaded.transform:
            # loaded.generate runs the embedded transform; feeding it
            # already-transformed examples would tokenize them twice.
            raise ValueError(
                "predict_method='generate' consumes RAW examples (the "
                "embedded transform is applied inside generate); wire the "
                "ExampleGen output, not transformed_examples"
            )
        predict = loaded.generate
    elif method == "forward":
        predict = (
            loaded.predict if ctx.exec_properties["raw_examples"]
            else loaded.predict_transformed
        )
    else:
        raise ValueError(
            f"predict_method must be 'forward' or 'generate', got {method!r}"
        )
    examples_uri = ctx.input("examples").uri
    splits = ctx.exec_properties["data_splits"] or examples_io.split_names(
        examples_uri
    )
    passthrough = ctx.exec_properties["passthrough_columns"] or []
    batch_size = ctx.exec_properties["batch_size"]

    # Column projection: decode only what the predict path + passthrough
    # actually consume (None = unknown model surface, read everything).
    columns = model_input_columns(
        loaded, raw=(
            method == "generate" or ctx.exec_properties["raw_examples"]
        ),
    )
    if columns is not None:
        columns = sorted(set(columns) | set(passthrough))

    def infer_shard(task):
        """One shard in, one predictions shard out.  Each batch is predicted
        and appended to this shard's Parquet writer immediately, so output
        memory is O(batch), never O(split) — the Beam-job scaling the
        reference's BulkInferrer had; shards fan out across threads (the
        jitted predict serializes on-device, but host decode/encode of
        shard i+1 overlaps the predict of shard i)."""
        split, shard, n_shards = task
        writer = None
        schema = None
        n_preds = 0
        try:
            for batch in _shard_batches(
                examples_uri, split, shard, batch_size, columns
            ):
                preds = np.asarray(predict(batch))
                cols = {}
                for c in passthrough:
                    if c not in batch:
                        raise KeyError(
                            f"passthrough column {c!r} not in split {split!r}"
                        )
                    cols[c] = batch[c]
                if preds.ndim == 1:
                    cols["prediction"] = preds
                else:
                    cols["prediction"] = preds.reshape(len(preds), -1)
                table = examples_io.table_from_columns(cols)
                if writer is None:
                    schema = table.schema
                    writer = examples_io.open_split_writer(
                        out.uri, split, schema,
                        shard=shard, num_shards=n_shards,
                    )
                writer.write_table(table)
                n_preds += len(preds)
        finally:
            if writer is not None:
                writer.close()
        return n_preds, schema

    total = 0
    written_splits = set(splits)
    for split in splits:
        n_shards = examples_io.num_split_shards(examples_uri, split)
        results = thread_map(
            infer_shard,
            [(split, shard, n_shards) for shard in range(n_shards)],
        )
        schemas = [s for _, s in results if s is not None]
        if not schemas:
            # Zero batches (hash-split left this split empty): no file was
            # written, so drop the split from the artifact's listing rather
            # than publishing a split name downstream reads would 404 on.
            logging.getLogger(__name__).warning(
                "BulkInferrer: split %r empty; omitted from output", split
            )
            written_splits.discard(split)
        else:
            for shard, (n, schema) in enumerate(results):
                if schema is None:  # backfill: complete shard set
                    examples_io.open_split_writer(
                        out.uri, split, schemas[0],
                        shard=shard, num_shards=n_shards,
                    ).close()
        total += sum(n for n, _ in results)
    out.properties["num_predictions"] = total
    out.properties["split_names"] = sorted(written_splits)
    return {"num_predictions": total, "projected_columns": columns}
