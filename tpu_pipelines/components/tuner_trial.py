"""Tuner trial entrypoints: subprocess isolation + cluster shard fan-out.

Two subcommands (``python -m tpu_pipelines.components.tuner_trial ...``):

  - ``trial --spec spec.json`` — run ONE trial from a JSON FnArgs spec and
    write its metrics to the spec's ``result_path``.  This is the isolation
    boundary the Tuner's ``parallel_trials``/``isolate_trials`` modes spawn:
    an OOM/crash here kills this process only, and the parent records a
    failed trial (Katib's per-pod trial failure semantics, SURVEY.md §2b).
  - ``shard --pipeline-module M --node-id N --shard i/k --shard-dir D`` —
    the cluster fan-out worker the TPUJobRunner schedules, one pod per
    shard: rebuild the pipeline, resolve the Tuner node's *inputs* read-only
    from the shared metadata store (Argo DAG ordering guarantees upstreams
    published), run candidates[i::k] in-process, and write
    ``D/shard_i_of_k.json``.  No store writes happen here — the Tuner node
    itself (running after the shards with ``TPP_TUNER_SHARD_DIR=D``) merges
    shard scores and publishes, so MLMD sees exactly one Tuner execution and
    the execution cache never keys on shard scratch state.

Runtime parameters resolve to their defaults in shard mode (fan-out of a
parameterized tuner should bake parameters into the pipeline module).
"""

from __future__ import annotations

import argparse
import json
import logging
import sys

logger = logging.getLogger(__name__)


def _run_single_trial(spec_path: str) -> int:
    from tpu_pipelines.components.tuner import run_trial
    from tpu_pipelines.trainer.fn_args import FnArgs

    with open(spec_path) as f:
        spec = json.load(f)
    fn_args = FnArgs(**spec["fn_args"])
    metrics = run_trial(spec["module_file"], fn_args)
    with open(spec["result_path"], "w") as f:
        json.dump({"trial": spec.get("trial"), "final_metrics": metrics}, f,
                  indent=2)
    return 0


def _run_shard(args) -> int:
    from tpu_pipelines.components.tuner import (
        _run_trial_subprocess,
        build_trial_fn_args,
        enumerate_candidates,
        trial_config_key,
        write_shard_results,
    )
    from tpu_pipelines.dsl.compiler import Compiler, resolve_property
    from tpu_pipelines.metadata.store import MetadataStore
    from tpu_pipelines.orchestration.local_runner import LocalDagRunner
    from tpu_pipelines.utils.module_loader import load_fn

    shard_s, _, num_s = args.shard.partition("/")
    shard, num_shards = int(shard_s), int(num_s)
    if not (0 <= shard < num_shards):
        raise ValueError(f"--shard must be i/k with 0 <= i < k, got {args.shard!r}")

    pipeline = load_fn(args.pipeline_module, "create_pipeline")()
    ir = Compiler().compile(pipeline)
    node = ir.node(args.node_id)
    if node.component_type != "Tuner":
        raise ValueError(
            f"{args.node_id!r} is a {node.component_type}, not a Tuner"
        )
    props = {
        k: resolve_property(v, {}) for k, v in node.exec_properties.items()
    }

    store = MetadataStore(ir.metadata_path)
    try:
        produced = {
            up: LocalDagRunner._resolve_prior_outputs(store, ir.node(up))
            for up in node.upstream
        }
        inputs = LocalDagRunner._resolve_inputs(node, produced)
    finally:
        store.close()

    def uri(key: str) -> str:
        arts = inputs.get(key) or []
        return arts[0].uri if arts else ""

    examples_uri = uri("examples")
    if not examples_uri:
        raise RuntimeError(
            f"{args.node_id}: no LIVE 'examples' input in the metadata store "
            f"at {ir.metadata_path!r} — did the upstream nodes run?"
        )

    module_file = props["module_file"]
    candidates = enumerate_candidates(props, module_file)
    base_hp = dict(props.get("base_hyperparameters") or {})
    mine = list(range(shard, len(candidates), num_shards))
    logger.info(
        "tuner shard %d/%d: trials %s of %d candidates",
        shard, num_shards, mine, len(candidates),
    )

    outcomes = []
    path = None
    for i in mine:
        hp = {**base_hp, **candidates[i]}
        fn_args = build_trial_fn_args(
            examples_uri=examples_uri,
            transform_graph_uri=uri("transform_graph"),
            schema_uri=uri("schema"),
            trial_dir=f"{args.shard_dir}/trials/{i}",
            hyperparameters=hp,
            exec_properties=props,
        )
        # Subprocess per trial: a trial that os._exit()s or segfaults must
        # not take down the shard worker (and the completed siblings' work).
        outcomes.append(_run_trial_subprocess(i, hp, module_file, fn_args))
        # Incremental atomic rewrite: a preempted/killed shard pod still
        # leaves its finished trials reusable by the merge.
        path = write_shard_results(
            args.shard_dir, shard, num_shards, outcomes,
            examples_uri=examples_uri,
            trial_config=trial_config_key(props),
        )
    logger.info("tuner shard %d/%d wrote %s", shard, num_shards, path)
    return 0


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO)
    # Subprocess-isolated trials all compile the same model family —
    # exactly the repeat-compile case the persistent cache removes.
    from tpu_pipelines.utils.compile_cache import maybe_enable_compile_cache

    maybe_enable_compile_cache()
    parser = argparse.ArgumentParser(description=__doc__)
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_trial = sub.add_parser("trial", help="run one trial from a JSON spec")
    p_trial.add_argument("--spec", required=True)

    p_shard = sub.add_parser("shard", help="run candidates[i::k] for a node")
    p_shard.add_argument("--pipeline-module", required=True)
    p_shard.add_argument("--node-id", required=True)
    p_shard.add_argument("--shard", required=True, help="i/k")
    p_shard.add_argument("--shard-dir", required=True)

    args = parser.parse_args(argv)
    if args.cmd == "trial":
        return _run_single_trial(args.spec)
    return _run_shard(args)


if __name__ == "__main__":
    sys.exit(main())
