"""InfraValidator: canary-load the model and smoke-infer before pushing.

Capability match for TFX InfraValidator (SURVEY.md §2a row 9): loads the
exported payload exactly the way serving does (``load_exported_model``), runs
a smoke inference on a few real examples, and emits an InfraBlessing that
Pusher can require.  The reference spins a serving container for this; here
the serving runtime *is* the in-process loader, so loading in-process is the
faithful canary.
"""

from __future__ import annotations

import json
import os

import numpy as np

from tpu_pipelines.data import examples_io
from tpu_pipelines.dsl.component import Parameter, component
from tpu_pipelines.trainer.export import load_exported_model

BLESSING_FILE = "BLESSED"
NOT_BLESSED_FILE = "NOT_BLESSED"


@component(
    inputs={"model": "Model", "examples": "Examples"},
    outputs={"blessing": "InfraBlessing"},
    parameters={
        "split": Parameter(type=str, default="eval"),
        "num_examples": Parameter(type=int, default=8),
        # Raw examples (apply embedded transform) vs pre-transformed.
        "raw_examples": Parameter(type=bool, default=True),
        # "inprocess": load + call predict directly.  "http": boot the
        # framework ModelServer on a loopback port and canary through the
        # REST surface — the closest local equivalent of the reference's
        # serving-container canary.
        "serving_binary": Parameter(type=str, default="inprocess"),
    },
)
def InfraValidator(ctx):
    blessing = ctx.output("blessing")
    os.makedirs(blessing.uri, exist_ok=True)
    n = ctx.exec_properties["num_examples"]
    split = ctx.exec_properties["split"]
    error = ""
    try:
        data = examples_io.read_split(ctx.input("examples").uri, split)
        batch = {k: v[:n] for k, v in data.items()}
        if ctx.exec_properties["serving_binary"] == "http":
            preds = _predict_over_http(
                ctx.input("model").uri, batch,
                raw=ctx.exec_properties["raw_examples"],
            )
        else:
            loaded = load_exported_model(ctx.input("model").uri)
            predict = (
                loaded.predict if ctx.exec_properties["raw_examples"]
                else loaded.predict_transformed
            )
            preds = np.asarray(predict(batch))
        if len(preds) != len(next(iter(batch.values()))):
            error = f"prediction count {len(preds)} != batch size"
        elif not np.isfinite(np.asarray(preds, dtype=np.float64)).all():
            error = "non-finite predictions"
    except Exception as e:  # the canary's entire job is catching these
        error = f"{type(e).__name__}: {e}"

    marker = NOT_BLESSED_FILE if error else BLESSING_FILE
    with open(os.path.join(blessing.uri, marker), "w") as f:
        json.dump({"error": error}, f)
    blessing.properties["blessed"] = not error
    if error:
        return {"blessed": False, "error": error}
    return {"blessed": True}


def _predict_over_http(model_uri: str, batch, raw: bool = True) -> np.ndarray:
    """Canary through the REST surface on a loopback port."""
    import urllib.request

    from tpu_pipelines.serving import ModelServer

    server = ModelServer("canary", model_uri, raw=raw)
    port = server.start()
    try:
        instances = [
            {k: np.asarray(v[i]).tolist() for k, v in batch.items()}
            for i in range(len(next(iter(batch.values()))))
        ]
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/models/canary:predict",
            data=json.dumps({"instances": instances}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            return np.asarray(json.load(r)["predictions"])
    finally:
        server.stop()
