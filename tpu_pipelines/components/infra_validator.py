"""InfraValidator: canary-load the model and smoke-infer before pushing.

Capability match for TFX InfraValidator (SURVEY.md §2a row 9): loads the
exported payload exactly the way serving does (``load_exported_model``), runs
a smoke inference on a few real examples, and emits an InfraBlessing that
Pusher can require.  The reference spins a serving container for this; here
the serving runtime *is* the in-process loader, so loading in-process is the
faithful canary.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

from tpu_pipelines.data import examples_io
from tpu_pipelines.dsl.component import Parameter, component
from tpu_pipelines.trainer.export import load_exported_model

BLESSING_FILE = "BLESSED"
NOT_BLESSED_FILE = "NOT_BLESSED"


def canary_check(predict, batch) -> str:
    """One smoke inference; returns an error string ('' = pass).

    THE canary verdict — shared by the InfraValidator executor and the
    serving fleet's version gate (serving/fleet/versions.py), so "gated by
    the InfraValidator canary" means literally the same check at push time
    and at hot-swap time: the prediction count must match the batch, and
    every prediction must be finite."""
    try:
        preds = predict(batch)
        if len(preds) != len(next(iter(batch.values()))):
            return f"prediction count {len(preds)} != batch size"
        if not np.isfinite(np.asarray(preds, dtype=np.float64)).all():
            return "non-finite predictions"
    except Exception as e:  # noqa: BLE001 — the canary's job is catching
        return f"{type(e).__name__}: {e}"
    return ""


def serving_batch_filter(batch, schema, environment):
    """Keep only features the schema expects in ``environment`` (labels drop
    out under "SERVING") — the canary then poses exactly the request
    production serving will see.  Columns the schema does not know keep
    flowing (passthrough keys are serving-legal)."""
    return {
        k: v for k, v in batch.items()
        if k not in schema.features or schema.expected_in(k, environment)
    }


@component(
    inputs={"model": "Model", "examples": "Examples", "schema": "Schema"},
    optional_inputs=("schema",),
    is_sink=True,
    outputs={"blessing": "InfraBlessing"},
    parameters={
        "split": Parameter(type=str, default="eval"),
        "num_examples": Parameter(type=int, default=8),
        # With a schema wired, the canary batch keeps ONLY features the
        # schema expects in this environment (labels drop out under
        # "SERVING") — the canary then exercises the exact request surface
        # production serving will see (TFDV schema environments).
        "environment": Parameter(type=str, default="SERVING"),
        # Raw examples (apply embedded transform) vs pre-transformed.
        "raw_examples": Parameter(type=bool, default=True),
        # "inprocess": load + call predict directly.  "http"/"grpc": boot
        # the framework ModelServer on a loopback port and canary through
        # that surface — the closest local equivalent of the reference's
        # serving-container canary (TF Serving speaks both, SURVEY.md §3.5).
        "serving_binary": Parameter(type=str, default="inprocess"),
        # Latency smoke: after one warmup, time this many repeat predicts on
        # the same batch and record p50/p95 (ms) into the blessing.
        "latency_probes": Parameter(type=int, default=5),
        # 0 = no gate; otherwise p95 above this many ms fails validation.
        "max_latency_ms": Parameter(type=float, default=0.0),
    },
)
def InfraValidator(ctx):
    blessing = ctx.output("blessing")
    os.makedirs(blessing.uri, exist_ok=True)
    n = ctx.exec_properties["num_examples"]
    split = ctx.exec_properties["split"]
    # .get: hand-built ExecutorContexts (tests, embedding users) may omit
    # optional params the runner would have defaulted.
    probes = max(0, ctx.exec_properties.get("latency_probes", 5))
    error = ""
    latency_p50 = latency_p95 = None
    try:
        # First streamed chunk only — the canary needs n rows, not the
        # split: a full read_split here was O(split) memory and wall for an
        # 8-row request batch.
        batch = next(
            examples_io.iter_column_chunks(
                ctx.input("examples").uri, split, rows=max(1, n)
            ),
            None,
        )
        if batch is None:
            raise ValueError(f"split {split!r} is empty")
        batch = {k: v[:n] for k, v in batch.items()}
        if ctx.inputs.get("schema"):
            from tpu_pipelines.data.schema import Schema

            batch = serving_batch_filter(
                batch,
                Schema.load(ctx.input("schema").uri),
                ctx.exec_properties.get("environment") or None,
            )
        binary = ctx.exec_properties.get("serving_binary", "inprocess")
        if binary == "http":
            predict = _http_canary(
                ctx.input("model").uri,
                raw=ctx.exec_properties["raw_examples"],
            )
        elif binary == "grpc":
            predict = _grpc_canary(
                ctx.input("model").uri,
                raw=ctx.exec_properties["raw_examples"],
            )
        else:
            loaded = load_exported_model(ctx.input("model").uri)
            raw_fn = (
                loaded.predict if ctx.exec_properties["raw_examples"]
                else loaded.predict_transformed
            )
            predict = lambda b: np.asarray(raw_fn(b))  # noqa: E731
        try:
            # Smoke-infer doubles as warmup; the verdict logic is shared
            # with the fleet's hot-swap gate (canary_check).
            error = canary_check(predict, batch)
            if not error and probes:
                lat_ms = []
                for _ in range(probes):
                    t0 = time.perf_counter()
                    predict(batch)
                    lat_ms.append((time.perf_counter() - t0) * 1000.0)
                latency_p50 = round(float(np.percentile(lat_ms, 50)), 3)
                latency_p95 = round(float(np.percentile(lat_ms, 95)), 3)
                gate = ctx.exec_properties.get("max_latency_ms", 0.0)
                if gate and latency_p95 > gate:
                    error = (
                        f"latency p95 {latency_p95}ms exceeds "
                        f"max_latency_ms={gate}"
                    )
        finally:
            closer = getattr(predict, "close", None)
            if closer:
                closer()
    except Exception as e:  # the canary's entire job is catching these
        error = f"{type(e).__name__}: {e}"

    marker = NOT_BLESSED_FILE if error else BLESSING_FILE
    with open(os.path.join(blessing.uri, marker), "w") as f:
        json.dump({
            "error": error,
            "latency_p50_ms": latency_p50,
            "latency_p95_ms": latency_p95,
        }, f)
    blessing.properties["blessed"] = not error
    if latency_p50 is not None:
        blessing.properties["latency_p50_ms"] = latency_p50
        blessing.properties["latency_p95_ms"] = latency_p95
    props = {"blessed": not error}
    if latency_p50 is not None:
        props["latency_p50_ms"] = latency_p50
        props["latency_p95_ms"] = latency_p95
    if error:
        props["error"] = error
    return props


def _urlopen_backoff(req, timeout: float = 60, attempts: int = 3,
                     base_delay_s: float = 0.5):
    """``urlopen`` under the shared :class:`RetryPolicy` (ISSUE 7: this
    was a private backoff loop — no jitter, invisible attempts).

    A model server that is still warming up refuses connections for a
    moment; without the retry the canary would declare the model
    NOT_BLESSED over a transient, gating a perfectly good push.  The
    shared taxonomy encodes the old contract exactly: connection-level
    failures (URLError wrapping ECONNREFUSED/reset, raw ConnectionError,
    timeouts) are transient and retried with full-jitter backoff; an
    ``HTTPError`` is PERMANENT — the server spoke, its verdict stands.
    Every retry now lands in ``retry_attempts_total{site=
    "infra_validator.urlopen"}`` on the process metrics registry.
    """
    import urllib.request

    from tpu_pipelines.robustness import RetryPolicy, retry_call

    return retry_call(
        urllib.request.urlopen,
        req,
        timeout=timeout,
        policy=RetryPolicy(
            max_attempts=attempts,
            base_delay_s=base_delay_s,
            max_delay_s=8.0,
        ),
        site="infra_validator.urlopen",
    )


def _http_canary(model_uri: str, raw: bool = True):
    """A reusable predict(batch) callable through the REST surface on a
    loopback port; ``.close()`` stops the server.  Keeping one server alive
    across the latency probes means they measure steady-state request cost,
    not model load."""
    import urllib.request

    from tpu_pipelines.serving import ModelServer

    server = ModelServer("canary", model_uri, raw=raw)
    port = server.start()

    def predict(batch) -> np.ndarray:
        instances = [
            {k: np.asarray(v[i]).tolist() for k, v in batch.items()}
            for i in range(len(next(iter(batch.values()))))
        ]
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/models/canary:predict",
            data=json.dumps({"instances": instances}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with _urlopen_backoff(req, timeout=60) as r:
            return np.asarray(json.load(r)["predictions"])

    predict.close = server.stop
    return predict


def _grpc_canary(model_uri: str, raw: bool = True):
    """predict(batch) through the gRPC surface on a loopback port."""
    from tpu_pipelines.serving import ModelServer
    from tpu_pipelines.serving.grpc_server import (
        PredictionClient,
        start_grpc_server,
    )

    server = ModelServer("canary", model_uri, raw=raw)
    grpc_server, port = start_grpc_server(server)
    client = PredictionClient(f"127.0.0.1:{port}")

    def predict(batch) -> np.ndarray:
        preds, _ = client.predict("canary", batch)
        return np.asarray(preds)

    def close() -> None:
        client.close()
        grpc_server.stop(grace=2)

    predict.close = close
    return predict
