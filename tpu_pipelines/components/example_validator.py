"""ExampleValidator: anomalies from validating statistics against a schema.

Capability match for TFX ExampleValidator / TFDV ``validate_statistics``
(SURVEY.md §2a row 4): schema-conformance checks per split, plus two
statistics-vs-statistics comparators mirroring TFDV's:

  - **drift**: this run's splits vs a *previous* statistics artifact
    (time-adjacent spans);
  - **skew**: the training split vs the other splits of the *same* artifact
    (TFDV's training/serving skew comparator — the eval/serving data a model
    will face must look like what it trained on).

Both use L-infinity distance over categorical top-value distributions and
Jensen-Shannon divergence (base 2, in [0, 1]) over numeric histograms.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Dict, List, Optional

from tpu_pipelines.data.schema import FeatureType, Schema
from tpu_pipelines.data.statistics import (
    SplitStatistics,
    load_statistics,
)
from tpu_pipelines.dsl.component import Parameter, component


@dataclasses.dataclass
class Anomaly:
    split: str
    feature: str
    kind: str          # MISSING_FEATURE | NEW_FEATURE | TYPE_MISMATCH |
                       # PRESENCE | OUT_OF_DOMAIN | OUT_OF_RANGE | DRIFT |
                       # SKEW | FEATURE_UNEXPECTED_IN_ENVIRONMENT
    severity: str      # ERROR | WARNING
    description: str


ANOMALIES_FILE = "anomalies.json"


def validate_split(
    split_stats: SplitStatistics,
    schema: Schema,
    environment: Optional[str] = None,
) -> List[Anomaly]:
    """Schema-conformance anomalies for one split.

    ``environment`` scopes presence expectations (TFDV schema
    environments): a feature not expected in the environment (e.g. the
    label under ``environment="SERVING"``) may be absent without anomaly —
    but one actually PRESENT is flagged FEATURE_UNEXPECTED_IN_ENVIRONMENT
    (TFDV's anomaly of the same name: the classic label-leakage-into-
    serving-data catch), and its type/domain/range constraints still
    apply."""
    anomalies: List[Anomaly] = []
    split = split_stats.split
    seen = set(split_stats.features)
    for name, feat in schema.features.items():
        expected = schema.expected_in(name, environment)
        fs = split_stats.features.get(name)
        if fs is None or fs.presence == 0.0:
            if not expected:
                continue
            anomalies.append(
                Anomaly(split, name, "MISSING_FEATURE", "ERROR",
                        f"schema feature {name!r} absent from split")
            )
            continue
        if not expected:
            anomalies.append(
                Anomaly(split, name, "FEATURE_UNEXPECTED_IN_ENVIRONMENT",
                        "ERROR",
                        f"feature {name!r} present in "
                        f"{fs.presence:.4f} of examples but not expected "
                        f"in environment {environment!r}")
            )
        if fs.type != feat.type.value:
            anomalies.append(
                Anomaly(split, name, "TYPE_MISMATCH", "ERROR",
                        f"expected {feat.type.value}, found {fs.type}")
            )
            continue
        if expected and fs.presence < feat.min_presence:
            anomalies.append(
                Anomaly(split, name, "PRESENCE", "ERROR",
                        f"present in {fs.presence:.4f} < required "
                        f"{feat.min_presence:.4f} of examples")
            )
        if feat.domain is not None and fs.string is not None:
            domain = set(feat.domain)
            total = sum(c for _, c in fs.string.top_values)
            bad = sum(c for v, c in fs.string.top_values if v not in domain)
            # top_values may truncate; unseen tail counts as out-of-domain
            # only when the domain was closed over full cardinality.
            frac = bad / max(1, total)
            if frac > feat.distribution_constraint:
                examples = [v for v, _ in fs.string.top_values if v not in domain][:5]
                anomalies.append(
                    Anomaly(split, name, "OUT_OF_DOMAIN", "ERROR",
                            f"{frac:.4f} of values outside domain "
                            f"(e.g. {examples})")
                )
        if feat.type in (FeatureType.INT, FeatureType.FLOAT) and fs.numeric:
            if feat.min_value is not None and fs.numeric.min < feat.min_value:
                anomalies.append(
                    Anomaly(split, name, "OUT_OF_RANGE", "ERROR",
                            f"min {fs.numeric.min} < schema min {feat.min_value}")
                )
            if feat.max_value is not None and fs.numeric.max > feat.max_value:
                anomalies.append(
                    Anomaly(split, name, "OUT_OF_RANGE", "ERROR",
                            f"max {fs.numeric.max} > schema max {feat.max_value}")
                )
    for name in seen - set(schema.features):
        anomalies.append(
            Anomaly(split, name, "NEW_FEATURE", "WARNING",
                    f"feature {name!r} not in schema")
        )
    return anomalies


def linf_categorical_distance(
    a: SplitStatistics, b: SplitStatistics, feature: str
) -> Optional[float]:
    """L-infinity distance between normalized top-value distributions."""
    fa, fb = a.features.get(feature), b.features.get(feature)
    if not (fa and fb and fa.string and fb.string):
        return None
    da = {v: c for v, c in fa.string.top_values}
    db = {v: c for v, c in fb.string.top_values}
    ta, tb = sum(da.values()) or 1, sum(db.values()) or 1
    keys = set(da) | set(db)
    return max(abs(da.get(k, 0) / ta - db.get(k, 0) / tb) for k in keys)


def _rebin(edges: List[float], counts: List[int], grid: List[float]) -> List[float]:
    """Histogram mass per ``grid`` interval, treating each source bin as a
    uniform density — exact for piecewise-constant distributions, which is
    all a histogram asserts."""
    total = float(sum(counts)) or 1.0
    out = []
    for g0, g1 in zip(grid, grid[1:]):
        m = 0.0
        for e0, e1, c in zip(edges, edges[1:], counts):
            if e1 <= g0 or e0 >= g1 or e1 == e0:
                continue
            m += c * (min(e1, g1) - max(e0, g0)) / (e1 - e0)
        out.append(m / total)
    return out


def js_numeric_divergence(
    a: SplitStatistics, b: SplitStatistics, feature: str
) -> Optional[float]:
    """Jensen-Shannon divergence (base 2, in [0, 1]) between the two splits'
    numeric histograms, rebinned onto the union of their edges so differing
    bucket boundaries compare exactly (TFDV's numeric skew/drift measure)."""
    fa, fb = a.features.get(feature), b.features.get(feature)
    if not (fa and fb and fa.numeric and fb.numeric):
        return None
    ha, hb = fa.numeric, fb.numeric
    if not (ha.histogram_edges and hb.histogram_edges):
        return None
    grid = sorted(set(ha.histogram_edges) | set(hb.histogram_edges))
    if len(grid) < 2:
        return None
    pa = _rebin(ha.histogram_edges, ha.histogram_counts, grid)
    pb = _rebin(hb.histogram_edges, hb.histogram_counts, grid)
    # Mass outside the other split's support lands in the union grid's outer
    # intervals automatically (the union covers both ranges).
    mid = [(x + y) / 2.0 for x, y in zip(pa, pb)]

    def kl(p, q):
        # q = mid >= p/2 > 0 wherever p > 0, so the sum is finite.
        return sum(x * math.log2(x / y) for x, y in zip(p, q) if x > 0)

    return 0.5 * kl(pa, mid) + 0.5 * kl(pb, mid)


def compare_splits(
    current: SplitStatistics,
    baseline: SplitStatistics,
    *,
    kind: str,
    linf_threshold: float,
    js_threshold: float,
    feature_thresholds: Optional[Dict[str, float]] = None,
    vs: str = "baseline",
) -> List[Anomaly]:
    """Distribution comparison between two splits: L-inf over categorical
    top values, JS divergence over numeric histograms.  A threshold of 0
    disables that family; ``feature_thresholds`` overrides per feature.
    Shared by the DRIFT (vs previous artifact) and SKEW (train vs eval/
    serving split) comparators."""
    overrides = feature_thresholds or {}
    anomalies: List[Anomaly] = []
    for name in current.features:
        linf_t = overrides.get(name, linf_threshold)
        if linf_t:
            d = linf_categorical_distance(current, baseline, name)
            if d is not None and d > linf_t:
                anomalies.append(
                    Anomaly(current.split, name, kind, "ERROR",
                            f"L-inf distance {d:.4f} > {linf_t} vs {vs}")
                )
        js_t = overrides.get(name, js_threshold)
        if js_t:
            d = js_numeric_divergence(current, baseline, name)
            if d is not None and d > js_t:
                anomalies.append(
                    Anomaly(current.split, name, kind, "ERROR",
                            f"JS divergence {d:.4f} > {js_t} vs {vs}")
                )
    return anomalies


@component(
    inputs={"statistics": "ExampleStatistics", "schema": "Schema"},
    outputs={"anomalies": "ExampleAnomalies"},
    parameters={
        # Optional uri of a previous ExampleStatistics payload for drift.
        "baseline_statistics_uri": Parameter(type=str, default=""),
        "drift_threshold": Parameter(type=float, default=0.3),
        # JS-divergence threshold for numeric drift (0 = categorical only,
        # the pre-existing behavior).
        "drift_js_threshold": Parameter(type=float, default=0.0),
        # Training/serving skew: compare skew_baseline_split's distributions
        # against every other split in THIS statistics artifact.  0 disables
        # that family; skew_feature_thresholds overrides per feature.
        "skew_baseline_split": Parameter(type=str, default="train"),
        "skew_linf_threshold": Parameter(type=float, default=0.0),
        "skew_js_threshold": Parameter(type=float, default=0.0),
        "skew_feature_thresholds": Parameter(type=dict, default=None),
        # Schema environment to validate under ("" = no environment: every
        # feature expected).  ExampleValidator(environment="SERVING")
        # validates label-less serving data against the training schema
        # without MISSING_FEATURE noise (TFDV schema environments).
        "environment": Parameter(type=str, default=""),
        # Fail the pipeline on ERROR-severity anomalies.
        "fail_on_anomalies": Parameter(type=bool, default=True),
    },
    is_sink=True,
)
def ExampleValidator(ctx):
    stats = load_statistics(ctx.input("statistics").uri)
    schema = Schema.load(ctx.input("schema").uri)
    environment = ctx.exec_properties.get("environment") or None
    anomalies: List[Anomaly] = []
    for split_stats in stats.values():
        anomalies.extend(validate_split(split_stats, schema, environment))

    baseline_uri = ctx.exec_properties["baseline_statistics_uri"]
    if baseline_uri:
        baseline = load_statistics(baseline_uri)
        for split, s in stats.items():
            prev = baseline.get(split)
            if prev is None:
                continue
            anomalies.extend(compare_splits(
                s, prev, kind="DRIFT",
                linf_threshold=ctx.exec_properties["drift_threshold"],
                js_threshold=ctx.exec_properties["drift_js_threshold"],
            ))

    skew_linf = ctx.exec_properties["skew_linf_threshold"]
    skew_js = ctx.exec_properties["skew_js_threshold"]
    skew_overrides = ctx.exec_properties["skew_feature_thresholds"]
    if skew_linf or skew_js or skew_overrides:
        train_split = ctx.exec_properties["skew_baseline_split"]
        train = stats.get(train_split)
        if train is None:
            raise ValueError(
                f"skew comparison needs split {train_split!r}; artifact has "
                f"{sorted(stats)}"
            )
        for split, s in stats.items():
            if split == train_split:
                continue
            anomalies.extend(compare_splits(
                s, train, kind="SKEW",
                linf_threshold=skew_linf,
                js_threshold=skew_js,
                feature_thresholds=skew_overrides,
                vs=f"{train_split} split",
            ))

    out = ctx.output("anomalies")
    os.makedirs(out.uri, exist_ok=True)
    with open(os.path.join(out.uri, ANOMALIES_FILE), "w") as f:
        json.dump([dataclasses.asdict(a) for a in anomalies], f, indent=2)
    n_errors = sum(1 for a in anomalies if a.severity == "ERROR")
    out.properties["anomaly_count"] = len(anomalies)
    out.properties["error_count"] = n_errors
    if n_errors and ctx.exec_properties["fail_on_anomalies"]:
        raise ValueError(
            f"{n_errors} ERROR anomalies: "
            + "; ".join(
                f"{a.split}/{a.feature}:{a.kind}" for a in anomalies
                if a.severity == "ERROR"
            )
        )
    return {"anomaly_count": len(anomalies), "error_count": n_errors}


def load_anomalies(uri: str) -> List[Anomaly]:
    with open(os.path.join(uri, ANOMALIES_FILE)) as f:
        return [Anomaly(**d) for d in json.load(f)]
