"""ExampleValidator: anomalies from validating statistics against a schema.

Capability match for TFX ExampleValidator / TFDV ``validate_statistics``
(SURVEY.md §2a row 4): schema-conformance checks per split, plus optional
drift detection against a previous statistics artifact (L-infinity distance
over categorical distributions — the TFDV drift comparator).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional

from tpu_pipelines.data.schema import FeatureType, Schema
from tpu_pipelines.data.statistics import (
    SplitStatistics,
    load_statistics,
)
from tpu_pipelines.dsl.component import Parameter, component


@dataclasses.dataclass
class Anomaly:
    split: str
    feature: str
    kind: str          # MISSING_FEATURE | NEW_FEATURE | TYPE_MISMATCH |
                       # PRESENCE | OUT_OF_DOMAIN | OUT_OF_RANGE | DRIFT
    severity: str      # ERROR | WARNING
    description: str


ANOMALIES_FILE = "anomalies.json"


def validate_split(
    split_stats: SplitStatistics, schema: Schema
) -> List[Anomaly]:
    anomalies: List[Anomaly] = []
    split = split_stats.split
    seen = set(split_stats.features)
    for name, feat in schema.features.items():
        fs = split_stats.features.get(name)
        if fs is None or fs.presence == 0.0:
            anomalies.append(
                Anomaly(split, name, "MISSING_FEATURE", "ERROR",
                        f"schema feature {name!r} absent from split")
            )
            continue
        if fs.type != feat.type.value:
            anomalies.append(
                Anomaly(split, name, "TYPE_MISMATCH", "ERROR",
                        f"expected {feat.type.value}, found {fs.type}")
            )
            continue
        if fs.presence < feat.min_presence:
            anomalies.append(
                Anomaly(split, name, "PRESENCE", "ERROR",
                        f"present in {fs.presence:.4f} < required "
                        f"{feat.min_presence:.4f} of examples")
            )
        if feat.domain is not None and fs.string is not None:
            domain = set(feat.domain)
            total = sum(c for _, c in fs.string.top_values)
            bad = sum(c for v, c in fs.string.top_values if v not in domain)
            # top_values may truncate; unseen tail counts as out-of-domain
            # only when the domain was closed over full cardinality.
            frac = bad / max(1, total)
            if frac > feat.distribution_constraint:
                examples = [v for v, _ in fs.string.top_values if v not in domain][:5]
                anomalies.append(
                    Anomaly(split, name, "OUT_OF_DOMAIN", "ERROR",
                            f"{frac:.4f} of values outside domain "
                            f"(e.g. {examples})")
                )
        if feat.type in (FeatureType.INT, FeatureType.FLOAT) and fs.numeric:
            if feat.min_value is not None and fs.numeric.min < feat.min_value:
                anomalies.append(
                    Anomaly(split, name, "OUT_OF_RANGE", "ERROR",
                            f"min {fs.numeric.min} < schema min {feat.min_value}")
                )
            if feat.max_value is not None and fs.numeric.max > feat.max_value:
                anomalies.append(
                    Anomaly(split, name, "OUT_OF_RANGE", "ERROR",
                            f"max {fs.numeric.max} > schema max {feat.max_value}")
                )
    for name in seen - set(schema.features):
        anomalies.append(
            Anomaly(split, name, "NEW_FEATURE", "WARNING",
                    f"feature {name!r} not in schema")
        )
    return anomalies


def linf_categorical_distance(
    a: SplitStatistics, b: SplitStatistics, feature: str
) -> Optional[float]:
    """L-infinity distance between normalized top-value distributions."""
    fa, fb = a.features.get(feature), b.features.get(feature)
    if not (fa and fb and fa.string and fb.string):
        return None
    da = {v: c for v, c in fa.string.top_values}
    db = {v: c for v, c in fb.string.top_values}
    ta, tb = sum(da.values()) or 1, sum(db.values()) or 1
    keys = set(da) | set(db)
    return max(abs(da.get(k, 0) / ta - db.get(k, 0) / tb) for k in keys)


@component(
    inputs={"statistics": "ExampleStatistics", "schema": "Schema"},
    outputs={"anomalies": "ExampleAnomalies"},
    parameters={
        # Optional uri of a previous ExampleStatistics payload for drift.
        "baseline_statistics_uri": Parameter(type=str, default=""),
        "drift_threshold": Parameter(type=float, default=0.3),
        # Fail the pipeline on ERROR-severity anomalies.
        "fail_on_anomalies": Parameter(type=bool, default=True),
    },
)
def ExampleValidator(ctx):
    stats = load_statistics(ctx.input("statistics").uri)
    schema = Schema.load(ctx.input("schema").uri)
    anomalies: List[Anomaly] = []
    for split_stats in stats.values():
        anomalies.extend(validate_split(split_stats, schema))

    baseline_uri = ctx.exec_properties["baseline_statistics_uri"]
    if baseline_uri:
        baseline = load_statistics(baseline_uri)
        thresh = ctx.exec_properties["drift_threshold"]
        for split, s in stats.items():
            prev = baseline.get(split)
            if prev is None:
                continue
            for name in s.features:
                d = linf_categorical_distance(s, prev, name)
                if d is not None and d > thresh:
                    anomalies.append(
                        Anomaly(split, name, "DRIFT", "ERROR",
                                f"L-inf distance {d:.4f} > {thresh} vs baseline")
                    )

    out = ctx.output("anomalies")
    os.makedirs(out.uri, exist_ok=True)
    with open(os.path.join(out.uri, ANOMALIES_FILE), "w") as f:
        json.dump([dataclasses.asdict(a) for a in anomalies], f, indent=2)
    n_errors = sum(1 for a in anomalies if a.severity == "ERROR")
    out.properties["anomaly_count"] = len(anomalies)
    out.properties["error_count"] = n_errors
    if n_errors and ctx.exec_properties["fail_on_anomalies"]:
        raise ValueError(
            f"{n_errors} ERROR anomalies: "
            + "; ".join(
                f"{a.split}/{a.feature}:{a.kind}" for a in anomalies
                if a.severity == "ERROR"
            )
        )
    return {"anomaly_count": len(anomalies), "error_count": n_errors}


def load_anomalies(uri: str) -> List[Anomaly]:
    with open(os.path.join(uri, ANOMALIES_FILE)) as f:
        return [Anomaly(**d) for d in json.load(f)]
