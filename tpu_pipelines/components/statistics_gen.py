"""StatisticsGen: full-pass per-split statistics over an Examples artifact.

Capability match for TFX StatisticsGen / TFDV GenerateStatistics (SURVEY.md
§2a row 2), as vectorized Arrow/numpy reductions instead of Beam.
"""

from __future__ import annotations

from tpu_pipelines.data import examples_io
from tpu_pipelines.data.statistics import (
    compute_split_statistics,
    save_statistics,
)
from tpu_pipelines.dsl.component import component


@component(
    inputs={"examples": "Examples"},
    outputs={"statistics": "ExampleStatistics"},
)
def StatisticsGen(ctx):
    examples = ctx.input("examples")
    splits = examples_io.split_names(examples.uri)
    if not splits:
        raise ValueError(f"Examples artifact at {examples.uri} has no splits")
    stats = {}
    for split in splits:
        table = examples_io.read_split_table(examples.uri, split)
        stats[split] = compute_split_statistics(split, table)
    out = ctx.output("statistics")
    save_statistics(out.uri, stats)
    out.properties["split_names"] = splits
    return {
        f"num_examples_{s}": stats[s].num_examples for s in splits
    }
