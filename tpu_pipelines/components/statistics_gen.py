"""StatisticsGen: full-pass per-split statistics over an Examples artifact.

Capability match for TFX StatisticsGen / TFDV GenerateStatistics (SURVEY.md
§2a row 2), as vectorized Arrow/numpy reductions instead of Beam.
"""

from __future__ import annotations

from tpu_pipelines.data import examples_io
from tpu_pipelines.data.statistics import (
    SplitStatsAccumulator,
    save_statistics,
)
from tpu_pipelines.dsl.component import Parameter, component


@component(
    inputs={"examples": "Examples"},
    outputs={"statistics": "ExampleStatistics"},
    parameters={
        # Rows per streamed chunk; peak host memory is O(chunk + reservoir),
        # never O(split).  0 = the Parquet row-group size.
        "chunk_rows": Parameter(type=int, default=0),
    },
)
def StatisticsGen(ctx):
    examples = ctx.input("examples")
    splits = examples_io.split_names(examples.uri)
    if not splits:
        raise ValueError(f"Examples artifact at {examples.uri} has no splits")
    chunk_rows = (
        ctx.exec_properties.get("chunk_rows") or examples_io.DEFAULT_ROW_GROUP
    )
    stats = {}
    for split in splits:
        acc = SplitStatsAccumulator(split)
        for table in examples_io.iter_table_chunks(
            examples.uri, split, rows=chunk_rows
        ):
            acc.update(table)
        stats[split] = acc.finalize()
    out = ctx.output("statistics")
    save_statistics(out.uri, stats)
    out.properties["split_names"] = splits
    return {
        f"num_examples_{s}": stats[s].num_examples for s in splits
    }
