"""StatisticsGen: full-pass per-split statistics over an Examples artifact.

Capability match for TFX StatisticsGen / TFDV GenerateStatistics (SURVEY.md
§2a row 2), as vectorized Arrow/numpy reductions instead of Beam.  Sharded
splits (examples_io native layout) accumulate per shard in a process pool
and merge — the accumulate/merge/extract CombineFn cycle Beam runs across a
cluster, here across host cores; merged output is identity-equal to the
single-pass result (exact for counts/min/max/top-k, float-summation-order
for mean/std, reservoir-exact while the split fits the reservoir).
"""

from __future__ import annotations

from tpu_pipelines.data import examples_io
from tpu_pipelines.data.shard_plan import ShardPlan, map_shards_resilient
from tpu_pipelines.data.statistics import (
    SplitStatsAccumulator,
    accumulate_split_shard,
    merge_accumulators,
    save_statistics,
)
from tpu_pipelines.dsl.component import Parameter, component

# Single-pass default (SplitStatsAccumulator) — repeated here so the pool
# tasks and the sequential path agree without reaching into class defaults.
_RESERVOIR_SIZE = 1 << 17


@component(
    inputs={"examples": "Examples"},
    outputs={"statistics": "ExampleStatistics"},
    parameters={
        # Rows per streamed chunk; peak host memory is O(chunk + reservoir),
        # never O(split).  0 = the Parquet row-group size.
        "chunk_rows": Parameter(type=int, default=0),
        # Worker cap for per-shard accumulation (ShardPlan precedence:
        # this param > TPP_DATA_SHARDS > host_cpus).  Parallelism itself
        # comes from the artifact's shard layout; a single-file split always
        # takes the sequential path regardless of this value.
        "num_shards": Parameter(type=int, default=None),
        # Partial-salvage mode (docs/RECOVERY.md): when a shard strikes
        # out of the resilient pool (poisoned file, worker that dies on
        # every retry), quarantine it and merge the SURVIVING shards —
        # merged statistics stay exact over the rows actually read
        # (SplitStatsAccumulator.merge is order-exact), and the
        # quarantined shard ids land on the execution + artifact so the
        # degradation is lineage-visible, never silent.  Off by default:
        # a struck-out shard fails the node.
        "salvage_shards": Parameter(type=bool, default=False),
        # Persist the PRE-MERGE per-shard accumulators (accumulators.pkl)
        # alongside stats.json, making this artifact mergeable with other
        # spans' statistics (docs/CONTINUOUS.md): the continuous window
        # merger folds them in global shard order and finalizes once, so
        # incremental merged stats reproduce a cold full-window pass.
        "save_accumulators": Parameter(type=bool, default=False),
    },
)
def StatisticsGen(ctx):
    examples = ctx.input("examples")
    splits = examples_io.split_names(examples.uri)
    if not splits:
        raise ValueError(f"Examples artifact at {examples.uri} has no splits")
    chunk_rows = (
        ctx.exec_properties.get("chunk_rows") or examples_io.DEFAULT_ROW_GROUP
    )
    plan = ShardPlan.resolve(ctx.exec_properties.get("num_shards"))
    salvage = bool(ctx.exec_properties.get("salvage_shards", False))
    keep_accs = bool(ctx.exec_properties.get("save_accumulators", False))
    stats = {}
    shard_accs = {}
    shard_counts = {}
    quarantined = {}
    for split in splits:
        n_shards = examples_io.num_split_shards(examples.uri, split)
        shard_counts[split] = n_shards
        if n_shards > 1:
            res = map_shards_resilient(
                accumulate_split_shard,
                [
                    (examples.uri, split, i, chunk_rows, _RESERVOIR_SIZE)
                    for i in range(n_shards)
                ],
                workers=min(plan.num_shards, n_shards),
            )
            if res.errors and not salvage:
                res.raise_on_failure()
            if res.errors:
                if len(res.errors) == n_shards:
                    # Nothing survived: "salvage" would fabricate an
                    # empty-statistics artifact for a split that has rows.
                    res.raise_on_failure()
                quarantined[split] = res.failure_summary()
            accs = [a for a in res.results if a is not None]
            if keep_accs:
                # merge_accumulators folds IN PLACE into accs[0]; the
                # persisted shard accumulators must stay pre-merge, so
                # the merge runs on copies (identical values — merge is
                # a pure function of the accumulator state).
                import copy

                shard_accs[split] = accs
                acc = merge_accumulators([copy.deepcopy(a) for a in accs])
            else:
                acc = merge_accumulators(accs)
        else:
            acc = SplitStatsAccumulator(split)
            for table in examples_io.iter_table_chunks(
                examples.uri, split, rows=chunk_rows
            ):
                acc.update(table)
            if keep_accs:
                shard_accs[split] = [acc]  # finalize() does not mutate
        stats[split] = acc.finalize()
    out = ctx.output("statistics")
    save_statistics(out.uri, stats)
    if keep_accs:
        from tpu_pipelines.data.statistics import save_split_accumulators

        save_split_accumulators(out.uri, shard_accs)
        out.properties["mergeable"] = True
    # Span lineage rides through (docs/CONTINUOUS.md): a per-span
    # statistics artifact must be joinable back to its span without a
    # store walk, so the rolling-window resolver can pair it with the
    # span's Examples.
    for key in ("span", "version"):
        if key in examples.properties:
            out.properties[key] = examples.properties[key]
    out.properties["split_names"] = splits
    props = {
        "data_shards": shard_counts,
        "shard_workers": plan.num_shards,
        "shard_plan_source": plan.source,
        **{f"num_examples_{s}": stats[s].num_examples for s in splits},
    }
    if quarantined:
        # Lineage-visible degradation: which shards were salvaged away,
        # and why — on the artifact (downstream consumers can refuse
        # partial stats) and the execution record (audit trail).
        out.properties["quarantined_shards"] = {
            split: sorted(errs) for split, errs in quarantined.items()
        }
        props["quarantined_shards"] = quarantined
        props["partial_statistics"] = True
    return props
