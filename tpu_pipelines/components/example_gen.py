"""ExampleGen: ingest external data, hash-split, emit an Examples artifact.

Capability match for TFX's ``CsvExampleGen`` / ``ImportExampleGen``
(SURVEY.md §2a row 1): CSV (or pre-built Arrow/Parquet/numpy) in, deterministic
train/eval splits out.  Splitting is content-hash bucketing — stable under row
reordering, independent of process seeds — the same contract as TFX's
hash-bucket SplitConfig.  No Beam: pyarrow reads the file columnar, the hash
is vectorized over a string join of the row.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Dict

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.csv as pacsv

from tpu_pipelines.data import examples_io
from tpu_pipelines.data.shard_plan import ShardPlan
from tpu_pipelines.dsl.component import Parameter, component
from tpu_pipelines.utils.hashing import hash_buckets

DEFAULT_SPLITS = {"train": 2, "eval": 1}


def _row_hash_buckets(table: pa.Table, num_buckets: int) -> np.ndarray:
    """Stable per-row bucket: vectorized FNV of the joined stringified row.

    Arrow compute stringifies and joins the columns; utils/hashing does the
    columnwise-vectorized hash — no per-row Python loop anywhere.
    """
    cols = []
    for name in table.column_names:
        col = table.column(name)
        if pa.types.is_nested(col.type):
            # Rare path (list columns): stringify via python.
            cols.append(pa.array([str(v) for v in col.to_pylist()]))
        else:
            cols.append(pc.fill_null(col.cast(pa.string()), ""))
    joined = pc.binary_join_element_wise(*cols, "\x1f")
    return hash_buckets(
        joined.to_numpy(zero_copy_only=False), num_buckets
    )


def _split_and_write(
    table: pa.Table, uri: str, splits: Dict[str, int], num_shards: int = 1
) -> Dict[str, int]:
    total = sum(splits.values())
    buckets = _row_hash_buckets(table, total)
    counts: Dict[str, int] = {}
    lo = 0
    for split, weight in splits.items():
        hi = lo + weight
        mask = (buckets >= lo) & (buckets < hi)
        sub = table.filter(pa.array(mask))
        # Native layout always (data-%05d-of-N); shard writes parallelize
        # inside write_split.  Split membership is the per-row hash above —
        # identical for every num_shards.
        examples_io.write_split(uri, split, sub, num_shards=num_shards)
        counts[split] = sub.num_rows
        lo = hi
    return counts


def _shard_worker(
    w: int,
    q: "queue.Queue",
    uri: str,
    splits: Dict[str, int],
    schema: pa.Schema,
    num_shards: int,
    counts: Dict[str, int],
    lock: "threading.Lock",
) -> None:
    """One ingest worker = one shard of every split: hash, filter, encode,
    write — the per-shard pipeline that makes streaming ingest scale with
    cores (hashing and Parquet encode release the GIL)."""
    total = sum(splits.values())
    writers = {
        split: examples_io.open_split_writer(
            uri, split, schema, shard=w, num_shards=num_shards
        )
        for split in splits
    }
    try:
        while True:
            batch = q.get()
            if batch is None:
                return
            table = pa.Table.from_batches([batch])
            buckets = _row_hash_buckets(table, total)
            lo = 0
            for split, weight in splits.items():
                hi = lo + weight
                mask = (buckets >= lo) & (buckets < hi)
                lo = hi
                sub = table.filter(pa.array(mask))
                if sub.num_rows:
                    writers[split].write_table(
                        sub, row_group_size=examples_io.DEFAULT_ROW_GROUP
                    )
                with lock:
                    counts[split] += sub.num_rows
    finally:
        for wr in writers.values():
            wr.close()


def _split_and_write_streaming(
    batches, uri: str, splits: Dict[str, int], schema: pa.Schema,
    num_shards: int = 1,
) -> Dict[str, int]:
    """Hash-split a stream of record batches into per-split Parquet shards.

    The out-of-core ingest path (the Beam-pipeline equivalent of SURVEY.md
    §2a ExampleGen): peak memory is O(read block * num_shards), never
    O(file).  Row-hash bucketing is per-row content, so streaming,
    whole-table, and any-shard-count ingest assign every row to the
    identical split; what ``num_shards`` changes is only how split rows
    spread across shard files (read blocks round-robin to workers, each
    worker owning one shard of every split).  Every writer opens upfront
    from ``schema``, so empty splits/shards still materialize, exactly like
    the whole-table path.
    """
    counts: Dict[str, int] = {s: 0 for s in splits}
    lock = threading.Lock()
    # Bounded per-worker queues keep memory at O(read block) per worker
    # while letting the reader run ahead of slow encoders.
    queues: list = [queue.Queue(maxsize=4) for _ in range(num_shards)]
    errors: list = []

    def run_worker(w: int) -> None:
        try:
            _shard_worker(
                w, queues[w], uri, splits, schema, num_shards, counts, lock
            )
        except BaseException as e:  # noqa: BLE001 — re-raised in the reader
            errors.append(e)
            # Keep draining so the reader's bounded put never deadlocks
            # against a dead worker.
            while queues[w].get() is not None:
                pass

    workers = [
        threading.Thread(
            target=run_worker, args=(w,),
            name=f"tpp-ingest-shard-{w}", daemon=True,
        )
        for w in range(num_shards)
    ]
    for t in workers:
        t.start()
    try:
        for i, batch in enumerate(batches):
            queues[i % num_shards].put(batch)
    finally:
        for wq in queues:
            wq.put(None)
        for t in workers:
            t.join()
    if errors:
        raise errors[0]
    return counts


def _convert_options(column_types):
    if not column_types:
        return None
    return pacsv.ConvertOptions(column_types={
        name: pa.type_for_alias(alias) for name, alias in column_types.items()
    })


@component(
    outputs={"examples": "Examples"},
    parameters={
        "input_path": Parameter(type=str, required=True),
        # {"train": 2, "eval": 1} -> 2/3 train, 1/3 eval by content hash.
        "splits": Parameter(type=dict, default=None),
        # Files above this many bytes stream through pyarrow's incremental
        # CSV reader into per-split writers (O(block) memory) instead of
        # being read whole.  0 = always stream.
        "streaming_threshold_bytes":  # tpp: disable=TPP214 (parameter)
            Parameter(type=int, default=256 << 20),
        # Optional {column: arrow-type-alias} (e.g. {"fare": "float64"}).
        # The streaming reader infers types from its FIRST block only, so
        # pin any column whose type could shift deeper into a large file
        # (whole-file inference below the threshold has no such limit).
        "column_types": Parameter(type=dict, default=None),
        # Span/version selection (the TFX ExampleGen convention): when
        # input_path contains "{SPAN}" (and optionally "{VERSION}"), the
        # highest numbered match ingests unless pinned here.  The runner
        # resolves the same pattern when content-fingerprinting, so a new
        # span invalidates the execution cache.
        "span": Parameter(type=int, default=None),
        "version": Parameter(type=int, default=None),
        # Shard files per split (examples_io native layout).  None follows
        # the ShardPlan precedence: TPP_DATA_SHARDS env, else host_cpus.
        # Split membership is per-row content hash, so it is byte-identical
        # at every shard count — only the file spread changes.
        "num_shards": Parameter(type=int, default=None),
    },
    external_input_parameters=("input_path",),
)
def CsvExampleGen(ctx):
    """Read CSV file(s), hash-split, write Parquet — streaming when large."""
    from tpu_pipelines.utils.span import has_span_pattern, resolve_span_pattern

    path = ctx.exec_properties["input_path"]
    span = version = None
    if has_span_pattern(path):
        path, span, version = resolve_span_pattern(
            path,
            ctx.exec_properties.get("span"),
            ctx.exec_properties.get("version"),
        )
    splits = ctx.exec_properties["splits"] or dict(DEFAULT_SPLITS)
    threshold = ctx.exec_properties["streaming_threshold_bytes"]  # tpp: disable=TPP214 (parameter)
    plan = ShardPlan.resolve(ctx.exec_properties.get("num_shards"))
    convert = _convert_options(ctx.exec_properties["column_types"])
    if os.path.isdir(path):
        files = sorted(
            os.path.join(path, f) for f in os.listdir(path) if f.endswith(".csv")
        )
        if not files:
            raise ValueError(f"no .csv files under {path!r}")
    else:
        files = [path]
    out = ctx.output("examples")
    t0 = time.monotonic()
    total_bytes = sum(os.path.getsize(f) for f in files)
    if total_bytes > threshold:
        first = pacsv.open_csv(files[0], convert_options=convert)

        def batches():
            with first as reader:
                yield from reader
            for f in files[1:]:
                with pacsv.open_csv(f, convert_options=convert) as reader:
                    yield from reader

        try:
            counts = _split_and_write_streaming(
                batches(), out.uri, splits, first.schema,
                num_shards=plan.num_shards,
            )
        except (pa.ArrowInvalid, pa.ArrowTypeError) as e:
            # The streaming reader infers each column's type from its FIRST
            # block only; a type that shifts deeper in a large file (ints
            # then floats, empty then strings, a schema differing across
            # files) surfaces here as a raw Arrow cast error mid-stream.
            raise ValueError(
                f"streaming CSV ingest of {path!r} failed mid-stream: {e}\n"
                "The streaming reader pins column types from the first "
                "block. If a column's type shifts deeper in the file (or "
                "across files), pin it explicitly via the column_types "
                "parameter, e.g. column_types={'fare': 'float64'}; "
                "whole-file reads (below streaming_threshold_bytes) infer "
                "from every row instead."
            ) from e
    else:
        table = pa.concat_tables([
            pacsv.read_csv(f, convert_options=convert) for f in files
        ])
        counts = _split_and_write(
            table, out.uri, splits, num_shards=plan.num_shards
        )
    out.properties["split_names"] = sorted(counts)
    out.properties["split_counts"] = counts
    out.properties["num_shards"] = plan.num_shards
    if span is not None:
        out.properties["span"] = span
    if version is not None:
        out.properties["version"] = version
    n = sum(counts.values())
    elapsed = max(1e-9, time.monotonic() - t0)
    props = {
        "num_examples": n,
        # Observability parity with the per-stage counters Beam jobs expose.
        "ingest_rows_per_sec": round(n / elapsed, 1),
        "data_shards": plan.num_shards,
        "shard_plan_source": plan.source,
        **{f"rows_{k}": v for k, v in counts.items()},
    }
    if span is not None:
        props["span"] = span
    if version is not None:
        props["version"] = version
    return props


RECORD_SUFFIXES = (".tfrecord", ".tfrecords", ".array_record", ".arrayrecord")


def _record_reader(path: str, verify_crc: bool = True):
    from tpu_pipelines.data import record_io

    if path.endswith((".array_record", ".arrayrecord")):
        return record_io.iter_array_records(path)
    return record_io.iter_tfrecords(path, verify_crc=verify_crc)


def _import_record_files(files, out_uri: str, splits: Dict[str, int],
                         per_split: bool,
                         verify_crc: bool = True,
                         num_shards: int = 1) -> Dict[str, int]:
    """tf.train.Example record files → Parquet splits, O(chunk) memory.

    ``per_split=True``: each file IS a split (``<split>.tfrecord``).
    Otherwise all files concatenate and hash-split row-by-row, identically
    to the CSV path.
    """
    from tpu_pipelines.data import record_io

    counts: Dict[str, int] = {}
    if per_split:
        stems = [os.path.splitext(os.path.basename(f))[0] for f in files]
        dupes = sorted({s for s in stems if stems.count(s) > 1})
        if dupes:
            raise ValueError(
                f"multiple record files map to the same split name(s) "
                f"{dupes} (e.g. train.tfrecord + train.tfrecords); "
                "one file per split"
            )
        for f in files:
            split = os.path.splitext(os.path.basename(f))[0]
            writer = None
            counts[split] = 0
            try:
                for batch in record_io.tf_example_batches(
                        _record_reader(f, verify_crc)):
                    if writer is None:
                        writer = examples_io.open_split_writer(
                            out_uri, split, batch.schema
                        )
                    writer.write_table(pa.Table.from_batches([batch]))
                    counts[split] += batch.num_rows
            finally:
                if writer is not None:
                    writer.close()
            if writer is None:
                raise ValueError(f"record file {f!r} is empty")
        return counts

    def batches():
        for f in files:
            yield from record_io.tf_example_batches(
                _record_reader(f, verify_crc))

    it = batches()
    first = next(it, None)
    if first is None:
        raise ValueError(f"no records in {files!r}")

    def chained():
        yield first
        yield from it

    return _split_and_write_streaming(
        chained(), out_uri, splits, first.schema, num_shards=num_shards
    )


@component(
    outputs={"examples": "Examples"},
    parameters={
        # Path to a directory of <split>.parquet (or <split>.tfrecord /
        # <split>.array_record) files, a single record file, OR an .npz file
        # whose arrays are columns (MNIST-style tensors allowed: dims beyond
        # the first are flattened into fixed-length list columns).
        "input_path": Parameter(type=str, required=True),
        "splits": Parameter(type=dict, default=None),
        # TFRecord masked-crc32c verification (record_io module note).
        # False = trusted-source opt-out, also the escape hatch for
        # third-party writers that zero or mis-mask the crc fields.
        "verify_record_crc": Parameter(type=bool, default=True),
        # Shard files per split for the hash-split paths (ShardPlan
        # precedence, see CsvExampleGen).  The split-per-file import paths
        # keep one file per split: the import IS the layout there.
        "num_shards": Parameter(type=int, default=None),
    },
    external_input_parameters=("input_path",),
)
def ImportExampleGen(ctx):
    """Import already-materialized data as an Examples artifact.

    Accepted layouts:
      - directory with ``<split>.parquet`` files → imported split-per-file
      - directory with ``<split>.tfrecord``/``.array_record`` files of
        ``tf.train.Example`` payloads → parsed split-per-file (the
        reference's canonical ingest format, SURVEY.md §2a ExampleGen;
        parsed TF-free by data/record_io.py)
      - a single record file → parsed, then hash-split like CsvExampleGen
      - a single ``.npz`` → columns hash-split like CsvExampleGen
    """
    path = ctx.exec_properties["input_path"]
    out = ctx.output("examples")
    plan = ShardPlan.resolve(ctx.exec_properties.get("num_shards"))
    t0 = time.monotonic()
    counts: Dict[str, int] = {}
    if os.path.isdir(path):
        import pyarrow.parquet as pq

        files = sorted(f for f in os.listdir(path) if f.endswith(".parquet"))
        record_files = sorted(
            f for f in os.listdir(path) if f.endswith(RECORD_SUFFIXES)
        )
        if not files and not record_files:
            raise ValueError(
                f"no .parquet or record files under {path!r}"
            )
        if files and record_files:
            raise ValueError(
                f"mixed .parquet and record files under {path!r}; "
                "one format per import"
            )
        if record_files:
            counts = _import_record_files(
                [os.path.join(path, f) for f in record_files],
                out.uri, {}, per_split=True,
                verify_crc=ctx.exec_properties["verify_record_crc"],
            )
            files = []
        for f in files:
            split = os.path.splitext(f)[0]
            table = pq.read_table(os.path.join(path, f))
            examples_io.write_split(out.uri, split, table)
            counts[split] = table.num_rows
    elif path.endswith(RECORD_SUFFIXES):
        splits = ctx.exec_properties["splits"] or dict(DEFAULT_SPLITS)
        counts = _import_record_files(
            [path], out.uri, splits, per_split=False,
            verify_crc=ctx.exec_properties["verify_record_crc"],
            num_shards=plan.num_shards,
        )
    elif path.endswith(".npz"):
        data = np.load(path)
        arrays = {}
        for name in data.files:
            arr = data[name]
            if arr.ndim > 2:
                arr = arr.reshape(arr.shape[0], -1)
            if arr.ndim == 2:
                arrays[name] = pa.array(list(arr))
            else:
                arrays[name] = pa.array(arr)
        table = pa.table(arrays)
        splits = ctx.exec_properties["splits"] or dict(DEFAULT_SPLITS)
        counts = _split_and_write(
            table, out.uri, splits, num_shards=plan.num_shards
        )
    else:
        raise ValueError(f"unsupported import source: {path!r}")
    out.properties["split_names"] = sorted(counts)
    out.properties["split_counts"] = counts
    n = sum(counts.values())
    return {
        "num_examples": n,
        "ingest_rows_per_sec": round(
            n / max(1e-9, time.monotonic() - t0), 1
        ),
        "data_shards": plan.num_shards,
        "shard_plan_source": plan.source,
    }
