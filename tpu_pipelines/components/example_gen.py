"""ExampleGen: ingest external data, hash-split, emit an Examples artifact.

Capability match for TFX's ``CsvExampleGen`` / ``ImportExampleGen``
(SURVEY.md §2a row 1): CSV (or pre-built Arrow/Parquet/numpy) in, deterministic
train/eval splits out.  Splitting is content-hash bucketing — stable under row
reordering, independent of process seeds — the same contract as TFX's
hash-bucket SplitConfig.  No Beam: pyarrow reads the file columnar, the hash
is vectorized over a string join of the row.
"""

from __future__ import annotations

import os
from typing import Dict

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.csv as pacsv

from tpu_pipelines.data import examples_io
from tpu_pipelines.dsl.component import Parameter, component
from tpu_pipelines.utils.hashing import hash_buckets

DEFAULT_SPLITS = {"train": 2, "eval": 1}


def _row_hash_buckets(table: pa.Table, num_buckets: int) -> np.ndarray:
    """Stable per-row bucket: vectorized FNV of the joined stringified row.

    Arrow compute stringifies and joins the columns; utils/hashing does the
    columnwise-vectorized hash — no per-row Python loop anywhere.
    """
    cols = []
    for name in table.column_names:
        col = table.column(name)
        if pa.types.is_nested(col.type):
            # Rare path (list columns): stringify via python.
            cols.append(pa.array([str(v) for v in col.to_pylist()]))
        else:
            cols.append(pc.fill_null(col.cast(pa.string()), ""))
    joined = pc.binary_join_element_wise(*cols, "\x1f")
    return hash_buckets(
        joined.to_numpy(zero_copy_only=False), num_buckets
    )


def _split_and_write(table: pa.Table, uri: str, splits: Dict[str, int]) -> Dict[str, int]:
    total = sum(splits.values())
    buckets = _row_hash_buckets(table, total)
    counts: Dict[str, int] = {}
    lo = 0
    for split, weight in splits.items():
        hi = lo + weight
        mask = (buckets >= lo) & (buckets < hi)
        sub = table.filter(pa.array(mask))
        examples_io.write_split(uri, split, sub)
        counts[split] = sub.num_rows
        lo = hi
    return counts


@component(
    outputs={"examples": "Examples"},
    parameters={
        "input_path": Parameter(type=str, required=True),
        # {"train": 2, "eval": 1} -> 2/3 train, 1/3 eval by content hash.
        "splits": Parameter(type=dict, default=None),
    },
    external_input_parameters=("input_path",),
)
def CsvExampleGen(ctx):
    """Read a CSV file (or directory of CSVs), hash-split, write Parquet."""
    path = ctx.exec_properties["input_path"]
    splits = ctx.exec_properties["splits"] or dict(DEFAULT_SPLITS)
    if os.path.isdir(path):
        files = sorted(
            os.path.join(path, f) for f in os.listdir(path) if f.endswith(".csv")
        )
        if not files:
            raise ValueError(f"no .csv files under {path!r}")
        table = pa.concat_tables([pacsv.read_csv(f) for f in files])
    else:
        table = pacsv.read_csv(path)
    out = ctx.output("examples")
    counts = _split_and_write(table, out.uri, splits)
    out.properties["split_names"] = sorted(counts)
    out.properties["split_counts"] = counts
    return {"num_examples": table.num_rows, **{f"rows_{k}": v for k, v in counts.items()}}


@component(
    outputs={"examples": "Examples"},
    parameters={
        # Path to a directory of <split>.parquet files OR an .npz file whose
        # arrays are columns (MNIST-style tensors allowed: dims beyond the
        # first are flattened into fixed-length list columns).
        "input_path": Parameter(type=str, required=True),
        "splits": Parameter(type=dict, default=None),
    },
    external_input_parameters=("input_path",),
)
def ImportExampleGen(ctx):
    """Import already-materialized data as an Examples artifact.

    Two accepted layouts:
      - directory with ``<split>.parquet`` files → imported split-per-file
      - a single ``.npz`` → columns hash-split like CsvExampleGen
    """
    path = ctx.exec_properties["input_path"]
    out = ctx.output("examples")
    counts: Dict[str, int] = {}
    if os.path.isdir(path):
        import pyarrow.parquet as pq

        files = sorted(f for f in os.listdir(path) if f.endswith(".parquet"))
        if not files:
            raise ValueError(f"no .parquet files under {path!r}")
        for f in files:
            split = os.path.splitext(f)[0]
            table = pq.read_table(os.path.join(path, f))
            examples_io.write_split(out.uri, split, table)
            counts[split] = table.num_rows
    elif path.endswith(".npz"):
        data = np.load(path)
        arrays = {}
        for name in data.files:
            arr = data[name]
            if arr.ndim > 2:
                arr = arr.reshape(arr.shape[0], -1)
            if arr.ndim == 2:
                arrays[name] = pa.array(list(arr))
            else:
                arrays[name] = pa.array(arr)
        table = pa.table(arrays)
        splits = ctx.exec_properties["splits"] or dict(DEFAULT_SPLITS)
        counts = _split_and_write(table, out.uri, splits)
    else:
        raise ValueError(f"unsupported import source: {path!r}")
    out.properties["split_names"] = sorted(counts)
    out.properties["split_counts"] = counts
    return {"num_examples": sum(counts.values())}
