"""Evaluator: jitted sliced evaluation + blessing gate.

Capability match for TFX Evaluator / TFMA (SURVEY.md §2a row 8): evaluates
the candidate model on the eval split (jit-compiled forward pass), writes a
sliced ModelEvaluation artifact, optionally compares against a baseline
model on the same data, and emits the ModelBlessing gate that Pusher honors.
"""

from __future__ import annotations

import json
import os
from typing import Dict

from tpu_pipelines.data.input_pipeline import BatchIterator, InputConfig
from tpu_pipelines.dsl.component import Parameter, component
from tpu_pipelines.evaluation.metrics import (
    AUC_EXACT_MAX_EXAMPLES,
    EvalOutcome,
    check_thresholds,
    evaluate_model,
)
from tpu_pipelines.trainer.export import (
    load_exported_model,
    model_input_columns,
)

BLESSING_FILE = "BLESSED"
NOT_BLESSED_FILE = "NOT_BLESSED"


def metric_deltas(
    base: Dict[str, float],
    other: Dict[str, float],
    keys=None,
) -> Dict[str, float]:
    """Relative |delta| per shared metric — THE quality-diff surface.

    The Rewriter's per-variant quality gate and any baseline-vs-candidate
    comparison share this one definition: ``|other - base| / max(|base|,
    1e-6)`` for every metric present in both (or just ``keys``), so
    "within quality_tolerance of the float model" means the same thing
    everywhere it is enforced.
    """
    out: Dict[str, float] = {}
    for k in keys if keys is not None else sorted(set(base) & set(other)):
        b, o = base.get(k), other.get(k)
        if b is None or o is None:
            continue
        out[k] = abs(float(o) - float(b)) / max(abs(float(b)), 1e-6)
    return out


def max_metric_delta(deltas: Dict[str, float]) -> float:
    return max(deltas.values()) if deltas else 0.0


def _capped_batches(batches, max_examples: int):
    rows = 0
    for batch in batches:
        yield batch
        rows += len(next(iter(batch.values())))
        if rows >= max_examples:
            return


def evaluate_payload(
    model_uri: str, examples_uri: str, props: Dict
) -> EvalOutcome:
    """Evaluate one exported payload on an eval split — the Evaluator's
    metric surface, reusable (the Rewriter re-runs it per variant).
    ``props["max_eval_examples"]`` (0/absent = all) caps the slice."""
    loaded = load_exported_model(model_uri)
    # Column projection: the model's transformed-feature surface plus the
    # label and slice columns — Parquet never decodes the rest.  None (no
    # transform graph in the payload) = unknown surface, read everything.
    columns = model_input_columns(loaded, raw=False)
    if columns is not None:
        columns = sorted(
            set(columns)
            | {props["label_key"]}
            | set(props["slice_columns"] or ())
        )
    batches = BatchIterator(
        examples_uri,
        props["eval_split"],
        InputConfig(
            batch_size=props["batch_size"], shuffle=False, num_epochs=1,
            drop_remainder=False,
        ),
        columns=columns,
    )
    cap = int(props.get("max_eval_examples") or 0)
    if cap > 0:
        batches = _capped_batches(batches, cap)
    return evaluate_model(
        # Eval data is transformed examples; the payload's transform was
        # already applied at materialization, so use the direct forward pass.
        loaded.predict_transformed,
        batches,
        label_key=props["label_key"],
        problem=props["problem"],
        slice_columns=tuple(props["slice_columns"] or ()),
        auc_buckets=props.get("auc_buckets") or 0,
        auto_bucket_threshold=props.get(
            "auc_exact_max_examples", AUC_EXACT_MAX_EXAMPLES
        ),
    )


# Internal name the Evaluator executor predates; evaluate_payload is the
# public, Rewriter-shared surface.
_evaluate = evaluate_payload


@component(
    inputs={
        "examples": "Examples",
        "model": "Model",
        "baseline_model": "Model",
    },
    optional_inputs=("baseline_model",),
    outputs={"evaluation": "ModelEvaluation", "blessing": "ModelBlessing"},
    parameters={
        "label_key": Parameter(type=str, required=True),
        "problem": Parameter(type=str, default="binary_classification"),
        "eval_split": Parameter(type=str, default="eval"),
        "batch_size": Parameter(type=int, default=512),
        "slice_columns": Parameter(type=list, default=None),
        # Ranking-metric aggregation: 0 (default) = exact AUC/PR-AUC while a
        # slice stays under AUC_EXACT_MAX_EXAMPLES rows, auto-spilling to a
        # 16384-bucket streaming histogram beyond that (flat memory at
        # BulkInferrer scale, deviation < 1e-3); N > 0 = N-bucket histogram
        # from the first row (metrics.py note).
        "auc_buckets": Parameter(type=int, default=0),
        # Auto-spill row threshold for auc_buckets=0; 0 = never spill
        # (reference-exact AUC at any size, memory grows with the slice).
        "auc_exact_max_examples": Parameter(
            type=int, default=AUC_EXACT_MAX_EXAMPLES
        ),
        # {"accuracy": {"lower_bound": 0.7}, "loss": {"upper_bound": 1.0}}
        "value_thresholds": Parameter(type=dict, default=None),
        # {"accuracy": {"min_improvement": 0.0, "higher_is_better": True}}
        "change_thresholds": Parameter(type=dict, default=None),
        # Bootstrap semantics apply ONLY when baseline_model is WIRED (e.g.
        # to a Resolver) but resolved empty — the first run of a
        # continuous-training pipeline has no blessed baseline yet, so
        # change thresholds are skipped (TFX LatestBlessedModelStrategy).
        # An UNWIRED baseline_model with change thresholds configured always
        # fails the gate (fail-closed: a forgotten channel must not bless a
        # regressed model).  require_baseline=True tightens further: even
        # the wired-but-empty bootstrap fails.
        "require_baseline": Parameter(type=bool, default=False),
    },
    resource_class="tpu",
    is_sink=True,
)
def Evaluator(ctx):
    props = ctx.exec_properties
    examples_uri = ctx.input("examples").uri
    outcome = _evaluate(ctx.input("model").uri, examples_uri, props)

    baseline_overall = None
    baseline_uri = ""
    if ctx.inputs.get("baseline_model"):
        baseline_uri = ctx.input("baseline_model").uri
        baseline_outcome = _evaluate(baseline_uri, examples_uri, props)
        baseline_overall = baseline_outcome.overall().metrics

    eval_art = ctx.output("evaluation")
    outcome.save(eval_art.uri)
    overall = outcome.overall()
    eval_art.properties["overall_metrics"] = overall.metrics

    # Wired-but-empty (resolver bootstrap) may skip change thresholds;
    # never-wired must not — see the require_baseline parameter note.
    baseline_wired = "baseline_model" in ctx.inputs
    blessed, reasons = check_thresholds(
        overall.metrics,
        props["value_thresholds"] or {},
        baseline=baseline_overall,
        change_thresholds=props["change_thresholds"] or {},
        require_baseline=(
            bool(props.get("require_baseline")) or not baseline_wired
        ),
    )
    blessing_art = ctx.output("blessing")
    os.makedirs(blessing_art.uri, exist_ok=True)
    marker = BLESSING_FILE if blessed else NOT_BLESSED_FILE
    with open(os.path.join(blessing_art.uri, marker), "w") as f:
        json.dump({"reasons": reasons}, f)
    blessing_art.properties["blessed"] = blessed
    return {
        "blessed": blessed,
        "not_blessed_reasons": reasons,
        "baseline_model_uri": baseline_uri,
        **{f"overall_{k}": v for k, v in overall.metrics.items()},
        "num_slices": len(outcome.slices),
    }


def is_blessed(blessing_uri: str) -> bool:
    return os.path.exists(os.path.join(blessing_uri, BLESSING_FILE))
