"""Evaluator: jitted sliced evaluation + blessing gate.

Capability match for TFX Evaluator / TFMA (SURVEY.md §2a row 8): evaluates
the candidate model on the eval split (jit-compiled forward pass), writes a
sliced ModelEvaluation artifact, optionally compares against a baseline
model on the same data, and emits the ModelBlessing gate that Pusher honors.
"""

from __future__ import annotations

import json
import os
from typing import Dict

from tpu_pipelines.data.input_pipeline import BatchIterator, InputConfig
from tpu_pipelines.dsl.component import Parameter, component
from tpu_pipelines.evaluation.metrics import (
    EvalOutcome,
    check_thresholds,
    evaluate_model,
)
from tpu_pipelines.trainer.export import load_exported_model

BLESSING_FILE = "BLESSED"
NOT_BLESSED_FILE = "NOT_BLESSED"


def _evaluate(model_uri: str, examples_uri: str, props: Dict) -> EvalOutcome:
    loaded = load_exported_model(model_uri)
    batches = BatchIterator(
        examples_uri,
        props["eval_split"],
        InputConfig(
            batch_size=props["batch_size"], shuffle=False, num_epochs=1,
            drop_remainder=False,
        ),
    )
    return evaluate_model(
        # Eval data is transformed examples; the payload's transform was
        # already applied at materialization, so use the direct forward pass.
        loaded.predict_transformed,
        batches,
        label_key=props["label_key"],
        problem=props["problem"],
        slice_columns=tuple(props["slice_columns"] or ()),
    )


@component(
    inputs={
        "examples": "Examples",
        "model": "Model",
        "baseline_model": "Model",
    },
    optional_inputs=("baseline_model",),
    outputs={"evaluation": "ModelEvaluation", "blessing": "ModelBlessing"},
    parameters={
        "label_key": Parameter(type=str, required=True),
        "problem": Parameter(type=str, default="binary_classification"),
        "eval_split": Parameter(type=str, default="eval"),
        "batch_size": Parameter(type=int, default=512),
        "slice_columns": Parameter(type=list, default=None),
        # {"accuracy": {"lower_bound": 0.7}, "loss": {"upper_bound": 1.0}}
        "value_thresholds": Parameter(type=dict, default=None),
        # {"accuracy": {"min_improvement": 0.0, "higher_is_better": True}}
        "change_thresholds": Parameter(type=dict, default=None),
    },
)
def Evaluator(ctx):
    props = ctx.exec_properties
    examples_uri = ctx.input("examples").uri
    outcome = _evaluate(ctx.input("model").uri, examples_uri, props)

    baseline_overall = None
    if ctx.inputs.get("baseline_model"):
        baseline_outcome = _evaluate(
            ctx.input("baseline_model").uri, examples_uri, props
        )
        baseline_overall = baseline_outcome.overall().metrics

    eval_art = ctx.output("evaluation")
    outcome.save(eval_art.uri)
    overall = outcome.overall()
    eval_art.properties["overall_metrics"] = overall.metrics

    blessed, reasons = check_thresholds(
        overall.metrics,
        props["value_thresholds"] or {},
        baseline=baseline_overall,
        change_thresholds=props["change_thresholds"] or {},
    )
    blessing_art = ctx.output("blessing")
    os.makedirs(blessing_art.uri, exist_ok=True)
    marker = BLESSING_FILE if blessed else NOT_BLESSED_FILE
    with open(os.path.join(blessing_art.uri, marker), "w") as f:
        json.dump({"reasons": reasons}, f)
    blessing_art.properties["blessed"] = blessed
    return {
        "blessed": blessed,
        "not_blessed_reasons": reasons,
        **{f"overall_{k}": v for k, v in overall.metrics.items()},
        "num_slices": len(outcome.slices),
    }


def is_blessed(blessing_uri: str) -> bool:
    return os.path.exists(os.path.join(blessing_uri, BLESSING_FILE))
