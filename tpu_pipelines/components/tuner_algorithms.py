"""Adaptive hyperparameter search: successive halving + TPE.

The reference's HPO surface is KerasTuner through TFX Tuner and Katib on
the cluster (SURVEY.md §2a Tuner row, §2b Katib row); both offer more than
grid/random — Hyperband-style early stopping and Bayesian search.  These
are their equivalents, built over the SAME trial machinery as grid/random
(the component supplies ``run_batch``, which already handles subprocess
isolation and parallelism):

  - ``successive_halving``: the inner loop of Hyperband.  Start n0 random
    candidates at a small step budget, keep the best 1/eta at eta x the
    budget, repeat until the full budget — compute goes to survivors, so a
    wide space costs a fraction of running every candidate to completion.

  - ``tpe``: Tree-structured Parzen Estimator over the discrete space.
    After a random startup batch, candidates are sampled per-dimension
    proportionally to l(v)/g(v), where l counts the value among the best
    ``gamma`` fraction of finished trials and g among the rest (Laplace
    smoothed) — the classic TPE density ratio restricted to categorical
    dimensions, which is exactly what a {name: [values]} space is.

Both are single-controller algorithms: promotion/proposal depends on
earlier scores, so they cannot ride the precomputed cluster shard files
(the component rejects trial_shards with an adaptive algorithm).
"""

from __future__ import annotations

import json
import logging
import random
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

# run_batch(candidates, train_steps, first_trial_id) -> [outcome, ...]
# (one outcome dict per candidate, status "ok" with metrics or "failed").
RunBatch = Callable[[List[Dict[str, Any]], int, int], List[Dict[str, Any]]]


def _resolve_objective(
    outcomes: Sequence[Dict[str, Any]], objective: str
) -> str:
    for o in outcomes:
        if o["status"] == "ok":
            m = o["metrics"]
            if objective:
                if objective not in m:
                    raise KeyError(
                        f"objective {objective!r} not in trial metrics "
                        f"{sorted(m)}"
                    )
                return objective
            return "eval_loss" if "eval_loss" in m else "loss"
    return objective  # every outcome failed; caller raises anyway


def _score(outcome: Dict[str, Any], objective: str,
           direction: str) -> Optional[float]:
    """Comparable score (higher = better) or None for failed trials."""
    if outcome["status"] != "ok":
        return None
    v = float(outcome["metrics"][objective])
    return -v if direction == "min" else v


def _annotate(outcomes, objective, direction) -> None:
    for o in outcomes:
        if o["status"] == "ok":
            o["objective"] = objective
            o["score"] = float(o["metrics"][objective])


def successive_halving(
    space: Dict[str, List[Any]],
    *,
    run_batch: RunBatch,
    max_steps: int,
    n0: int,
    eta: int = 3,
    min_steps: int = 0,
    objective: str = "",
    direction: str = "min",
    seed: int = 0,
) -> Tuple[List[Dict[str, Any]], Optional[Dict[str, Any]]]:
    """Returns (all_trials, best_outcome).  Rung r runs the surviving
    ``n0 / eta^r`` candidates at ``max_steps / eta^(rungs-1-r)`` steps."""
    if eta < 2:
        raise ValueError(f"halving eta must be >= 2, got {eta}")
    from tpu_pipelines.components.tuner import _random

    rungs = 1
    while n0 // (eta ** rungs) >= 1 and rungs < 10:
        rungs += 1
    if min_steps <= 0:
        min_steps = max(1, max_steps // (eta ** (rungs - 1)))

    # Budget schedule upfront, capped at max_steps: once a rung reaches the
    # full budget there is nothing further to promote INTO — re-running the
    # survivor at the same budget would buy zero information — so the
    # schedule ends there even if the width plan had more rungs.
    budgets: List[int] = []
    for r in range(rungs):
        steps = min(max_steps, max(min_steps, min_steps * (eta ** r)))
        if r == rungs - 1:
            steps = max_steps
        budgets.append(steps)
        if steps >= max_steps:
            break
    rungs = len(budgets)

    survivors = _random(space, n0, seed)
    trials: List[Dict[str, Any]] = []
    obj = objective
    best: Optional[Dict[str, Any]] = None
    best_score: Optional[float] = None
    trial_id = 0
    for r, steps in enumerate(budgets):
        outcomes = run_batch(survivors, steps, trial_id)
        trial_id += len(outcomes)
        obj = obj or _resolve_objective(outcomes, objective)
        if obj:
            _annotate(outcomes, obj, direction)
        for o in outcomes:
            o["rung"] = r
            o["train_steps"] = steps
        trials.extend(outcomes)

        scored = [
            (s, o) for o in outcomes
            if (s := _score(o, obj, direction)) is not None
        ] if obj else []
        if not scored:
            logger.warning("halving rung %d: every trial failed", r)
            break
        scored.sort(key=lambda so: so[0], reverse=True)
        # Best-at-full-budget wins; lower rungs only steer promotion, but
        # keep a fallback in case the last rung fails entirely.  Explicit
        # None check: a 0.0 score is falsy but perfectly valid.
        top_score, top = scored[0]
        if r == rungs - 1 or best_score is None or top_score > best_score:
            best, best_score = top, top_score
        keep = max(1, len(scored) // eta)
        survivors = [o["hyperparameters"] for _, o in scored[:keep]]
    return trials, best


def tpe(
    space: Dict[str, List[Any]],
    *,
    run_batch: RunBatch,
    train_steps: int,
    max_trials: int,
    batch_size: int = 4,
    startup: int = 0,
    gamma: float = 0.25,
    objective: str = "",
    direction: str = "min",
    seed: int = 0,
) -> Tuple[List[Dict[str, Any]], Optional[Dict[str, Any]]]:
    """Returns (all_trials, best_outcome) after ``max_trials`` evaluations."""
    from tpu_pipelines.components.tuner import _random, candidate_key

    rng = random.Random(seed)
    keys = sorted(space)
    startup = startup or min(max_trials, max(4, batch_size))
    trials: List[Dict[str, Any]] = []
    seen: set = set()
    obj = objective
    trial_id = 0

    def run(cands: List[Dict[str, Any]]) -> None:
        nonlocal obj, trial_id
        outcomes = run_batch(cands, train_steps, trial_id)
        trial_id += len(outcomes)
        obj = obj or _resolve_objective(outcomes, objective)
        if obj:
            _annotate(outcomes, obj, direction)
        trials.extend(outcomes)
        for c in cands:
            seen.add(candidate_key(c))

    def propose(n: int) -> List[Dict[str, Any]]:
        finished = [
            (s, o) for o in trials
            if (s := _score(o, obj, direction)) is not None
        ]
        if not finished:
            return _random(space, n, rng.randrange(1 << 30))
        finished.sort(key=lambda so: so[0], reverse=True)
        n_good = max(1, int(len(finished) * gamma))
        good = [o["hyperparameters"] for _, o in finished[:n_good]]
        bad = [o["hyperparameters"] for _, o in finished[n_good:]]

        def weights(dim: str) -> List[float]:
            values = space[dim]
            lg = [1.0] * len(values)    # Laplace smoothing
            gg = [1.0] * len(values)
            enc = [json.dumps(v, sort_keys=True, default=str) for v in values]
            index = {e: i for i, e in enumerate(enc)}
            for cand in good:
                i = index.get(json.dumps(cand.get(dim), sort_keys=True,
                                         default=str))
                if i is not None:
                    lg[i] += 1.0
            for cand in bad:
                i = index.get(json.dumps(cand.get(dim), sort_keys=True,
                                         default=str))
                if i is not None:
                    gg[i] += 1.0
            ln = sum(lg)
            gn = sum(gg)
            return [(lg[i] / ln) / (gg[i] / gn) for i in range(len(values))]

        dim_weights = {k: weights(k) for k in keys}
        out: List[Dict[str, Any]] = []
        attempts = 0
        while len(out) < n and attempts < 100 * n:
            cand = {
                k: rng.choices(space[k], weights=dim_weights[k])[0]
                for k in keys
            }
            ck = candidate_key(cand)
            if ck not in seen or attempts > 50 * n:
                out.append(cand)
                seen.add(ck)
            attempts += 1
        return out

    run(_random(space, min(startup, max_trials), seed))
    while len(trials) < max_trials:
        n = min(batch_size, max_trials - len(trials))
        cands = propose(n)
        if not cands:
            break
        run(cands)

    best = None
    best_score = None
    for o in trials:
        s = _score(o, obj, direction) if obj else None
        if s is not None and (best_score is None or s > best_score):
            best, best_score = o, s
    return trials, best
