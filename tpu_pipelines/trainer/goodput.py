"""Goodput/badput accounting via ``ml_goodput_measurement`` (SURVEY.md §5).

The reference delegates goodput to the substrate; the TPU stack's canonical
tool is Google's ``ml_goodput_measurement``, whose recorder/calculator pair
normally rides Google Cloud Logging.  Here the logger is duck-typed onto an
in-process entry list (optionally mirrored to a JSONL next to the
checkpoints), so the real badput algebra — TPU init, training prep,
sync/async data loading, program startup, checkpoint save/restore, wasted
progress — runs with zero GCP dependency and works in air-gapped tests.

``GoodputTracker`` is the train-loop-facing wrapper: every record method is
a no-op when the library is unavailable, and ``summary()`` returns {} so the
loop's own host-input-wait proxy remains the fallback.
"""

from __future__ import annotations

import datetime
import json
import logging
import os
import time
from typing import Any, Dict, List, Optional

log = logging.getLogger("tpu_pipelines.trainer")


def _now() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


class LocalEntryLogger:
    """Duck-types ``ml_goodput_measurement``'s ``_CloudLogger`` interface
    (``write_cloud_logging_entry`` / ``read_cloud_logging_entries``) over an
    in-memory list, optionally mirrored to a JSONL file for post-hoc
    inspection (`model_run/goodput_log.jsonl`).

    Mirror failures (a full or read-only disk) never break training, and
    no longer latch the mirror off forever: every failure is counted in
    the metrics registry (``goodput_mirror_failures_total``), writes are
    suppressed for ``mirror_retry_backoff_s``, then retried ONCE — a
    transient ENOSPC recovers, a genuinely dead path disables the mirror
    after its second strike.
    """

    def __init__(
        self,
        job_name: str,
        jsonl_path: str = "",
        mirror_retry_backoff_s: float = 30.0,
    ):
        self.job_name = job_name
        self.job_start_time = None  # attribute the real logger also exposes
        self._entries: List[Dict[str, Any]] = []
        self._jsonl_path = jsonl_path
        self._mirror_retry_backoff_s = mirror_retry_backoff_s
        self._mirror_retry_at: Optional[float] = None  # monotonic
        self._mirror_dead = False
        from tpu_pipelines.observability.metrics import default_registry

        self._m_mirror_failures = default_registry().counter(
            "goodput_mirror_failures_total",
            "Goodput JSONL mirror write failures (OSError).",
        )

    def write_cloud_logging_entry(self, entry) -> None:
        if entry is None or entry.get("job_name") != self.job_name:
            return
        self._entries.append(entry)
        if not self._jsonl_path or self._mirror_dead:
            return
        if (
            self._mirror_retry_at is not None
            and time.monotonic() < self._mirror_retry_at
        ):
            return  # backing off; the entry stays in-memory only
        try:
            parent = os.path.dirname(self._jsonl_path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            with open(self._jsonl_path, "a") as f:
                f.write(json.dumps(entry, default=str) + "\n")
        except OSError as e:
            self._m_mirror_failures.inc()
            if self._mirror_retry_at is None:
                # First strike this episode: back off, then retry once.
                self._mirror_retry_at = (
                    time.monotonic() + self._mirror_retry_backoff_s
                )
                log.warning(
                    "goodput jsonl mirror failed (%s); retrying once "
                    "after %gs", e, self._mirror_retry_backoff_s,
                )
            else:
                # The post-backoff retry also failed: the path is dead.
                self._mirror_dead = True
                log.warning(
                    "goodput jsonl mirror disabled after retry: %s", e
                )
        else:
            # A success closes the failure episode: a future failure gets
            # its own backoff + single retry.
            self._mirror_retry_at = None

    def read_cloud_logging_entries(self):
        # The calculator iterates this return value directly as the list of
        # payload dicts (no pagination tuple) — returning anything else makes
        # ``_get_total_job_time`` iterate the wrapper and blow up on None.
        return list(self._entries)


class GoodputTracker:
    """Recorder facade for the train loop; disabled ⇒ every call no-ops."""

    def __init__(self, job_name: str = "train", jsonl_path: str = ""):
        self.job_name = job_name
        self._recorder = None
        self._goodput_mod = None
        try:
            from ml_goodput_measurement.src import goodput as goodput_mod

            self._logger = LocalEntryLogger(job_name, jsonl_path)
            # Keyword is ``logger=`` (ml_goodput_measurement >= 0.0.2);
            # the old ``cloud_logger=`` raised TypeError here, which the
            # best-effort except silently downgraded EVERY run to the
            # proxy path — the regression test drives this constructor
            # for real.
            self._recorder = goodput_mod.GoodputRecorder(
                job_name, "local", logging_enabled=True,
                logger=self._logger,
            )
            self._goodput_mod = goodput_mod
        except Exception as e:  # noqa: BLE001 — accounting is best-effort
            log.info("ml_goodput_measurement unavailable (%s); using proxy", e)

    @property
    def enabled(self) -> bool:
        return self._recorder is not None

    # ---- recording (thin pass-throughs; timestamps default to now-UTC)

    def job_start(self):
        if self._recorder:
            self._recorder.record_job_start_time(_now())

    def job_end(self):
        if self._recorder:
            self._recorder.record_job_end_time(_now())

    def tpu_init_start(self):
        if self._recorder:
            self._recorder.record_tpu_init_start_time(_now())

    def tpu_init_end(self):
        if self._recorder:
            self._recorder.record_tpu_init_end_time(_now())

    def training_prep_start(self):
        if self._recorder:
            self._recorder.record_training_preparation_start_time(_now())

    def training_prep_end(self):
        if self._recorder:
            self._recorder.record_training_preparation_end_time(_now())

    def data_loading_start(self):
        if self._recorder:
            self._recorder.record_data_loading_start_time(_now())

    def data_loading_end(self):
        if self._recorder:
            self._recorder.record_data_loading_end_time(_now())

    def step_start(self, step: int):
        if self._recorder:
            self._recorder.record_step_start_time(step, _now())

    # ---- summary

    def summary(self) -> Dict[str, Any]:
        """{"goodput": fraction, "badput": {kind: fraction}, "last_step": n}
        or {} when disabled / nothing recorded / calculator error."""
        if not self._recorder:
            return {}
        try:
            calc = self._goodput_mod.GoodputCalculator(
                self.job_name, "local", logger=self._logger
            )
            goodput_pct, badput, last_step = calc.get_job_goodput(
                include_badput_breakdown=True
            )
        except Exception as e:  # noqa: BLE001
            log.warning("goodput calculation failed: %s", e)
            return {}
        breakdown: Dict[str, float] = {}
        for kind, pct in badput.items():
            name = getattr(kind, "name", str(kind)).lower()
            if isinstance(pct, dict):  # CUSTOM_BADPUT_EVENTS sub-breakdown
                pct = sum(pct.values())
            if pct:
                breakdown[name] = round(float(pct) / 100.0, 4)
        return {
            "goodput": round(float(goodput_pct) / 100.0, 4),
            "badput": breakdown,
            "last_step": int(last_step),
        }
