"""Generic jitted training loop: the framework-owned hot path.

SURVEY.md §3.3 maps the reference's per-step path (tf.function graph →
CollectiveAllReduce over NCCL) to: one ``jax.jit``-compiled train step with
params replicated and the batch sharded over the mesh ``data`` axis; XLA
emits the gradient all-reduce over ICI.  The host loop only feeds batches
(``device_put`` at the infeed boundary) and drains metrics every
``log_every`` steps — no per-step host sync.

Also here: the measurement harness (examples/sec/chip — the BASELINE metric),
orbax checkpoint/resume (the BackupAndRestore equivalent), and optional
per-parameter sharding rules for model parallelism.
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_pipelines.parallel.mesh import (
    MeshConfig,
    data_parallel_sharding,
    make_mesh,
    replicate,
)
from tpu_pipelines.parallel.partition import (
    foreign_axis_paths,
    fsdp_param_partition,
    validate_partition,
)
from tpu_pipelines.trainer.fn_args import TrainResult
from tpu_pipelines.trainer.goodput import GoodputTracker

log = logging.getLogger("tpu_pipelines.trainer")


# ---- XLA compile-event tracking (the training twin of the serving
# fleet's aot-compiles-after-warm audit).  jax.monitoring fires
# '/jax/core/compile/backend_compile_duration' for every backend
# compile; listeners cannot be unregistered, so ONE process-wide
# listener is installed lazily and dispatches to the hook of whichever
# train loop is currently running — the indirection is what scopes
# attribution to the live loop and makes repeated train_loop calls in
# one process (tests, tuner trials) not leak listeners.
_COMPILE_EVENT_SUFFIX = "backend_compile_duration"
_COMPILE_HOOK: Optional[Callable[[float], None]] = None
_COMPILE_LISTENER_INSTALLED = False


def _on_xla_compile_event(event: str, duration_s: float, **_kw: Any) -> None:
    hook = _COMPILE_HOOK
    if hook is not None and event.endswith(_COMPILE_EVENT_SUFFIX):
        hook(float(duration_s))


# Marked administrative regions: compiles inside one (same thread) are
# real XLA work but never a step stall — the hook books them under the
# "admin" label instead of the after-warm counter.  threading.local so a
# region opened on the loop thread cannot mask a concurrent thread.
_COMPILE_ADMIN = threading.local()


def _compile_admin_depth() -> int:
    return getattr(_COMPILE_ADMIN, "depth", 0)


@contextlib.contextmanager
def _compile_admin_region():
    _COMPILE_ADMIN.depth = _compile_admin_depth() + 1
    try:
        yield
    finally:
        _COMPILE_ADMIN.depth -= 1


def _set_compile_hook(hook: Optional[Callable[[float], None]]) -> None:
    global _COMPILE_HOOK, _COMPILE_LISTENER_INSTALLED
    _COMPILE_HOOK = hook
    if hook is not None and not _COMPILE_LISTENER_INSTALLED:
        try:
            from jax import monitoring

            monitoring.register_event_duration_secs_listener(
                _on_xla_compile_event
            )
            _COMPILE_LISTENER_INSTALLED = True
        except Exception as e:  # noqa: BLE001 — telemetry must not fail a run
            log.debug("compile-event listener unavailable: %s", e)


# Peak per-chip bf16 FLOPs for the live train_mfu gauge.  Precedence:
# TrainLoopConfig.peak_flops_per_chip > TPP_PEAK_FLOPS env > device-kind
# table (same table bench.py matches) > 0.0 (MFU not computed — an
# assumed denominator would publish a made-up utilization).
ENV_PEAK_FLOPS = "TPP_PEAK_FLOPS"
_PEAK_BF16_FLOPS = [
    ("v5 lite", 197e12), ("v5e", 197e12),
    ("v5p", 459e12),
    ("v6 lite", 918e12), ("v6e", 918e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
]


def _peak_flops_per_chip(config: "TrainLoopConfig") -> float:
    if config.peak_flops_per_chip:
        return float(config.peak_flops_per_chip)
    env = os.environ.get(ENV_PEAK_FLOPS, "").strip()
    if env:
        try:
            return float(env)
        except ValueError:
            log.warning("ignoring non-numeric %s=%r", ENV_PEAK_FLOPS, env)
    try:
        kind = jax.local_devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001
        return 0.0
    for key, peak in _PEAK_BF16_FLOPS:
        if key in kind:
            return peak
    return 0.0


def _tree_bytes(tree: Any) -> int:
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        try:
            total += int(np.prod(shape)) * int(np.dtype(dtype).itemsize)
        except (TypeError, ValueError):
            pass
    return total


def _collective_fraction(params: Any, first_batch: Any, mesh: Mesh,
                         dp_mode: str) -> float:
    """Estimated share of a window's device span spent in the gradient
    exchange, splitting the measured device phase into device_compute /
    device_collective.  Bandwidth proxy over the same byte counts the
    PR 18 memory_analysis checks reason about: per step the exchange
    moves ~factor x (N-1)/N x param_bytes over the interconnect
    (factor 2 for an all-reduce — reduce-scatter + all-gather — and 3
    for fsdp's JIT gathers + reduce-scatter), against an HBM-traffic
    proxy of 3 x param_bytes (read params + read grads + write params)
    plus the per-device batch.  An estimate, not a measurement — but the
    published phases still sum exactly to wall-clock because only the
    measured device span is being split."""
    try:
        n = int(mesh.shape["data"])
    except (KeyError, TypeError):
        n = 1
    if n <= 1:
        return 0.0
    param_bytes = _tree_bytes(params)
    if param_bytes <= 0:
        return 0.0
    batch_bytes = _tree_bytes(first_batch) / n
    factor = 3.0 if dp_mode == "fsdp" else 2.0
    coll = factor * (n - 1) / n * param_bytes
    hbm = 3.0 * param_bytes + batch_bytes
    return coll / max(coll + hbm, 1.0)


class TrainState(struct.PyTreeNode):
    """Step counter + params + optimizer state + rng, all on device.

    ``model_state`` carries non-trained mutable collections (BatchNorm
    running statistics — flax's ``batch_stats``); None for stateless models.
    """

    step: jax.Array
    params: Any
    opt_state: Any
    rng: jax.Array
    model_state: Any = None

    @classmethod
    def create(cls, params, optimizer, rng, model_state=None) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=optimizer.init(params),
            rng=rng,
            model_state=model_state,
        )


@dataclasses.dataclass
class TrainLoopConfig:
    train_steps: int
    batch_size: int = 128
    eval_every: int = 0            # 0 = eval only at the end
    eval_steps: int = 0            # 0 = full eval split pass per eval
    checkpoint_every: int = 0      # 0 = no mid-training checkpoints
    keep_checkpoints: int = 3
    log_every: int = 100
    # Device-resident multi-step window: dispatch this many optimizer steps
    # as ONE compiled ``lax.scan`` over a device-staged batch stack (leading
    # axis = step-in-window), with a single device->host metric fetch per
    # window — the per-step host round-trip (device_put + dispatch + drain)
    # is the ~100x gap between the real train_loop path and the
    # device-resident fori_loop ceiling on µs-scale steps (BENCH_R5).
    # None = read env TPP_WINDOW_STEPS, else default to ``log_every``
    # (window cadence == metric cadence); <=1 = the per-step loop,
    # bit-for-bit in metric semantics.  Windows shrink to land exactly on
    # eval/checkpoint/train_steps boundaries; per-step metric values are
    # reconstructed host-side from the windowed accumulator, so log_every
    # emission and the NaN/stall/loss-spike watchdogs keep their per-step
    # semantics, sampled at window boundaries.  Forced to 1 while
    # profile_dir is set (profiling needs per-step dispatch granularity).
    window_steps: Optional[int] = None
    seed: int = 0
    mesh_config: Optional[MeshConfig] = None
    # Optional pytree-of-PartitionSpec matching params, for model parallelism;
    # None = fully replicated params (pure DP, the reference's strategy).
    param_partition: Optional[Any] = None
    # Optional {batch_key: PartitionSpec} for input sharding beyond plain
    # batch-dim DP — e.g. P("data", "seq") on token ids for ring-attention
    # sequence parallelism.  Keys not listed shard dim 0 over "data".
    batch_partition: Optional[Dict[str, Any]] = None
    donate_state: bool = True
    # Gradient accumulation: the per-step batch splits into this many
    # microbatches, scanned inside ONE jitted step (grads averaged, one
    # optimizer update) — the large-effective-batch story when the full
    # batch's activations exceed HBM.  Microbatches interleave rows
    # (every a-th row) so each stays evenly sharded over the mesh ``data``
    # axis.  batch_size must divide evenly.
    grad_accum_steps: int = 1
    # ---- explicit data-parallel collective modes (multi-chip window) ----
    # How the scan body's gradient all-reduce is expressed on a >1 'data'
    # axis.  None/"auto" (default): implicit GSPMD — XLA inserts one fused
    # all-reduce wherever it likes, which on µs-scale steps lands exactly
    # at the window boundary and serializes against the next step.
    # "psum_bucketed": grads are computed per device under shard_map and
    # all-reduced as ``collective_buckets`` chunked psums INSIDE the scan
    # body, so the scheduler can overlap bucket k's collective with the
    # remaining backward compute (verified from compiled HLO in
    # tests/test_multichip_window.py).  "ordered": grads are computed per
    # fixed global block (``dp_grad_blocks`` blocks, a count chosen
    # independently of the mesh), all-gathered, and summed in block order —
    # the param trajectory is bitwise-invariant to the data-axis size, so
    # an elastic resume onto a survivor mesh continues the exact same
    # trajectory; costs all-gather bandwidth (block grads move whole).
    # "fsdp": ZeRO-3 — params (and Adam moments) live SHARDED over the
    # data axis per ``param_partition`` (or a derived default: first dim
    # divisible by the axis), each leaf is all-gathered just-in-time
    # inside the scan body (a distinct collective per leaf, overlappable
    # like the bucketed psums; the backward re-gathers under a remat
    # policy instead of saving full params), and the gradient exchange is
    # the reduce-scatter AD transpose of those gathers — per-device
    # resident bytes ≈ params/N + one layer's gather.  Capability table:
    # param_partition requires "fsdp" (data-axis specs) or None/"auto"
    # (arbitrary GSPMD axes); batch_partition (ring-attention sequence
    # sharding) requires None/"auto"; grad_accum_steps and model_state
    # compose with every mode.
    dp_collective: Optional[str] = None
    # Chunked-psum bucket count for "psum_bucketed" (>=1; grad leaves are
    # round-robined into buckets, one psum each).
    collective_buckets: int = 2
    # Fixed global gradient-block count for "ordered".  None = the mesh
    # data-axis size (cheapest).  Pin it to the LARGEST mesh you intend to
    # resume across — trajectories are bitwise-comparable only between
    # runs sharing the same block count.
    dp_grad_blocks: Optional[int] = None
    # Sync-anchored throughput windows: every ``anchor_every`` post-compile
    # steps, force a device-to-host read of that step's loss (the same
    # cannot-lie transfer used for t_start below) and time the span since the
    # previous anchor.  The median windowed examples/sec over these spans is
    # the defensible throughput figure on platforms where async dispatch (or
    # a tunneled backend) lets host clocks run ahead of device progress.
    # 0 = whole-run timing only.
    anchor_every: int = 0
    # PRNG implementation for the training rng (dropout masks etc.).
    # "rbg" is the TPU-fast generator — measured ~1.5x step throughput on
    # BERT-base fine-tune vs the default threefry, whose counter math
    # dominates dropout cost on the MXU-light path.  Set "threefry2x32" for
    # jax-default stream reproducibility, or None for the jax default.
    prng_impl: Optional[str] = "rbg"
    # Device profiling (the TensorBoard-profile equivalent, SURVEY.md §5):
    # capture a jax.profiler trace for steps [profile_from, profile_to).
    profile_dir: str = ""
    profile_from: int = 2
    profile_to: int = 5
    # TensorBoard scalar sink (SURVEY.md §5 observability, the Keras
    # TensorBoard-callback equivalent): when set, train metrics at log_every
    # cadence + eval metrics land there as tf.summary scalars via clu.
    tensorboard_dir: str = ""
    # Record XLA's own FLOP count for the compiled train step
    # (TrainResult.cost_analysis_flops_per_step) — the falsifiability
    # cross-check for analytic MFU numerators (VERDICT r4 weak#3).  Runs
    # AFTER the timed loop (an extra trace, and possibly an extra backend
    # compile) so throughput is unaffected; costs wall-clock, so off by
    # default and enabled by the bench's flagship leg.
    collect_cost_analysis: bool = False
    # Live telemetry (observability/metrics.py + health.py): the loop
    # always publishes step-time / examples-per-sec / input-wait / device
    # -memory gauges into the process metrics registry (in-memory — zero
    # file/socket footprint) and heartbeats a HealthMonitor whose NaN and
    # loss-spike checks ride the log_every host transfer.  The stall
    # watchdog THREAD starts only when a timeout is configured:
    # None = read env TPP_STALL_TIMEOUT_S, 0 = no watchdog thread.
    stall_timeout_s: Optional[float] = None
    # Called as cb(kind, detail) when a watchdog fires ("stall", "nan",
    # "loss_spike") — wire pagers, or sys.exit for fail-fast jobs.
    health_alert_cb: Optional[Callable[[str, str], None]] = None
    # ---- telemetry plane (observability/federation + metrics_history) --
    # Pipeline root the durable metrics-history ring lives under
    # (<pipeline_root>/.runs/_metrics/<run_id>/).  "" = derive both from
    # the active RunTrace recorder when one is installed.  Snapshots are
    # written only when TPP_METRICS_HISTORY is set — zero files
    # otherwise.  Federation publishing needs no config: it keys off
    # TPP_FEDERATION_DIR alone.
    pipeline_root: str = ""
    run_id: str = ""
    # Peak per-chip FLOPs for the live train_mfu gauge; None = env
    # TPP_PEAK_FLOPS, else the device-kind table, else no MFU (a made-up
    # denominator would publish a made-up utilization).
    peak_flops_per_chip: Optional[float] = None


LossFn = Callable[[Any, Dict[str, jax.Array], jax.Array], Tuple[jax.Array, Dict[str, jax.Array]]]


def _param_sharding(mesh: Mesh, config: TrainLoopConfig, params):
    if config.param_partition is None:
        return jax.tree_util.tree_map(lambda _: replicate(mesh), params)
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), config.param_partition,
        is_leaf=lambda x: isinstance(x, P),
    )


def _key_name(entry) -> str:
    for attr in ("key", "idx", "name"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def _opt_state_sharding(opt_state, params, p_shard, mesh: Mesh):
    """Shard optimizer state like its matching params, replicate the rest.

    Optax states (e.g. Adam's mu/nu) embed copies of the params pytree, so an
    opt_state leaf whose tree-path *suffix* and shape match a param leaf gets
    that param's sharding — Adam moments stay sharded alongside
    model-parallel params instead of being replicated onto every chip.
    """
    param_entries = [
        (tuple(_key_name(k) for k in path), leaf.shape, shard)
        for (path, leaf), (_, shard) in zip(
            jax.tree_util.tree_flatten_with_path(params)[0],
            jax.tree_util.tree_flatten_with_path(p_shard)[0],
        )
    ]

    def match(path, leaf):
        tail = tuple(_key_name(k) for k in path)
        for ptail, pshape, pshard in param_entries:
            if (
                len(tail) >= len(ptail)
                and tail[-len(ptail):] == ptail
                and getattr(leaf, "shape", None) == pshape
            ):
                return pshard
        return replicate(mesh)

    flat, treedef = jax.tree_util.tree_flatten_with_path(opt_state)
    return jax.tree_util.tree_unflatten(
        treedef, [match(path, leaf) for path, leaf in flat]
    )


ENV_DP_COLLECTIVE = "TPP_DP_COLLECTIVE"
_DP_MODES = ("auto", "psum_bucketed", "ordered", "fsdp")


def _effective_dp_collective(config: TrainLoopConfig) -> str:
    """Resolve the explicit-collective mode: config > TPP_DP_COLLECTIVE
    env > '' (implicit GSPMD).  'auto' normalizes to ''."""
    mode = config.dp_collective
    if mode is None:
        mode = os.environ.get(ENV_DP_COLLECTIVE, "").strip() or None
    if mode in (None, "", "auto"):
        return ""
    if mode not in _DP_MODES:
        raise ValueError(
            f"dp_collective {mode!r}: expected one of {_DP_MODES}"
        )
    return mode


_FSDP_GATHER_NAME = "fsdp_allgather"


def _make_dp_forward_backward(
    loss_fn: LossFn,
    mesh: Mesh,
    mode: str,
    *,
    buckets: int,
    grad_blocks: int,
    accum: int = 1,
    has_model_state: bool = False,
    fsdp_specs: Optional[Any] = None,
):
    """Mesh-explicit DP forward/backward: (params, model_state, batch, rng)
    -> (loss, metrics, grads, new_model_state), loss/metrics replicated.

    The gradient exchange is expressed INSIDE the function (and therefore
    inside the windowed scan body) instead of being left to GSPMD:

      * ``psum_bucketed`` — per-device grads, leaves round-robined into
        ``buckets`` chunks, one ``psum`` per chunk.  Distinct all-reduce
        ops in the compiled HLO let the scheduler start bucket k's
        collective while the rest of the backward still computes, instead
        of one fused all-reduce serialized at the window boundary.
      * ``ordered`` — grads per fixed global block (``grad_blocks`` blocks
        of the global batch, a count independent of the mesh), block grads
        all-gathered to every device and summed in block order by one
        ``jnp.sum`` over the stacked [G, ...] axis.  Because every mesh
        size computes the same per-block grads and reduces them with the
        same op, the result is bitwise-invariant to the data-axis size —
        the contract elastic resume onto a survivor mesh relies on.
      * ``fsdp`` — ZeRO-3: params arrive SHARDED per ``fsdp_specs`` (data
        axis only).  Each leaf is all-gathered just-in-time (tiled, one
        distinct op per leaf — the overlappable analogue of the psum
        buckets) under a ``jax.checkpoint`` policy that refuses to save
        the gathered values, so the backward re-gathers instead of
        holding full params as residuals; differentiating w.r.t. the
        SHARDS makes the AD transpose of each tiled all-gather a
        ``psum_scatter`` — the reduce-scatter gradient exchange falls out
        of autodiff, and grads leave sharded exactly like the params the
        optimizer then updates shard-wise.

    ``accum > 1`` composes with every mode as an inner ``lax.scan`` over
    interleaved micro-batches of the LOCAL batch.  For ``psum_bucketed``
    the scan accumulates per-device grads and the bucketed psums run once
    per OUTER step (exchange volume independent of accum).  For
    ``ordered`` the block-ordered exchange IS the summation-order
    contract, so it runs per micro-batch and the replicated micro results
    accumulate in fixed scan order — mesh-size bitwise invariance holds
    through accumulation.  For ``fsdp`` the reduce-scatter is the AD
    transpose inside each micro step (deferring it would need a
    full-size local accumulator, defeating the sharded memory model);
    the accumulator itself stays sharded at params/N bytes.

    ``model_state`` (BatchNorm-style collections) threads micro-batch to
    micro-batch; float leaves of the step's final state are psum-averaged
    over the data axis (the sync-BN convention) for ``psum_bucketed`` /
    ``fsdp``, while ``ordered`` averages the per-block states in block
    order, preserving its mesh-size-invariance contract.

    Loss/metrics follow the same reduction as the grads, so the reported
    series inherits the mode's determinism contract.
    """
    from jax.ad_checkpoint import checkpoint_name

    from tpu_pipelines.parallel.compat import shard_map
    from tpu_pipelines.parallel.partition import gather_leaf

    data_axis = mesh.shape["data"]

    def call_loss(params, ms, mb, rng):
        """Either loss contract -> (loss, (metrics, new_model_state))."""
        if has_model_state:
            return loss_fn(params, ms, mb, rng)
        loss, metrics = loss_fn(params, mb, rng)
        return loss, (metrics, ms)

    def plain_micro(params, ms, mb, rng):
        (loss, (metrics, new_ms)), grads = jax.value_and_grad(
            lambda p: call_loss(p, ms, mb, rng), has_aux=True
        )(params)
        return loss, metrics, grads, new_ms

    def fsdp_micro(p_shards, ms, mb, rng):
        def from_shards(shards):
            full = jax.tree_util.tree_map(
                lambda x, s: checkpoint_name(
                    gather_leaf(x, s), _FSDP_GATHER_NAME
                ),
                shards, fsdp_specs,
            )
            return call_loss(full, ms, mb, rng)

        f = jax.checkpoint(
            from_shards,
            policy=jax.checkpoint_policies.save_anything_except_these_names(
                _FSDP_GATHER_NAME
            ),
        )
        (loss, (metrics, new_ms)), g_shards = jax.value_and_grad(
            f, has_aux=True
        )(p_shards)
        # g_shards left psum_scatter as the SUM over devices of the local
        # grads' shard slice; the caller scales to the global mean.
        return loss, metrics, g_shards, new_ms

    def ordered_micro(params, ms, mb, rng):
        blocks = grad_blocks // data_axis

        def block_fb(bmb):
            (loss, (metrics, new_ms)), grads = jax.value_and_grad(
                lambda p: call_loss(p, ms, bmb, rng), has_aux=True
            )(params)
            return loss, metrics, grads, new_ms

        bmb = jax.tree_util.tree_map(
            lambda x: x.reshape(
                blocks, x.shape[0] // blocks, *x.shape[1:]
            ),
            mb,
        )
        l_b, m_b, g_b, s_b = jax.vmap(block_fb)(bmb)
        gather = lambda t: jax.lax.all_gather(t, "data", tiled=True)
        inv = 1.0 / grad_blocks
        ordered_sum = lambda v: jnp.sum(gather(v), axis=0) * inv
        # Float collections average in block order (the mode's contract);
        # integer leaves (counters) advance identically in every block and
        # must keep their dtype — take block 0's value.
        new_ms = (
            jax.tree_util.tree_map(
                lambda v: (
                    ordered_sum(v)
                    if jnp.issubdtype(v.dtype, jnp.inexact) else v[0]
                ),
                s_b,
            )
            if has_model_state else ms
        )
        return (
            ordered_sum(l_b),
            jax.tree_util.tree_map(ordered_sum, m_b),
            jax.tree_util.tree_map(ordered_sum, g_b),
            new_ms,
        )

    micro_fb = {
        "psum_bucketed": plain_micro,
        "ordered": ordered_micro,
        "fsdp": fsdp_micro,
    }[mode]

    def fb(params, mstate, batch, rng):
        # Loss/metrics shapes for the accumulator carry, traced OUTSIDE the
        # shard_map (mean reductions make them batch-size independent).
        out_sd = (
            jax.eval_shape(call_loss, params, mstate, batch, rng)
            if accum > 1 else None
        )

        def local(params, ms, lb, rng):
            if accum == 1:
                loss, metrics, grads, new_ms = micro_fb(params, ms, lb, rng)
            else:
                # Micro-batch i takes every accum-th LOCAL row (interleaved
                # split, same as the implicit path) so each micro stays
                # evenly spread over the data axis.
                def split(x):
                    return jnp.moveaxis(
                        x.reshape(
                            x.shape[0] // accum, accum, *x.shape[1:]
                        ), 1, 0,
                    )

                micro = jax.tree_util.tree_map(split, lb)
                loss_sd, (metrics_sd, _) = out_sd
                zeros = lambda sd: jax.tree_util.tree_map(
                    lambda s: jnp.zeros(s.shape, s.dtype), sd
                )

                def mb_step(carry, idx_mb):
                    g_acc, l_acc, m_acc, ms_c = carry
                    i, mb = idx_mb
                    l, m, g, ms_c = micro_fb(
                        params, ms_c, mb, jax.random.fold_in(rng, i)
                    )
                    g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
                    m_acc = jax.tree_util.tree_map(jnp.add, m_acc, m)
                    return (g_acc, l_acc + l, m_acc, ms_c), None

                # Grad accumulator: zeros shaped like the LOCAL param view —
                # full params for psum/ordered, the shard for fsdp, so the
                # donated carry never exceeds the mode's resident budget.
                (g_sum, l_sum, m_sum, new_ms), _ = jax.lax.scan(
                    mb_step,
                    (
                        jax.tree_util.tree_map(jnp.zeros_like, params),
                        zeros(loss_sd), zeros(metrics_sd), ms,
                    ),
                    (jnp.arange(accum), micro),
                )
                inv_a = 1.0 / accum
                grads = jax.tree_util.tree_map(lambda v: v * inv_a, g_sum)
                loss = l_sum * inv_a
                metrics = jax.tree_util.tree_map(
                    lambda v: v * inv_a, m_sum
                )

            # The per-outer-step exchange.  "ordered" already exchanged
            # inside each micro step (the block order IS the contract) and
            # returned replicated means; "fsdp" grads left the AD transpose
            # as reduce-scattered sums — only scaling remains.
            inv = 1.0 / data_axis
            if mode == "psum_bucketed":
                leaves, treedef = jax.tree_util.tree_flatten(grads)
                k = max(1, min(buckets, len(leaves)))
                reduced: list = [None] * len(leaves)
                for i in range(k):
                    chunk = tuple(leaves[i::k])
                    out = jax.lax.psum(chunk, "data")
                    for j, v in enumerate(out):
                        reduced[i + j * k] = v
                grads = jax.tree_util.tree_unflatten(
                    treedef, [v * inv for v in reduced]
                )
                loss = jax.lax.psum(loss, "data") * inv
                metrics = jax.tree_util.tree_map(
                    lambda v: jax.lax.psum(v, "data") * inv, metrics
                )
            elif mode == "fsdp":
                grads = jax.tree_util.tree_map(lambda v: v * inv, grads)
                loss = jax.lax.psum(loss, "data") * inv
                metrics = jax.tree_util.tree_map(
                    lambda v: jax.lax.psum(v, "data") * inv, metrics
                )
            if has_model_state and mode != "ordered":
                # Sync-BN convention: float collections average over the
                # data axis (replicated out); integer leaves (counters)
                # advance identically on every device and pass through.
                new_ms = jax.tree_util.tree_map(
                    lambda v: (
                        jax.lax.psum(v, "data") * inv
                        if jnp.issubdtype(v.dtype, jnp.inexact) else v
                    ),
                    new_ms,
                )
            return loss, metrics, grads, new_ms

        pspec = fsdp_specs if mode == "fsdp" else P()
        return shard_map(
            local,
            mesh=mesh,
            in_specs=(pspec, P(), P("data"), P()),
            out_specs=(P(), P(), pspec, P()),
            check_vma=False,
        )(params, mstate, batch, rng)

    return fb


def train_loop(
    *,
    loss_fn: LossFn,
    init_params_fn: Callable[[jax.Array, Dict[str, np.ndarray]], Any],
    optimizer: optax.GradientTransformation,
    train_iter: Iterable[Dict[str, np.ndarray]],
    config: TrainLoopConfig,
    eval_iter_fn: Optional[Callable[[], Iterable[Dict[str, np.ndarray]]]] = None,
    checkpoint_dir: str = "",
    mesh: Optional[Mesh] = None,
    metrics_cb: Optional[Callable[[int, Dict[str, float]], None]] = None,
    has_model_state: bool = False,
) -> Tuple[Any, TrainResult]:
    """Run the jitted train loop; returns (final_params, TrainResult).

    Enables the persistent XLA compile cache (utils/compile_cache.py)
    before compiling, so a re-run of an unchanged program — another
    trial, a retry, a resumed job — skips the multi-10-second compile.

    ``loss_fn(params, batch, rng) -> (loss, metrics)`` must be jax-traceable.
    ``init_params_fn(rng, sample_batch)`` builds the params pytree.
    ``train_iter`` yields host batches (dict of numpy, fixed shapes).

    ``has_model_state=True`` switches both contracts to thread mutable
    non-trained collections (flax ``batch_stats`` for BatchNorm models):
      - ``init_params_fn(rng, batch) -> (params, model_state)``
      - ``loss_fn(params, model_state, batch, rng)
           -> (loss, (metrics, new_model_state))``
    and the returned "final params" is ``(params, model_state)``.
    """
    from tpu_pipelines.utils.compile_cache import maybe_enable_compile_cache

    maybe_enable_compile_cache()
    # Badput accounting (SURVEY.md §5): the real ml_goodput_measurement
    # algebra over a local logger; falls back to the host-input-wait proxy
    # when the library is absent (tracker no-ops, summary() == {}).
    tracker = GoodputTracker(
        job_name="train_loop",
        jsonl_path=(
            os.path.join(checkpoint_dir, "goodput_log.jsonl")
            if checkpoint_dir else ""
        ),
    )
    tracker.job_start()
    tracker.tpu_init_start()
    if mesh is None:
        mesh = make_mesh(config.mesh_config)
    n_devices = mesh.devices.size
    tracker.tpu_init_end()

    train_it = iter(train_iter)
    tracker.data_loading_start()
    first_batch = next(train_it)
    tracker.data_loading_end()

    tracker.training_prep_start()
    rng = (
        jax.random.key(config.seed, impl=config.prng_impl)
        if config.prng_impl else jax.random.key(config.seed)
    )
    rng, init_rng = jax.random.split(rng)
    model_state = None
    if has_model_state:
        params, model_state = init_params_fn(init_rng, first_batch)
    else:
        params = init_params_fn(init_rng, first_batch)
    bp = config.batch_partition or {}
    accum = max(1, int(config.grad_accum_steps))
    if accum > 1 and config.batch_size % accum:
        raise ValueError(
            f"batch_size {config.batch_size} not divisible by "
            f"grad_accum_steps {accum}"
        )

    # Explicit DP collective modes (multi-chip window): replace the
    # implicit GSPMD gradient exchange with a shard_map-expressed one.
    # Capability table — each refusal below routes to the mode that
    # supports the ask instead of just blocking:
    #   psum_bucketed / ordered  params replicated (pure DP exchange);
    #   fsdp                     params sharded over 'data' (per-leaf JIT
    #                            all-gather + reduce-scatter grads);
    #   None/'auto' (implicit)   arbitrary param_partition axes and
    #                            batch_partition (ring-attention sequence
    #                            sharding) live here.
    # grad_accum_steps and model_state compose with EVERY mode.
    dp_mode = _effective_dp_collective(config)
    data_axis = mesh.shape["data"]
    fsdp_partition = None
    if dp_mode:
        if bp:
            raise ValueError(
                f"dp_collective={dp_mode!r}: batch_partition (sequence-"
                "sharded inputs for ring attention) rides the implicit-"
                "GSPMD window — use dp_collective=None/'auto' for "
                "long-context configs; explicit collective modes shard "
                "the batch over 'data' only"
            )
        if dp_mode == "fsdp":
            fsdp_partition = (
                config.param_partition
                if config.param_partition is not None
                else fsdp_param_partition(params, mesh)
            )
            foreign = foreign_axis_paths(params, fsdp_partition)
            if foreign:
                raise ValueError(
                    "dp_collective='fsdp' shards params over the mesh "
                    "'data' axis only; these param_partition specs name "
                    "other axes — model-parallel specs ride the implicit "
                    "mode (dp_collective=None/'auto'):\n  "
                    + "\n  ".join(foreign)
                )
        elif config.param_partition is not None:
            raise ValueError(
                f"dp_collective={dp_mode!r} keeps params replicated "
                "(pure data parallelism); param_partition requires "
                "dp_collective='fsdp' (params sharded over 'data', "
                "per-layer all-gather inside the scan body) or the "
                "implicit mode (None/'auto') for model-parallel specs"
            )
        if config.batch_size % data_axis:
            raise ValueError(
                f"dp_collective={dp_mode!r}: batch_size "
                f"{config.batch_size} must be divisible by the mesh "
                f"data axis ({data_axis})"
            )
        if accum > 1 and (config.batch_size // data_axis) % accum:
            raise ValueError(
                f"grad_accum_steps {accum} must divide the per-device "
                f"batch ({config.batch_size} over data axis {data_axis} "
                f"= {config.batch_size // data_axis} rows)"
            )

    # Surface bad partitions BEFORE compilation (satellite of ISSUE 18):
    # a spec whose mesh-axis size doesn't divide the param dim otherwise
    # only fails deep inside jit with a GSPMD error naming no parameter.
    partition_in_play = (
        fsdp_partition if dp_mode == "fsdp" else config.param_partition
    )
    if partition_in_play is not None:
        problems = validate_partition(params, partition_in_play, mesh)
        if problems:
            raise ValueError(
                "param_partition does not fit this mesh — fix these "
                "rules before compilation:\n  " + "\n  ".join(problems)
            )

    if fsdp_partition is not None:
        p_shard = jax.tree_util.tree_map(
            lambda spec: NamedSharding(mesh, spec), fsdp_partition,
            is_leaf=lambda x: isinstance(x, P),
        )
    else:
        p_shard = _param_sharding(mesh, config, params)
    params = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), params, p_shard
    )
    state = TrainState.create(params, optimizer, rng, model_state=model_state)
    # Pin the whole state's sharding explicitly (TrainState.create built
    # opt_state/step on the default device) so jit's donation is stable.
    state_shard = TrainState(
        step=replicate(mesh),
        params=p_shard,
        opt_state=_opt_state_sharding(state.opt_state, params, p_shard, mesh),
        rng=replicate(mesh),
        model_state=(
            jax.tree_util.tree_map(lambda _: replicate(mesh), model_state)
            if model_state is not None else None
        ),
    )
    state = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), state, state_shard
    )
    unknown = sorted(set(bp) - set(first_batch))
    if unknown:
        raise ValueError(
            f"batch_partition keys {unknown} not in batch "
            f"(has {sorted(first_batch)})"
        )
    batch_shard = {
        k: (
            NamedSharding(mesh, bp[k]) if k in bp
            else data_parallel_sharding(mesh, np.asarray(v).ndim)
        )
        for k, v in first_batch.items()
    }

    # Runs even on a data=1 mesh so a single-chip "ordered" run shares the
    # multi-chip run's exact reduction structure.
    dp_fb = None
    if dp_mode:
        grad_blocks = int(config.dp_grad_blocks or data_axis)
        if dp_mode == "ordered" and (
            grad_blocks % data_axis
            or (config.batch_size // accum) % grad_blocks
        ):
            raise ValueError(
                f"dp_grad_blocks {grad_blocks} must be a multiple of the "
                f"mesh data axis ({data_axis}) and divide the "
                f"per-microbatch global batch "
                f"({config.batch_size} / grad_accum_steps {accum} = "
                f"{config.batch_size // accum})"
            )
        dp_fb = _make_dp_forward_backward(
            loss_fn, mesh, dp_mode,
            buckets=max(1, int(config.collective_buckets)),
            grad_blocks=grad_blocks,
            accum=accum,
            has_model_state=has_model_state,
            fsdp_specs=fsdp_partition,
        )

    def forward_backward(params, mstate, mb, rng):
        if has_model_state:
            (loss, (metrics, new_mstate)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params, mstate, mb, rng)
        else:
            (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, mb, rng
            )
            new_mstate = mstate
        return loss, metrics, grads, new_mstate

    def step_fn(state: TrainState, batch) -> Tuple[TrainState, Dict[str, jax.Array]]:
        step_rng = jax.random.fold_in(state.rng, state.step)
        if dp_fb is not None:
            # Accumulation and model_state live INSIDE the collective fb
            # (the inner scan accumulates under the same shard_map as the
            # exchange), so every dp mode composes with both.
            loss, metrics, grads, new_mstate = dp_fb(
                state.params, state.model_state, batch, step_rng
            )
        elif accum == 1:
            loss, metrics, grads, new_mstate = forward_backward(
                state.params, state.model_state, batch, step_rng
            )
        else:
            # Microbatch i takes every accum-th row: an interleaved split
            # keeps each microbatch evenly spread across the contiguous
            # per-device blocks of the batch-dim sharding (a blocked split
            # would put whole microbatches on single devices).
            def split(x):
                if x.shape[0] % accum:
                    raise ValueError(
                        f"batch dim {x.shape[0]} not divisible by "
                        f"grad_accum_steps {accum}"
                    )
                return jnp.moveaxis(
                    x.reshape(x.shape[0] // accum, accum, *x.shape[1:]), 1, 0
                )

            micro = jax.tree_util.tree_map(split, batch)

            def mb_step(carry, idx_mb):
                g_acc, l_acc, m_acc, mstate = carry
                i, mb = idx_mb
                loss, metrics, grads, mstate = forward_backward(
                    state.params, mstate, mb,
                    jax.random.fold_in(step_rng, i),
                )
                g_acc = jax.tree_util.tree_map(jnp.add, g_acc, grads)
                m_acc = {k: m_acc[k] + v for k, v in metrics.items()}
                return (g_acc, l_acc + loss, m_acc, mstate), None

            # Zero-seeded carry via eval_shape: tracing the forward once for
            # shapes only, so the fwd+bwd graph compiles ONCE (as the scan
            # body) instead of once unrolled + once scanned.
            out_shape = jax.eval_shape(
                lambda: forward_backward(
                    state.params, state.model_state,
                    jax.tree_util.tree_map(lambda x: x[0], micro),
                    jax.random.fold_in(step_rng, 0),
                )
            )
            loss_s, metrics_s, grads_s, _ = out_shape
            zeros = lambda tree: jax.tree_util.tree_map(
                lambda s: jnp.zeros(s.shape, s.dtype), tree
            )
            (g_sum, l_sum, m_sum, new_mstate), _ = jax.lax.scan(
                mb_step,
                (zeros(grads_s), zeros(loss_s), zeros(metrics_s),
                 state.model_state),
                (jnp.arange(accum), micro),
            )
            inv = 1.0 / accum
            grads = jax.tree_util.tree_map(lambda g: g * inv, g_sum)
            loss = l_sum * inv
            metrics = {k: v * inv for k, v in m_sum.items()}
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss, **metrics}
        return (
            TrainState(
                step=state.step + 1,
                params=new_params,
                opt_state=new_opt,
                rng=state.rng,
                model_state=new_mstate,
            ),
            metrics,
        )

    train_step = jax.jit(
        step_fn,
        in_shardings=(state_shard, batch_shard),
        out_shardings=(state_shard, None),
        donate_argnums=(0,) if config.donate_state else (),
    )

    eval_step = None
    if eval_iter_fn is not None:
        # Same input shardings as the train step: without them, eval batches
        # and (on a TP mesh) params would take default placement — a silent
        # per-batch replication/transfer cost on multi-chip meshes.
        if has_model_state:
            def eval_fn(params, mstate, batch):
                loss, (metrics, _) = loss_fn(
                    params, mstate, batch, jax.random.key(0)
                )
                return {"loss": loss, **metrics}

            eval_step = jax.jit(
                eval_fn,
                in_shardings=(p_shard, state_shard.model_state, batch_shard),
            )
        else:
            def eval_fn(params, batch):
                loss, metrics = loss_fn(
                    params, batch, jax.random.key(0)
                )
                return {"loss": loss, **metrics}

            eval_step = jax.jit(eval_fn, in_shardings=(p_shard, batch_shard))

    # ---- checkpoint manager (resume support)
    # TPP_DISABLE_MID_CHECKPOINT=1 suppresses mid-run saves regardless of
    # config (bench legs: orbax's blocking wait-for-previous-save serializes
    # against µs-scale steps and burns the wall-clock budget); the final
    # checkpoint is still written, so export and resume behave the same.
    checkpoint_every = config.checkpoint_every
    if os.environ.get("TPP_DISABLE_MID_CHECKPOINT", "") == "1":
        checkpoint_every = 0
    mngr = None
    start_step = 0
    if checkpoint_dir:
        import orbax.checkpoint as ocp

        mngr = ocp.CheckpointManager(
            os.path.abspath(checkpoint_dir),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=config.keep_checkpoints,
                save_interval_steps=max(1, checkpoint_every),
            ),
        )
        latest = mngr.latest_step()
        if latest is not None:
            # rng (a typed PRNG key) is rebuilt from the seed, not restored.
            saveable = {"step": state.step, "params": state.params,
                        "opt_state": state.opt_state}
            if has_model_state:
                saveable["model_state"] = state.model_state
            abstract = jax.tree_util.tree_map(
                ocp.utils.to_shape_dtype_struct, saveable
            )
            restored = mngr.restore(
                latest, args=ocp.args.StandardRestore(abstract)
            )
            state = TrainState(
                step=restored["step"],
                params=restored["params"],
                opt_state=restored["opt_state"],
                rng=state.rng,
                model_state=restored.get("model_state", state.model_state),
            )
            state = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(x, s), state, state_shard
            )
            start_step = int(latest)
            log.info("resumed from checkpoint step %d", start_step)
    # Replayed-span accounting: the progress marker records the furthest
    # EXECUTED step; resuming from an earlier durable checkpoint means the
    # gap re-executes.  Reported (never double-counted as fresh progress)
    # so an elastic restart can prove exactly how much work the lost host
    # cost — see tests/test_multichip_window.py.
    replayed_steps = 0
    if checkpoint_dir:
        executed = _read_progress_step(checkpoint_dir)
        if executed > start_step:
            replayed_steps = executed - start_step
            log.info(
                "resume replays steps %d..%d (executed before the "
                "interruption, lost with the non-durable window)",
                start_step + 1, executed,
            )
    tracker.training_prep_end()

    # ---- the loop
    from tpu_pipelines.data.input_pipeline import stage_global

    def put_batch(b):
        return stage_global(b, batch_shard)

    tb_writer = None
    if config.tensorboard_dir and jax.process_index() == 0:
        # Process 0 only (multi-host peers would write N duplicate points per
        # tag into the shared logdir).  Lazy import — clu pulls TensorFlow —
        # and optional: a missing clu degrades to no sink, not a dead loop.
        try:
            from clu import metric_writers

            tb_writer = metric_writers.SummaryWriter(config.tensorboard_dir)
        except ImportError as e:
            log.warning("tensorboard_dir set but clu unavailable (%s)", e)

    last_tb = {"train": -1, "eval": -1}

    def tb_write(kind: str, at_step: int, scalars: Dict[str, float]) -> None:
        if tb_writer is None or not scalars:
            return
        tb_writer.write_scalars(at_step, scalars)
        # Flush per write (log_every cadence, so amortized): a crash mid-run
        # must not lose the tail of the curve to tf.summary buffering.
        tb_writer.flush()
        last_tb[kind] = at_step

    # ---- live telemetry: gauges + health watchdog (observability/)
    from tpu_pipelines.observability.health import HealthMonitor
    from tpu_pipelines.observability.metrics import default_registry

    reg = default_registry()
    g_step_s = reg.gauge(
        "train_step_seconds", "Mean wall time per step over the last "
        "log_every window.",
    )
    g_eps = reg.gauge(
        "train_examples_per_sec", "Window throughput at log_every cadence.",
    )
    g_tps = reg.gauge(
        "train_tokens_per_sec", "Window token throughput (0 when the "
        "batch carries no token-shaped integer feature).",
    )
    g_input_wait = reg.gauge(
        "train_host_input_wait_seconds_total",
        "Cumulative post-compile host time spent feeding batches "
        "(the goodput proxy's numerator).",
    )
    g_device_mem = reg.gauge(
        "train_device_memory_bytes",
        "bytes_in_use on device 0 (0 where the backend reports none).",
    )
    g_steps = reg.gauge("train_steps_total", "Steps completed so far.")
    # ---- step-time attribution + compile/HBM tracking (telemetry plane)
    c_phase = reg.counter(
        "train_window_time_seconds",
        "Post-warmup windowed-loop wall-clock attributed per phase "
        "(infeed_wait | device_compute | device_collective | host); the "
        "phases of each window sum to its wall-clock.",
        labels=("phase",),
    )
    c_compiles_warm = reg.counter(
        "train_compiles_after_warm_total",
        "XLA backend compiles of the TRAINING STEP path observed after "
        "the first window retired — each one is a mid-run recompile "
        "stall; steady state is 0.  Administrative compiles (checkpoint "
        "snapshot copy, the eval program's own first build, background "
        "threads) land under train_compile_seconds_total{when=\"admin\"} "
        "instead.",
    )
    c_compile_s = reg.counter(
        "train_compile_seconds_total",
        "Cumulative XLA backend compile wall-clock, split by when it "
        "happened (warmup = before the first window retired, steady = "
        "after, admin = checkpoint-copy / eval-first-build / "
        "background-thread compiles that are not step stalls).",
        labels=("when",),
    )
    g_mfu = reg.gauge(
        "train_mfu",
        "Model-FLOPs utilization: cost-analysis FLOPs/step x post-warmup "
        "steps / device-compute seconds / (peak chip FLOPs x chips); 0 "
        "until measured (needs collect_cost_analysis and a known peak).",
    )
    g_dev_peak = reg.gauge(
        "device_memory_peak_bytes",
        "Per-device HBM high-water mark (memory_stats peak_bytes_in_use)"
        ", live at window cadence.",
        labels=("device",),
    )
    c_compiles_warm.inc(0)  # materialize the zero: absence is not proof

    compile_stats = {
        "warm": False, "after_warm": 0, "seconds": 0.0,
        # True while dispatching the FIRST window of a given length: a
        # cadence-split short window (checkpoint_every not a multiple of
        # window_steps) compiles a new scan once, which is that
        # program's warmup — only a re-compile of a length already seen
        # is a genuine steady-state stall.
        "first_of_len": False,
    }
    loop_thread = threading.get_ident()

    def _on_compile(duration_s: float) -> None:
        # Only the dispatch thread's un-suppressed compiles can be step
        # stalls: the async checkpointer's orbax thread and the marked
        # admin regions (snapshot copy, eval first build) compile real
        # XLA programs too, but none of them block a training step — a
        # healthy checkpointing run must still read after_warm == 0.
        if (threading.get_ident() != loop_thread
                or _compile_admin_depth() > 0):
            compile_stats["seconds"] += duration_s
            c_compile_s.labels("admin").inc(duration_s)
            return
        steady = compile_stats["warm"] and not compile_stats["first_of_len"]
        compile_stats["seconds"] += duration_s
        c_compile_s.labels("steady" if steady else "warmup").inc(duration_s)
        if steady:
            compile_stats["after_warm"] += 1
            c_compiles_warm.inc()

    # ---- federation + durable history publication (both opt-in by env;
    # no knob set => no file, no socket, byte-identical scrape).
    from tpu_pipelines.observability import federation as _fed
    from tpu_pipelines.observability import trace as _obs
    from tpu_pipelines.observability.metrics_history import MetricsHistory

    fed_source = (
        f"trainer-p{jax.process_index()}-{os.getpid()}"
        if _fed.federation_dir() is not None else None
    )
    _active_rec = _obs.active_recorder()
    _pipeline_root = config.pipeline_root
    _hist_run_id = config.run_id
    if _active_rec is not None:
        _hist_run_id = _hist_run_id or getattr(_active_rec, "run_id", "")
        rec_dir = getattr(_active_rec, "run_dir", "")
        if not _pipeline_root and rec_dir:
            # run_dir is <pipeline_root>/.runs/<run_id>
            _pipeline_root = os.path.dirname(os.path.dirname(rec_dir))
    history = (
        MetricsHistory.from_env(_pipeline_root) if _pipeline_root else None
    )
    hist_run_id = _hist_run_id or "train"
    # tokens/example: the widest trailing extent among integer features
    # (token ids); mask-like siblings share the shape, max() dedups them.
    tokens_per_example = max(
        (
            int(np.prod(np.asarray(v).shape[1:]))
            for v in first_batch.values()
            if np.asarray(v).dtype.kind in "iu" and np.asarray(v).ndim >= 2
        ),
        default=0,
    )
    monitor = HealthMonitor(
        "train_loop",
        stall_timeout_s=config.stall_timeout_s,
        on_alert=config.health_alert_cb,
    )

    def _publish_window(at_step: int, window_steps: int, window_s: float,
                        loss: Optional[float]) -> None:
        if window_steps > 0 and window_s > 0:
            step_s = window_s / window_steps
            g_step_s.set(step_s)
            g_eps.set(config.batch_size / step_s)
            g_tps.set(config.batch_size * tokens_per_example / step_s)
        g_input_wait.set(input_wait_s)
        g_steps.set(at_step)
        try:
            stats = jax.local_devices()[0].memory_stats()
            g_device_mem.set(float((stats or {}).get("bytes_in_use", 0)))
        except Exception:  # noqa: BLE001 — not every backend reports
            pass
        try:
            # Per-device HBM watermark, promoted from a bench-only number
            # to a live labeled gauge (not every backend reports it).
            for d in jax.local_devices():
                peak = (d.memory_stats() or {}).get("peak_bytes_in_use")
                if peak is not None:
                    g_dev_peak.labels(str(d.id)).set(float(peak))
        except Exception:  # noqa: BLE001
            pass
        monitor.heartbeat(at_step, loss=loss)
        if fed_source is not None:
            try:
                _fed.publish_registry(reg, source=fed_source)
            except OSError as e:
                log.warning("federation publish failed: %s", e)
        if history is not None:
            try:
                history.append(reg, hist_run_id, step=at_step)
            except OSError as e:
                log.warning("metrics-history append failed: %s", e)

    metrics_hist: list = []
    metrics = None   # stays None when resume starts at/past train_steps
    t_start = None
    anchors: list = []   # (step, host time) at each forced device read
    examples_after_t0 = 0
    input_wait_s = 0.0     # host-side time not overlapped with device work
    profiling = False
    device_batch = None
    batch = first_batch
    step = start_step
    eff_window = _effective_window_steps(config)
    window_anchor = (step, time.perf_counter())  # telemetry window start
    # Step-time attribution state (windowed path): measured per-window
    # partition (the infeed wait and device span are clocked; host is
    # the remainder, so the family sums exactly to wall-clock) with the
    # estimated collective fraction splitting the device span.
    phase_totals = {
        "infeed_wait": 0.0, "device_compute": 0.0,
        "device_collective": 0.0, "host": 0.0,
    }
    coll_frac = _collective_fraction(
        state.params, first_batch, mesh, dp_mode
    )

    eval_warmed = {"done": False}

    def emit_eval(at_step: int) -> None:
        # The eval program's FIRST build is its own warmup, not a step
        # stall — admin-book it; a re-compile on a later eval is real.
        region = (
            _compile_admin_region() if not eval_warmed["done"]
            else contextlib.nullcontext()
        )
        eval_warmed["done"] = True
        with region:
            ev = _run_eval(eval_step, state, eval_iter_fn, config,
                           put_batch, has_model_state)
        if metrics_cb:
            metrics_cb(at_step, {f"eval_{k}": v for k, v in ev.items()})
        tb_write("eval", at_step, {f"eval_{k}": v for k, v in ev.items()})
        log.info("step %d eval: %s", at_step, ev)

    _set_compile_hook(_on_compile)
    try:
        if eff_window > 1:
            # ---- device-resident multi-step window (the host-loop-tax fix).
            # The log_every window runs as ONE compiled lax.scan over a batch
            # stack staged on device by the double-buffered infeed; the only
            # per-window host traffic is the fetch of the scan's stacked
            # metrics — a copy-out, never a sync on the (donated) hot state.
            from tpu_pipelines.data.input_pipeline import windowed_infeed

            win_shard = {
                k: NamedSharding(mesh, P(None, *s.spec))
                for k, s in batch_shard.items()
            }
            train_window = jax.jit(
                lambda st, bats: jax.lax.scan(step_fn, st, bats),
                in_shardings=(state_shard, win_shard),
                out_shardings=(state_shard, None),
                donate_argnums=(0,) if config.donate_state else (),
            )

            def stage_window(stacked):
                return stage_global(stacked, win_shard)

            def window_lengths(start: int):
                # Windows shrink to land exactly on eval/checkpoint/train_steps
                # boundaries, so boundary consumers still see the state at the
                # exact step they expect.  Scan length is shape-static (each
                # distinct length is one compile); the schedule keeps distinct
                # lengths to O(1): the window itself plus boundary remainders.
                s = start
                while s < config.train_steps:
                    stop = s + eff_window
                    for every in (
                        config.eval_every if eval_step is not None else 0,
                        checkpoint_every if mngr is not None else 0,
                    ):
                        if every:
                            stop = min(stop, ((s // every) + 1) * every)
                    stop = min(stop, config.train_steps)
                    yield stop - s
                    s = stop

            saver = _AsyncCheckpointSaver(mngr) if mngr is not None else None
            seen_window_lens: set = set()
            infeed = windowed_infeed(
                itertools.chain([first_batch], train_it),
                window_lengths(step),
                stage_window,
            )
            while step < config.train_steps:
                t_in = time.perf_counter()
                tracker.data_loading_start()
                try:
                    item = next(infeed, None)
                finally:
                    tracker.data_loading_end()
                if item is None:
                    log.info("train iterator exhausted at step %d", step)
                    break
                t_fetched = time.perf_counter()
                infeed_s = t_fetched - t_in
                if t_start is not None:
                    input_wait_s += infeed_s
                w, dev_window = item
                tracker.step_start(step)
                # Scan programs are keyed by window length; the first
                # dispatch of a NEW length (cadence-split short window)
                # compiles once as that program's warmup.
                compile_stats["first_of_len"] = w not in seen_window_lens
                seen_window_lens.add(w)
                try:
                    state, mstack = train_window(state, dev_window)
                finally:
                    compile_stats["first_of_len"] = False
                step += w
                # ONE device-to-host fetch per window: the stacked metrics are
                # a data dependency of every step in the window, so the
                # transfer proves the whole window executed before the clock
                # is read — the same cannot-lie anchoring as the per-step
                # path, at window granularity.  Per HOST, not per device: the
                # scan's metric outputs land replicated (the loss mean/psum
                # makes them so), so device_get reads one locally-addressable
                # copy — no cross-device gather, and each process in a
                # multi-host run fetches only from its own devices.
                host_stack = jax.device_get(mstack)
                now = time.perf_counter()
                if t_start is None:
                    t_start = now  # the first window absorbs compile
                    # From here on, every backend compile is a mid-run stall
                    # (a shrunk boundary window, a shape change) — counted by
                    # the listener as train_compiles_after_warm_total.
                    compile_stats["warm"] = True
                else:
                    examples_after_t0 += w * config.batch_size
                    # Measured window partition: infeed wait + device span
                    # are clocked, host is the remainder (the previous
                    # window's post-fetch host work: per-step reconstruction,
                    # publishing, checkpoint markers) — so the four phases
                    # sum EXACTLY to this window's wall-clock.  The estimated
                    # collective fraction only splits the device span.
                    device_s = now - t_fetched
                    host_s = max(
                        0.0, (now - window_anchor[1]) - infeed_s - device_s
                    )
                    phases = {
                        "infeed_wait": infeed_s,
                        "device_compute": device_s * (1.0 - coll_frac),
                        "device_collective": device_s * coll_frac,
                        "host": host_s,
                    }
                    for ph, secs in phases.items():
                        phase_totals[ph] += secs
                        c_phase.labels(ph).inc(secs)
                    _obs.instant(
                        "window_breakdown", cat="trainer",
                        args={
                            "step": step, "window_steps": w,
                            "window_s": now - window_anchor[1], **phases,
                        },
                    )
                anchors.append((step, now))
                # Per-step values reconstructed from the windowed accumulator:
                # the watchdog sees every step's loss (a mid-window NaN fires
                # at the boundary) and log_every keeps its exact cadence.
                for i in range(w):
                    s_i = step - w + 1 + i
                    monitor.heartbeat(s_i, loss=float(host_stack["loss"][i]))
                    if config.log_every and s_i % config.log_every == 0:
                        host_metrics = {
                            k: float(v[i]) for k, v in host_stack.items()
                        }
                        metrics_hist.append((s_i, host_metrics))
                        if metrics_cb:
                            metrics_cb(s_i, host_metrics)
                        tb_write("train", s_i, host_metrics)
                        log.info("step %d: %s", s_i, host_metrics)
                metrics = {k: v[-1] for k, v in host_stack.items()}
                _publish_window(
                    step, step - window_anchor[0], now - window_anchor[1],
                    float(host_stack["loss"][-1]),
                )
                window_anchor = (step, now)
                if checkpoint_dir:
                    # The window just proved itself executed (the metric fetch
                    # above is a data dependency of every step in it): advance
                    # the progress marker so a crash before the NEXT durable
                    # checkpoint shows up as a replayed span on resume.
                    _write_progress(checkpoint_dir, step)
                if (
                    saver is not None and checkpoint_every
                    and step % checkpoint_every == 0
                ):
                    saver.save(step, state)
                if (
                    eval_step is not None
                    and config.eval_every
                    and step % config.eval_every == 0
                ):
                    emit_eval(step)
            if saver is not None:
                # Completion fence at loop exit: the in-flight save must be
                # durable before the final synchronous save/export below.
                saver.fence()
        else:
            while step < config.train_steps:
                if config.profile_dir and not profiling and step - start_step == config.profile_from:
                    jax.profiler.start_trace(config.profile_dir)
                    profiling = True
                tracker.step_start(step)
                t_in = time.perf_counter()
                device_batch = put_batch(batch)
                if t_start is not None:  # only measure the post-compile window
                    input_wait_s += time.perf_counter() - t_in
                state, metrics = train_step(state, device_batch)
                step += 1
                monitor.heartbeat(step)  # liveness only; loss rides log cadence
                if profiling and step - start_step >= config.profile_to:
                    # Device-to-host read (not block_until_ready — see t_start
                    # note) so the trace captures the step's full execution.
                    np.asarray(metrics["loss"])
                    jax.profiler.stop_trace()
                    profiling = False
                if t_start is None:
                    # Start timing after step 1 retires (excludes compile time).  A
                    # device-to-host READ, not block_until_ready: on some platforms
                    # (e.g. tunneled experimental backends) block_until_ready returns
                    # before execution finishes, which would start the clock early —
                    # a transfer of the step's output cannot lie.
                    np.asarray(metrics["loss"])
                    t_start = time.perf_counter()
                    compile_stats["warm"] = True  # later compiles are stalls
                    anchors.append((step, t_start))
                else:
                    examples_after_t0 += config.batch_size
                    if (
                        config.anchor_every
                        and (step - anchors[0][0]) % config.anchor_every == 0
                    ):
                        # Device-to-host read of THIS step's output: the step chain
                        # is a data dependency, so the transfer proves every step up
                        # to here executed on device before the clock is read.
                        np.asarray(metrics["loss"])
                        anchors.append((step, time.perf_counter()))
                if config.log_every and step % config.log_every == 0:
                    host_metrics = {
                        k: float(v) for k, v in metrics.items()
                    }
                    metrics_hist.append((step, host_metrics))
                    if metrics_cb:
                        metrics_cb(step, host_metrics)
                    tb_write("train", step, host_metrics)
                    log.info("step %d: %s", step, host_metrics)
                    # Telemetry window: the host loss just materialized above, so
                    # the NaN/spike checks are free here; gauges cover the span
                    # since the previous log point.
                    now = time.perf_counter()
                    _publish_window(
                        step, step - window_anchor[0], now - window_anchor[1],
                        host_metrics.get("loss"),
                    )
                    window_anchor = (step, now)
                if (
                    mngr is not None and checkpoint_every
                    and step % checkpoint_every == 0
                ):
                    # Gated on the cadence here, not just inside orbax: building
                    # save args and consulting the manager every step is pure
                    # per-step host overhead on the hot path.
                    mngr.save(step, args=_ocp_save_args(state))
                    _write_progress(checkpoint_dir, step)
                if (
                    eval_step is not None
                    and config.eval_every
                    and step % config.eval_every == 0
                ):
                    emit_eval(step)
                if step >= config.train_steps:
                    break
                try:
                    t_in = time.perf_counter()
                    tracker.data_loading_start()
                    try:
                        batch = next(train_it)
                    finally:
                        # On StopIteration too — an open-ended data-loading interval
                        # would misattribute everything through job_end as badput.
                        tracker.data_loading_end()
                    if t_start is not None:
                        input_wait_s += time.perf_counter() - t_in
                except StopIteration:
                    log.info("train iterator exhausted at step %d", step)
                    break

    finally:
        _set_compile_hook(None)

    if profiling:
        jax.profiler.stop_trace()
    if metrics is not None:
        # Host read of the final step's output: the step sequence is a
        # dependency chain, so this proves every timed step executed (see
        # t_start note on why block_until_ready is not sufficient).
        final_loss = float(np.asarray(metrics["loss"]))
        now = time.perf_counter()
        _publish_window(
            step, step - window_anchor[0], now - window_anchor[1],
            final_loss,
        )
    jax.block_until_ready(state.params)
    monitor.close()
    elapsed = max(1e-9, time.perf_counter() - (t_start or time.perf_counter()))
    eps = examples_after_t0 / elapsed if examples_after_t0 else 0.0

    # Median examples/sec over the sync-anchored windows (see anchor_every).
    anchored_eps = 0.0
    window_rates = []
    for (s1, t1), (s2, t2) in zip(anchors, anchors[1:]):
        if t2 > t1:
            window_rates.append((s2 - s1) * config.batch_size / (t2 - t1))
    if window_rates:
        window_rates.sort()
        anchored_eps = window_rates[len(window_rates) // 2]

    # Report the actual final-step metrics (not the last logged snapshot).
    final_metrics: Dict[str, float] = (
        {k: float(v) for k, v in metrics.items()} if metrics is not None else {}
    )
    if eval_step is not None:
        # Post-loop final eval: any compile here (first build when no
        # in-loop eval cadence fired) happens after the last step — by
        # definition not a step stall.
        with _compile_admin_region():
            ev = _run_eval(eval_step, state, eval_iter_fn, config,
                           put_batch, has_model_state)
        final_metrics.update({f"eval_{k}": v for k, v in ev.items()})

    if tb_writer is not None:
        # Only what the in-loop cadence didn't already emit at this step —
        # a same-tag/same-step rewrite doubles points in TensorBoard.
        tail: Dict[str, float] = {}
        if step != last_tb["train"]:
            tail.update({
                k: v for k, v in final_metrics.items()
                if not k.startswith("eval_")
            })
        if step != last_tb["eval"]:
            tail.update({
                k: v for k, v in final_metrics.items()
                if k.startswith("eval_")
            })
        tb_write("train", step, tail)
        tb_writer.close()

    if mngr is not None:
        if mngr.latest_step() != step:
            mngr.save(step, args=_ocp_save_args(state), force=True)
        mngr.wait_until_finished()
        _write_progress(checkpoint_dir, step)

    cost_flops = None
    cost_source = ""
    if config.collect_cost_analysis and metrics is not None:
        # XLA's per-step FLOP count for the SAME step function — after the
        # timed loop, so the extra trace/compile cannot pollute throughput.
        # Preference order: cost analysis of the optimized executable, then
        # HLO cost analysis of the unoptimized lowering (backends without
        # the former).  Both count every op, so a figure BELOW an analytic
        # 6NT-style numerator falsifies that numerator.
        try:
            if device_batch is None:
                # Windowed path: no per-step batch is alive; the analysis
                # only needs shapes/shardings, so re-stage the first batch.
                device_batch = put_batch(first_batch)
            lowered = train_step.lower(state, device_batch)
            ca = None
            try:
                ca = lowered.compile().cost_analysis()
                cost_source = "compiled"
            except Exception:
                ca = lowered.cost_analysis()
                cost_source = "lowered"
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else None
            if ca and ca.get("flops"):
                cost_flops = float(ca["flops"])
            else:
                cost_source = ""
        except Exception as e:  # noqa: BLE001 — diagnostics must not fail a run
            log.warning("train-step cost analysis failed: %s", e)

    # MFU over the ATTRIBUTED device-compute seconds when the windowed
    # loop measured them (post-warmup windows only), else post-compile
    # wall-clock (the per-step path cannot separate device from host
    # without a per-step sync — that figure is a lower bound).
    mfu = None
    peak = _peak_flops_per_chip(config)
    steps_measured = (
        examples_after_t0 / config.batch_size if config.batch_size else 0
    )
    if cost_flops and peak and steps_measured > 0:
        device_s = phase_totals["device_compute"] or elapsed
        if device_s > 0:
            mfu = cost_flops * steps_measured / device_s / (
                peak * n_devices
            )
            g_mfu.set(round(mfu, 4))
            # The gauge changed after the loop's last window publish:
            # push one more snapshot so the scrape/ring carry it.
            if fed_source is not None:
                try:
                    _fed.publish_registry(reg, source=fed_source)
                except OSError as e:
                    log.warning("federation publish failed: %s", e)
            if history is not None:
                try:
                    history.append(reg, hist_run_id, step=step)
                except OSError as e:
                    log.warning("metrics-history append failed: %s", e)

    tracker.job_end()
    gsum = tracker.summary()
    # The proxy stays the reported floor when the library is absent; when
    # present, the library's number is the real (stricter) figure — it counts
    # init/prep/compile windows as badput, so short runs read lower.
    proxy_goodput = (
        round(max(0.0, 1.0 - input_wait_s / elapsed), 4)
        if examples_after_t0 else 1.0
    )
    # Bridge the goodput/badput decomposition into the run trace (no-op
    # outside a traced pipeline run): the run-wide profile then carries
    # the same algebra trainer/goodput.py computes for the train loop.
    from tpu_pipelines.observability import trace as _obs

    _obs.instant(
        "goodput_summary", cat="trainer",
        args={
            "goodput": gsum.get("goodput", proxy_goodput),
            "source": (
                "ml_goodput_measurement" if gsum
                else "host_input_wait_proxy"
            ),
            "badput": gsum.get("badput", {}),
            "goodput_post_compile": proxy_goodput,
            "steps_completed": step,
            # Replayed span (elastic resume): steps re-executed because
            # the interruption outran the last durable window.  Counted
            # here as lost work, never as fresh progress.
            "replayed_steps": replayed_steps,
        },
    )
    # Stamp the window-phase breakdown into the RunTrace alongside the
    # per-window instants, so `trace`/`trace diff` compare runs on where
    # their windows went, not just how long they took.
    _obs.instant(
        "train_telemetry_summary", cat="trainer",
        args={
            "window_phase_seconds": {
                k: round(v, 6) for k, v in phase_totals.items()
            },
            "compiles_after_warm": compile_stats["after_warm"],
            "compile_seconds": round(compile_stats["seconds"], 6),
            "collective_fraction_est": round(coll_frac, 6),
            "mfu": mfu,
            "window_steps": eff_window,
        },
    )
    result = TrainResult(
        final_metrics=final_metrics,
        examples_per_sec=round(eps, 2),
        examples_per_sec_per_chip=round(eps / n_devices, 2),
        anchored_examples_per_sec_per_chip=round(anchored_eps / n_devices, 2),
        anchor_windows=len(window_rates),
        steps_completed=step,
        resumed_from_step=start_step,
        goodput=gsum.get("goodput", proxy_goodput),
        goodput_source=(
            "ml_goodput_measurement" if gsum else "host_input_wait_proxy"
        ),
        goodput_post_compile=proxy_goodput,
        badput=gsum.get("badput", {}),
        cost_analysis_flops_per_step=cost_flops,
        cost_analysis_source=cost_source,
        window_steps=eff_window,
        replayed_steps=replayed_steps,
        dp_collective=dp_mode,
        mfu=round(mfu, 4) if mfu is not None else None,
        compiles_after_warm=compile_stats["after_warm"],
        window_phase_seconds={
            k: round(v, 6) for k, v in phase_totals.items()
        },
    )
    final = (
        (state.params, state.model_state) if has_model_state
        else state.params
    )
    return final, result


ENV_WINDOW_STEPS = "TPP_WINDOW_STEPS"


def _effective_window_steps(config: TrainLoopConfig) -> int:
    """Resolve the multi-step window length: explicit config >
    TPP_WINDOW_STEPS env > log_every; floor 1.  Profiling forces 1 —
    a trace of one scan dispatch has no per-step spans to look at."""
    w = config.window_steps
    if w is None:
        raw = os.environ.get(ENV_WINDOW_STEPS, "").strip()
        if raw:
            try:
                w = int(raw)
            except ValueError:
                log.warning("ignoring non-integer %s=%r", ENV_WINDOW_STEPS, raw)
    if w is None:
        w = config.log_every
    w = max(1, int(w or 0))
    if w > 1 and config.profile_dir:
        log.info(
            "window_steps=%d forced to 1: profile_dir is set and the "
            "profiler needs per-step dispatch granularity", w,
        )
        return 1
    return w


def _progress_path(checkpoint_dir: str) -> str:
    return os.path.join(os.path.abspath(checkpoint_dir), "window_progress.json")


def _write_progress(checkpoint_dir: str, step: int) -> None:
    """Record the furthest step the loop has EXECUTED (crash-durable,
    atomic) — intentionally ahead of the last durable checkpoint.  On
    resume the gap between this marker and the restored step is the
    replayed span: work that ran, was lost with the host, and runs again.
    The resumed run reports it (TrainResult.replayed_steps) so goodput
    accounting can prove replayed examples are counted as badput, not as
    fresh progress."""
    from tpu_pipelines.robustness import atomic_write_json

    try:
        atomic_write_json(
            _progress_path(checkpoint_dir), {"step": int(step)}
        )
    except OSError as e:  # progress is accounting, never a run failure
        log.warning("window progress write failed: %s", e)


def _read_progress_step(checkpoint_dir: str) -> int:
    from tpu_pipelines.robustness import load_json_tolerant

    data = load_json_tolerant(_progress_path(checkpoint_dir))
    try:
        return int((data or {}).get("step", 0))
    except (TypeError, ValueError):
        return 0


def _saveable(state):
    out = {"step": state.step, "params": state.params,
           "opt_state": state.opt_state}
    if state.model_state is not None:
        out["model_state"] = state.model_state
    return out


def _ocp_save_args(state):
    import orbax.checkpoint as ocp

    return ocp.args.StandardSave(_saveable(state))


class _AsyncCheckpointSaver:
    """Checkpoint writes off the windowed loop's critical path.

    ``save()`` first snapshots the saveable state with an on-device copy —
    the hot state's buffers are donated into the next dispatched window,
    so a background reader must not touch them — then a daemon thread
    fetches the copy and runs the orbax save to completion.  ``fence()``
    (run before every subsequent save and at loop exit) joins the thread
    and re-raises any save error, so a kill between windows loses at most
    the one in-flight save, never a finished one (orbax step dirs are
    atomic), and the final checkpoint is always durable before
    ``train_loop`` returns."""

    def __init__(self, mngr):
        self._mngr = mngr
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def save(self, step: int, state: "TrainState") -> None:
        self.fence()
        with _compile_admin_region():
            snap = jax.tree_util.tree_map(
                lambda x: jnp.array(x) if isinstance(x, jax.Array) else x,
                _saveable(state),
            )

        def run() -> None:
            import orbax.checkpoint as ocp

            try:
                self._mngr.save(step, args=ocp.args.StandardSave(snap))
                self._mngr.wait_until_finished()
            except BaseException as e:  # noqa: BLE001 — re-raised at fence
                self._error = e

        self._thread = threading.Thread(
            target=run, name="tpp-async-ckpt", daemon=True
        )
        self._thread.start()

    def fence(self) -> None:
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err


def _run_eval(eval_step, state, eval_iter_fn, config, put_batch,
              has_model_state) -> Dict[str, float]:
    totals: Dict[str, float] = {}
    n = 0
    for i, batch in enumerate(eval_iter_fn()):
        if config.eval_steps and i >= config.eval_steps:
            break
        if has_model_state:
            m = eval_step(state.params, state.model_state, put_batch(batch))
        else:
            m = eval_step(state.params, put_batch(batch))
        for k, v in m.items():
            totals[k] = totals.get(k, 0.0) + float(v)
        n += 1
    return {k: v / max(1, n) for k, v in totals.items()}
