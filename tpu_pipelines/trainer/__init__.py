"""Trainer runtime: FnArgs contract, jitted train loop, checkpointing, export.

TPU-native equivalent of the TFX Trainer + tf.distribute strategy stack
(SURVEY.md §2a Trainer, §3.3): the user's ``run_fn(fn_args)`` keeps the TFX
contract; the distribution strategy is a ``jax.sharding.Mesh`` — the hot loop
is one jitted train step with the batch sharded over the ``data`` axis and
gradient all-reduce emitted by XLA over ICI/DCN.
"""

from tpu_pipelines.trainer.fn_args import FnArgs, TrainResult  # noqa: F401
from tpu_pipelines.trainer.train_loop import (  # noqa: F401
    TrainLoopConfig,
    TrainState,
    train_loop,
)
from tpu_pipelines.trainer.export import (  # noqa: F401
    export_model,
    load_exported_model,
    warm_start_init,
)
