"""Model export/load: self-contained serving payloads.

The Model artifact payload (what Pusher ships, what InfraValidator/
BulkInferrer/serving load) is fully self-contained:

    <uri>/checkpoint/        orbax params checkpoint
    <uri>/module_copy.py     user module (defines build_model)
    <uri>/transform_graph/   copy of the resolved TransformGraph (optional)
    <uri>/model_spec.json    hyperparameters, feature names, format version

Loading reconstructs ``predict(raw_batch)`` = transform host stage (numpy
string ops) → one jitted on-chip function (numeric transform fused with the
model forward pass) — preprocessing and model co-located on TPU, the
``jit_compile=True`` serving/bulk-inference story from BASELINE, with zero
training/serving skew because the TransformGraph is the same artifact the
Trainer's input data was materialized through.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from tpu_pipelines.trainer import quantize as qz
from tpu_pipelines.transform.graph import TransformGraph
from tpu_pipelines.utils.module_loader import load_fn, load_module

SPEC_FILE = "model_spec.json"
MODULE_COPY = "module_copy.py"
CHECKPOINT_DIR = "checkpoint"
TRANSFORM_DIR = "transform_graph"
FORMAT_VERSION = "tpu-pipelines-model/v1"


def export_model(
    *,
    serving_model_dir: str,
    params: Any,
    module_file: str,
    hyperparameters: Optional[Dict[str, Any]] = None,
    transform_graph_uri: str = "",
    extra_spec: Optional[Dict[str, Any]] = None,
    serving_dtype: Optional[str] = None,
    training_statistics_uri: str = "",
    training_schema_uri: str = "",
) -> str:
    """Write a self-contained model payload; returns the dir.

    Multi-host safe: the orbax save is a collective every process joins
    (each writes the param shards it owns into the shared dir); all other
    writes are plain files and happen on process 0 only.
    """
    os.makedirs(serving_model_dir, exist_ok=True)
    import orbax.checkpoint as ocp

    primary = jax.process_index() == 0
    ckpt_path = os.path.abspath(os.path.join(serving_model_dir, CHECKPOINT_DIR))
    if primary and os.path.exists(ckpt_path):
        shutil.rmtree(ckpt_path)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("export_model:pre_save")
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(ckpt_path, params)

    if primary:
        shutil.copyfile(
            module_file, os.path.join(serving_model_dir, MODULE_COPY)
        )
        if transform_graph_uri:
            dst = os.path.join(serving_model_dir, TRANSFORM_DIR)
            if os.path.exists(dst):
                shutil.rmtree(dst)
            shutil.copytree(transform_graph_uri, dst)
        spec = {
            "format": FORMAT_VERSION,
            "hyperparameters": hyperparameters or {},
            "has_transform": bool(transform_graph_uri),
            # Serving-payload metadata (ISSUE 14): the dtype the loader
            # should serve at (bf16 payloads cast ONCE at load; aqt_int8
            # payloads dequantize inside the jitted step) and the
            # resident parameter bytes — what the fleet's
            # serving_version_memory_bytes gauge reports per version.
            "dtype": serving_dtype or qz.infer_dtype(params),
            "params_bytes": qz.params_nbytes(params),  # tpp: disable=TPP214 (payload key)
            **(extra_spec or {}),
        }
        # Training-data lineage (ISSUE 20): the statistics/schema URIs the
        # deployed fleet scores live traffic against — recorded on the
        # payload itself so serving never walks the metadata store.  Only
        # written when provided, so pre-existing payload specs stay
        # byte-identical.
        if training_statistics_uri:
            spec["training_statistics_uri"] = training_statistics_uri
        if training_schema_uri:
            spec["training_schema_uri"] = training_schema_uri
        with open(os.path.join(serving_model_dir, SPEC_FILE), "w") as f:
            json.dump(spec, f, indent=2, sort_keys=True, default=str)
    return serving_model_dir


class AotDispatch:
    """Shape-keyed table of ahead-of-time compiled serving executables.

    ``serving/aot.py`` populates it at swap/canary time (one compiled —
    or cache-deserialized — executable per padded bucket shape); the
    loaded model's predict paths consult it before falling back to the
    lazily-traced jit.  Empty table = zero-cost passthrough (one truthy
    check per request), so payloads outside the fleet never pay for it.

    A post-warm lookup MISS that falls back to jit is a broken warmup
    contract — the request pays an XLA trace mid-traffic.  The first
    miss per (endpoint, signature) increments ``compiles_after_warm``
    (repeats hit the jit cache, so only the first is a compile) and
    fires ``on_compile_after_warm`` — the fleet wires that to
    ``serving_aot_compiles_after_warm_total`` (budget: zero), the
    predict twin of the decode engine's counter.
    """

    def __init__(self):
        self.entries: Dict[Tuple[str, tuple], Any] = {}
        self.fallbacks = 0
        self.compiles_after_warm = 0
        self.on_compile_after_warm: Optional[Callable[[], None]] = None
        self._fallback_sigs: set = set()
        self._lock = threading.Lock()

    @staticmethod
    def signature(batch: Dict[str, Any]) -> tuple:
        return tuple(sorted(
            (k, tuple(np.shape(v)), str(np.asarray(v).dtype))
            for k, v in batch.items()
        ))

    def lookup(self, endpoint: str, batch: Dict[str, Any]):
        return self.entries.get((endpoint, self.signature(batch)))

    def install(self, endpoint: str, sig: tuple, executable: Any) -> None:
        with self._lock:
            self.entries[(endpoint, sig)] = executable

    def record_fallback(self, endpoint: str, batch: Dict[str, Any]) -> None:
        sig = (endpoint, self.signature(batch))
        fresh = False
        with self._lock:
            self.fallbacks += 1
            if sig not in self._fallback_sigs:
                self._fallback_sigs.add(sig)
                self.compiles_after_warm += 1
                fresh = True
            cb = self.on_compile_after_warm
        if fresh and cb is not None:
            cb()


@dataclasses.dataclass
class LoadedModel:
    params: Any
    model: Any                       # flax Module from build_model
    spec: Dict[str, Any]
    transform: Optional[TransformGraph]
    predict: Callable[[Dict[str, np.ndarray]], Any]
    predict_transformed: Callable[[Dict[str, np.ndarray]], Any]
    # Autoregressive generation (seq2seq models): present when the exported
    # module defines ``make_generate_step(model, hyperparameters)`` (preferred;
    # returns ``fn(params, transformed_batch)``) or the legacy
    # ``make_generate_fn(model, params, hyperparameters)``.  ``generate``
    # takes raw batches (host transform applied first); None otherwise.
    generate: Optional[Callable[[Dict[str, np.ndarray]], Any]] = None
    # Continuous-batching decode contract (serving/generative.py): present
    # when the exported module defines ``make_decode_fns(model,
    # hyperparameters)`` (e.g. ``models/t5.py make_continuous_decode_fns``)
    # — prefill/step + geometry the generative fleet model type builds its
    # per-replica engines from.  None = whole-request generate only.
    decode_fns: Any = None
    # Speculative-decoding draft lane (serving/generative.py spec_tokens):
    # present when the exported module defines
    # ``make_draft_decode_fns(model, hyperparameters)`` returning
    # ``(draft_fns, draft_params)`` — a smaller model speaking the same
    # decode contract with the SAME geometry constants.  None = the
    # engine self-drafts (or speculation stays off).
    draft_decode_fns: Any = None
    draft_params: Any = None
    # The two halves of `predict`, exposed for exporters (serving/
    # saved_model.py): host string stage (numpy, identity when no transform)
    # and the device computation (numeric transform fused with the forward
    # pass).  ``device_predict`` binds the loaded params, so tracing it
    # (jax2tf) embeds the weights — correct for SavedModel export.
    host_preprocess: Callable[[Dict[str, np.ndarray]], Dict[str, np.ndarray]] = None
    device_predict: Callable[[Dict[str, Any]], Any] = None
    # The raw jitted step underlying predict/predict_transformed, taking
    # ``(params, batch)``.  Params are ARGUMENTS of the compiled program —
    # never closed over — so the compiled predict program is weight-free
    # (a closure would bake every weight into the HLO as a literal constant:
    # one copy per compiled entry point, and oversized compile payloads on
    # remote-compile platforms).  Tested by test_export_no_weight_constants.
    forward_step: Callable[[Any, Dict[str, Any]], Any] = None
    device_step: Callable[[Any, Dict[str, Any]], Any] = None
    # Serving-payload metadata recorded at export (spec["dtype"] /
    # spec["params_bytes"]): the dtype this payload serves at
    # ("float32" | "bfloat16" | "aqt_int8") and its resident parameter
    # bytes (quantized payloads count int8 + scale storage).  The fleet
    # publishes both per resident version.
    dtype: str = "float32"
    params_bytes: int = 0
    # Training-data lineage stamped on the payload spec at export or
    # Pusher time (ISSUE 20): the ExampleStatistics payload the model
    # trained against ("" = unstamped) and its schema.  The fleet's
    # TrafficSampler resolves its drift baseline from these — no
    # metadata-store walk at serving time.
    training_statistics_uri: str = ""
    training_schema_uri: str = ""
    # Payload directory this model was loaded from ("" for hand-built
    # instances) — the AOT executable cache keys on its content hash.
    uri: str = ""
    # Ahead-of-time executable table (serving/aot.py warms it at the
    # fleet's canary gate; empty = lazy jit, the pre-ISSUE-14 behavior).
    aot: Optional[AotDispatch] = None


def model_input_columns(
    loaded: "LoadedModel", raw: bool
) -> Optional[List[str]]:
    """Columns the loaded model's predict path actually consumes, for
    column-projected Parquet reads (Evaluator/BulkInferrer pass these as
    ``columns=`` instead of decoding every column).

    ``raw=True`` is the predict/generate surface (embedded transform applied
    to raw examples): the transform graph's input features.  ``raw=False``
    is predict_transformed: the transform's output features.  Returns None —
    read everything — when the payload carries no transform graph (the
    model's feature selection is then invisible from the spec) so projection
    can never starve an unknown model.
    """
    if loaded.transform is None:
        return None
    cols = (
        loaded.transform.input_feature_names() if raw
        else loaded.transform.output_feature_names()
    )
    # Models may read declared feature lists beyond the transform surface
    # (e.g. a hyperparameter-selected passthrough column).
    extra = (loaded.spec.get("hyperparameters") or {}).get("features")
    if isinstance(extra, (list, tuple)):
        cols = sorted(set(cols) | {str(c) for c in extra})
    return cols


def _checkpoint_abstract(uri: str, sharding=None) -> Any:
    """Shape/dtype(/sharding) tree of an exported payload's checkpoint, read
    from checkpoint metadata — no arrays materialized.  None when the
    metadata layout is unreadable (orbax version drift); the ONE place that
    parsing lives, so restore and warm-start validation cannot diverge."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(os.path.join(uri, CHECKPOINT_DIR))
    try:
        with ocp.StandardCheckpointer() as ckptr:
            meta = ckptr.metadata(path).item_metadata.tree
        return jax.tree.map(
            lambda m: jax.ShapeDtypeStruct(
                tuple(m.shape), m.dtype, sharding=sharding
            ),
            meta,
        )
    except Exception:
        return None


def restore_exported_params(uri: str) -> Any:
    """Restore the params checkpoint of an exported payload, device-resident.

    The checkpoint is restored against an abstract target reconstructed from
    the checkpoint's own metadata (shape/dtype tree), avoiding orbax's
    untyped-restore path and its UNSAFE warnings, then ``device_put`` once so
    every subsequent jitted call ships no host arrays.
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(os.path.join(uri, CHECKPOINT_DIR))
    target = _checkpoint_abstract(
        uri, sharding=jax.sharding.SingleDeviceSharding(jax.local_devices()[0])
    )
    with ocp.StandardCheckpointer() as ckptr:
        if target is not None:
            return ckptr.restore(path, target)
        return jax.device_put(ckptr.restore(path))


def exported_params_abstract(uri: str) -> Any:
    """Shape/dtype tree of an exported payload's checkpoint — see
    ``_checkpoint_abstract`` (no arrays materialized; None when the
    metadata layout is unreadable)."""
    return _checkpoint_abstract(uri)


def warm_start_init(fn_args, init_params_fn):
    """TFX warm-start semantics for ``run_fn`` modules.

    When the Trainer received a ``base_model`` input (e.g. wired from
    ``Resolver(strategy="latest_created")``), ``fn_args.custom_config``
    carries ``base_model_uri``; the returned init fn then restores the
    exported payload's params instead of random-initializing.  Without a
    base model it returns ``init_params_fn`` unchanged, so modules can wrap
    unconditionally::

        init_params_fn = warm_start_init(fn_args, init_params_fn)

    Both init contracts are honored: a plain params tree, and the
    ``has_model_state`` two-tuple ``(params, model_state)`` — exported
    payloads carry params only, so model_state stays freshly initialized.

    The restored params must match the module's own init exactly
    (structure, shapes, dtypes) — warm-starting across architecture changes
    is a config error surfaced with the offending paths, not a silent
    partial load.  Validation runs on ``jax.eval_shape`` of the init and
    the checkpoint's metadata, so no throwaway random init is materialized.
    """
    uri = (getattr(fn_args, "custom_config", None) or {}).get(
        "base_model_uri", ""
    )
    if not uri:
        return init_params_fn

    from tpu_pipelines.parallel.partition import path_str

    def _validate(fresh_params, restored):
        fresh_flat = jax.tree_util.tree_flatten_with_path(fresh_params)[0]
        rest_flat = jax.tree_util.tree_flatten_with_path(restored)[0]
        fresh_map = {path_str(path): leaf for path, leaf in fresh_flat}
        rest_map = {path_str(path): leaf for path, leaf in rest_flat}
        problems = []
        for key in sorted(set(fresh_map) | set(rest_map)):
            a, b = fresh_map.get(key), rest_map.get(key)
            if a is None or b is None:
                problems.append(f"{key}: only in "
                                f"{'base model' if a is None else 'init'}")
            elif a.shape != b.shape or a.dtype != b.dtype:
                problems.append(
                    f"{key}: init {a.shape}/{a.dtype} vs "
                    f"base model {b.shape}/{b.dtype}"
                )
        if problems:
            raise ValueError(
                f"warm-start base model at {uri!r} does not match this "
                f"module's params: " + "; ".join(problems[:8])
            )

    def init(rng, sample_batch):
        shapes = jax.eval_shape(init_params_fn, rng, sample_batch)
        is_tuple = isinstance(shapes, tuple) and len(shapes) == 2
        params_shapes = shapes[0] if is_tuple else shapes
        abstract = exported_params_abstract(uri)
        if abstract is not None:
            _validate(params_shapes, abstract)
        model_state = None
        if is_tuple:
            fresh_params, model_state = init_params_fn(rng, sample_batch)
            # Free the throwaway random params before restoring, so peak
            # device memory holds one params tree, not two.
            del fresh_params
        restored = restore_exported_params(uri)
        if abstract is None:  # metadata unreadable: concrete validation
            _validate(params_shapes, restored)
        return (restored, model_state) if is_tuple else restored

    return init


def load_exported_model(uri: str) -> LoadedModel:
    """Reload an exported payload into a ready predict function."""
    with open(os.path.join(uri, SPEC_FILE)) as f:
        spec = json.load(f)
    if spec.get("format") != FORMAT_VERSION:
        raise ValueError(
            f"model at {uri!r} has format {spec.get('format')!r}, "
            f"expected {FORMAT_VERSION}"
        )
    module_copy = os.path.join(uri, MODULE_COPY)
    module = load_module(module_copy)
    build_model = load_fn(module_copy, "build_model")
    model = build_model(spec.get("hyperparameters", {}))
    # Optional module hook for models whose __call__ is not dict-of-features
    # (e.g. image models taking one array): apply_fn(model, params, batch).
    apply_fn = getattr(
        module, "apply_fn",
        lambda model, params, batch: model.apply({"params": params}, batch),
    )

    params = restore_exported_params(uri)
    dtype = str(spec.get("dtype") or qz.infer_dtype(params))
    quantized = dtype == qz.DTYPE_AQT_INT8 or qz.tree_is_quantized(params)
    if dtype == qz.DTYPE_BFLOAT16:
        # bf16 fast path: ONE cast at load (a no-op when the checkpoint
        # already stores bf16), so no request ever pays a per-call cast
        # and the resident tree holds half the bytes.
        import jax.numpy as jnp

        params = qz.cast_params(params, jnp.bfloat16)
    if quantized:
        # aqt_int8 payloads stay int8-resident; the dequant runs INSIDE
        # the jitted step (fused by XLA — gathers read int8 rows), so
        # apply_fn always sees the dense tree it was written against.
        raw_apply = apply_fn

        def apply_fn(model, p, batch, _apply=raw_apply):
            return _apply(model, qz.dequantize_params(p), batch)

    transform = None
    if spec.get("has_transform"):
        transform = TransformGraph.load(os.path.join(uri, TRANSFORM_DIR))

    @jax.jit
    def _forward(params, transformed: Dict[str, Any]):
        return apply_fn(model, params, transformed)

    # AOT executable table: serving/aot.py fills it per padded bucket at
    # the fleet's swap gate; until then every lookup short-circuits on
    # the empty-dict check and the jit path below is exactly pre-AOT.
    aot = AotDispatch()

    def _dispatch(endpoint: str, jit_fn, batch):
        if aot.entries:
            exe = aot.lookup(endpoint, batch)
            if exe is not None:
                return exe(params, batch)
            aot.record_fallback(endpoint, batch)
        return jit_fn(params, batch)

    if transform is not None:
        host_fn, device_fn, _ = transform.split_host_device()

        @jax.jit
        def _transform_and_forward(params, iface: Dict[str, Any]):
            # Numeric transform + model forward in ONE compiled computation.
            return apply_fn(model, params, device_fn(iface))

        def predict(raw_batch: Dict[str, np.ndarray]):
            return _dispatch("raw", _transform_and_forward, host_fn(raw_batch))

        host_preprocess = host_fn
        device_step = _transform_and_forward
    else:
        def predict(raw_batch: Dict[str, np.ndarray]):
            return _dispatch("raw", _forward, raw_batch)

        host_preprocess = lambda b: b  # noqa: E731
        device_step = _forward

    def predict_transformed(batch: Dict[str, np.ndarray]):
        return _dispatch("transformed", _forward, batch)

    generate = None
    step_builder = getattr(module, "make_generate_step", None)
    gen_builder = getattr(module, "make_generate_fn", None)
    if quantized:
        # Generate/decode hooks receive the params tree verbatim and were
        # written against dense params; a quantized payload serves the
        # predict surfaces only.  A generative fleet's canary refuses it
        # (no decode contract) instead of crashing mid-decode.
        step_builder = gen_builder = None
    if step_builder is not None:
        # Preferred hook: fn(params, transformed_batch) — params stay a jit
        # argument all the way down.
        generate_step = step_builder(model, spec.get("hyperparameters", {}))
        device_generate = lambda b: generate_step(params, b)  # noqa: E731
    elif gen_builder is not None:
        # Legacy hook closes over params inside the user module; still
        # supported, but large models should migrate to make_generate_step.
        device_generate = gen_builder(
            model, params, spec.get("hyperparameters", {})
        )
    else:
        device_generate = None
    if device_generate is not None:
        if transform is not None:
            _transform_dev = jax.jit(device_fn)

            def generate(raw_batch: Dict[str, np.ndarray]):
                return device_generate(_transform_dev(host_fn(raw_batch)))
        else:
            generate = device_generate

    decode_builder = (
        None if quantized else getattr(module, "make_decode_fns", None)
    )
    decode_fns = None
    draft_decode_fns = draft_params = None
    if decode_builder is not None:
        # Continuous-batching contract for the generative fleet model
        # type; params stay engine arguments (never closed over), same
        # discipline as make_generate_step.
        decode_fns = decode_builder(model, spec.get("hyperparameters", {}))
        draft_builder = getattr(module, "make_draft_decode_fns", None)
        if draft_builder is not None:
            # Draft lane for speculative decoding: the module supplies a
            # smaller model speaking the same contract (and geometry)
            # plus its own params — e.g. a distilled T5 checkpoint
            # shipped inside the payload.  The engine only consumes this
            # when the fleet enables ``spec_tokens``.
            draft_decode_fns, draft_params = draft_builder(
                model, spec.get("hyperparameters", {})
            )

    return LoadedModel(
        params=params,
        model=model,
        spec=spec,
        transform=transform,
        predict=predict,
        predict_transformed=predict_transformed,
        host_preprocess=host_preprocess,
        device_predict=lambda batch: device_step(params, batch),
        forward_step=_forward,
        device_step=device_step,
        generate=generate,
        decode_fns=decode_fns,
        draft_decode_fns=draft_decode_fns,
        draft_params=draft_params,
        dtype=dtype,
        # Resident bytes of the tree actually held in memory (after the
        # bf16 load cast / with int8 + scales), not the on-disk figure.
        params_bytes=qz.params_nbytes(params),
        training_statistics_uri=str(spec.get("training_statistics_uri") or ""),
        training_schema_uri=str(spec.get("training_schema_uri") or ""),
        uri=os.path.abspath(uri),
        aot=aot,
    )
