"""Model export/load: self-contained serving payloads.

The Model artifact payload (what Pusher ships, what InfraValidator/
BulkInferrer/serving load) is fully self-contained:

    <uri>/checkpoint/        orbax params checkpoint
    <uri>/module_copy.py     user module (defines build_model)
    <uri>/transform_graph/   copy of the resolved TransformGraph (optional)
    <uri>/model_spec.json    hyperparameters, feature names, format version

Loading reconstructs ``predict(raw_batch)`` = transform host stage (numpy
string ops) → one jitted on-chip function (numeric transform fused with the
model forward pass) — preprocessing and model co-located on TPU, the
``jit_compile=True`` serving/bulk-inference story from BASELINE, with zero
training/serving skew because the TransformGraph is the same artifact the
Trainer's input data was materialized through.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from tpu_pipelines.transform.graph import TransformGraph
from tpu_pipelines.utils.module_loader import load_fn, load_module

SPEC_FILE = "model_spec.json"
MODULE_COPY = "module_copy.py"
CHECKPOINT_DIR = "checkpoint"
TRANSFORM_DIR = "transform_graph"
FORMAT_VERSION = "tpu-pipelines-model/v1"


def export_model(
    *,
    serving_model_dir: str,
    params: Any,
    module_file: str,
    hyperparameters: Optional[Dict[str, Any]] = None,
    transform_graph_uri: str = "",
    extra_spec: Optional[Dict[str, Any]] = None,
) -> str:
    """Write a self-contained model payload; returns the dir.

    Multi-host safe: the orbax save is a collective every process joins
    (each writes the param shards it owns into the shared dir); all other
    writes are plain files and happen on process 0 only.
    """
    os.makedirs(serving_model_dir, exist_ok=True)
    import orbax.checkpoint as ocp

    primary = jax.process_index() == 0
    ckpt_path = os.path.abspath(os.path.join(serving_model_dir, CHECKPOINT_DIR))
    if primary and os.path.exists(ckpt_path):
        shutil.rmtree(ckpt_path)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("export_model:pre_save")
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(ckpt_path, params)

    if primary:
        shutil.copyfile(
            module_file, os.path.join(serving_model_dir, MODULE_COPY)
        )
        if transform_graph_uri:
            dst = os.path.join(serving_model_dir, TRANSFORM_DIR)
            if os.path.exists(dst):
                shutil.rmtree(dst)
            shutil.copytree(transform_graph_uri, dst)
        spec = {
            "format": FORMAT_VERSION,
            "hyperparameters": hyperparameters or {},
            "has_transform": bool(transform_graph_uri),
            **(extra_spec or {}),
        }
        with open(os.path.join(serving_model_dir, SPEC_FILE), "w") as f:
            json.dump(spec, f, indent=2, sort_keys=True, default=str)
    return serving_model_dir


@dataclasses.dataclass
class LoadedModel:
    params: Any
    model: Any                       # flax Module from build_model
    spec: Dict[str, Any]
    transform: Optional[TransformGraph]
    predict: Callable[[Dict[str, np.ndarray]], Any]
    predict_transformed: Callable[[Dict[str, np.ndarray]], Any]
    # Autoregressive generation (seq2seq models): present when the exported
    # module defines ``make_generate_fn(model, params, hyperparameters)``
    # returning a callable over TRANSFORMED feature batches (e.g. a jitted
    # T5 beam/greedy decode from models/t5.py).  ``generate`` takes raw
    # batches (host transform applied first); None for non-seq2seq models.
    generate: Optional[Callable[[Dict[str, np.ndarray]], Any]] = None
    # The two halves of `predict`, exposed for exporters (serving/
    # saved_model.py): host string stage (numpy, identity when no transform)
    # and the single jitted device computation (numeric transform fused with
    # the forward pass).
    host_preprocess: Callable[[Dict[str, np.ndarray]], Dict[str, np.ndarray]] = None
    device_predict: Callable[[Dict[str, Any]], Any] = None


def load_exported_model(uri: str) -> LoadedModel:
    """Reload an exported payload into a ready predict function."""
    with open(os.path.join(uri, SPEC_FILE)) as f:
        spec = json.load(f)
    if spec.get("format") != FORMAT_VERSION:
        raise ValueError(
            f"model at {uri!r} has format {spec.get('format')!r}, "
            f"expected {FORMAT_VERSION}"
        )
    module_copy = os.path.join(uri, MODULE_COPY)
    module = load_module(module_copy)
    build_model = load_fn(module_copy, "build_model")
    model = build_model(spec.get("hyperparameters", {}))
    # Optional module hook for models whose __call__ is not dict-of-features
    # (e.g. image models taking one array): apply_fn(model, params, batch).
    apply_fn = getattr(
        module, "apply_fn",
        lambda model, params, batch: model.apply({"params": params}, batch),
    )

    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        params = ckptr.restore(
            os.path.abspath(os.path.join(uri, CHECKPOINT_DIR))
        )

    transform = None
    if spec.get("has_transform"):
        transform = TransformGraph.load(os.path.join(uri, TRANSFORM_DIR))

    @jax.jit
    def _forward(transformed: Dict[str, Any]):
        return apply_fn(model, params, transformed)

    if transform is not None:
        host_fn, device_fn, _ = transform.split_host_device()

        @jax.jit
        def _transform_and_forward(iface: Dict[str, Any]):
            # Numeric transform + model forward in ONE compiled computation.
            return apply_fn(model, params, device_fn(iface))

        def predict(raw_batch: Dict[str, np.ndarray]):
            return _transform_and_forward(host_fn(raw_batch))

        host_preprocess, device_predict = host_fn, _transform_and_forward
    else:
        def predict(raw_batch: Dict[str, np.ndarray]):
            return _forward(raw_batch)

        host_preprocess, device_predict = (lambda b: b), _forward

    generate = None
    gen_builder = getattr(module, "make_generate_fn", None)
    if gen_builder is not None:
        device_generate = gen_builder(
            model, params, spec.get("hyperparameters", {})
        )
        if transform is not None:
            _transform_dev = jax.jit(device_fn)

            def generate(raw_batch: Dict[str, np.ndarray]):
                return device_generate(_transform_dev(host_fn(raw_batch)))
        else:
            generate = device_generate

    return LoadedModel(
        params=params,
        model=model,
        spec=spec,
        transform=transform,
        predict=predict,
        predict_transformed=_forward,
        host_preprocess=host_preprocess,
        device_predict=device_predict,
        generate=generate,
    )
