"""FnArgs: everything the Trainer hands to user training code.

Mirrors TFX's ``tfx.components.trainer.fn_args_utils.FnArgs`` so workshop
``run_fn``s port directly: data uris in, model dirs out, plus the mesh and
transform handles that replace the tf.distribute strategy.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


@dataclasses.dataclass
class FnArgs:
    # Data (Examples artifact uris; transformed when a Transform ran).
    train_examples_uri: str = ""
    eval_examples_uri: str = ""
    # Resolved TransformGraph artifact uri ("" when no Transform in the DAG).
    transform_graph_uri: str = ""
    schema_uri: str = ""
    # Output locations.
    serving_model_dir: str = ""      # final export (Model artifact payload)
    model_run_dir: str = ""          # checkpoints, logs, profiles
    # Budgets.
    train_steps: int = 1000
    eval_steps: int = 0
    # Hyperparameters from the Tuner (or user-set); free-form.
    hyperparameters: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Mesh requested by the component (data/model/seq sizes).
    mesh_config: Dict[str, int] = dataclasses.field(default_factory=dict)
    # Anything else the pipeline author wants to thread through.
    custom_config: Dict[str, Any] = dataclasses.field(default_factory=dict)


def make_fn_args(
    *,
    examples_uri: str,
    transform_graph_uri: str,
    schema_uri: str,
    serving_model_dir: str,
    model_run_dir: str,
    hyperparameters: Dict[str, Any],
    train_steps: int,
    eval_steps: int,
    mesh: Optional[Dict[str, int]] = None,
    custom_config: Optional[Dict[str, Any]] = None,
) -> "FnArgs":
    """The one place FnArgs fields are assembled — every caller (Trainer,
    Tuner in-process/subprocess/shard) routes here so the run_fn contract
    cannot drift between execution modes."""
    return FnArgs(
        train_examples_uri=examples_uri,
        eval_examples_uri=examples_uri,
        transform_graph_uri=transform_graph_uri,
        schema_uri=schema_uri,
        serving_model_dir=serving_model_dir,
        model_run_dir=model_run_dir,
        train_steps=train_steps,
        eval_steps=eval_steps,
        hyperparameters=hyperparameters,
        mesh_config=dict(mesh or {}),
        custom_config=dict(custom_config or {}),
    )


def ctx_data_uris(ctx) -> Dict[str, str]:
    """Resolve the (examples, optional transform_graph/schema) input uris
    from an executor context — shared by Trainer and Tuner."""
    return {
        "examples_uri": ctx.input("examples").uri,
        "transform_graph_uri": (
            ctx.input("transform_graph").uri
            if ctx.inputs.get("transform_graph") else ""
        ),
        "schema_uri": (
            ctx.input("schema").uri if ctx.inputs.get("schema") else ""
        ),
    }


def resolve_fn_args(
    ctx,
    *,
    serving_model_dir: str,
    model_run_dir: str,
    hyperparameters: Dict[str, Any],
    train_steps: int,
    eval_steps: int,
    mesh: Optional[Dict[str, int]] = None,
    custom_config: Optional[Dict[str, Any]] = None,
) -> "FnArgs":
    """Build FnArgs from an executor context's resolved artifacts."""
    return make_fn_args(
        **ctx_data_uris(ctx),
        serving_model_dir=serving_model_dir,
        model_run_dir=model_run_dir,
        train_steps=train_steps,
        eval_steps=eval_steps,
        hyperparameters=hyperparameters,
        mesh=mesh,
        custom_config=custom_config,
    )


@dataclasses.dataclass
class TrainResult:
    """What run_fn reports back; recorded as execution properties."""

    final_metrics: Dict[str, float] = dataclasses.field(default_factory=dict)
    examples_per_sec: float = 0.0
    examples_per_sec_per_chip: float = 0.0
    # Median examples/sec/chip over device-sync-anchored step windows
    # (TrainLoopConfig.anchor_every > 0); 0.0 when anchoring was off or the
    # run was too short for a full window.  On platforms where host clocks
    # can run ahead of device execution this is the primary throughput
    # figure; examples_per_sec_per_chip (whole-run, end-anchored) is the
    # secondary.
    anchored_examples_per_sec_per_chip: float = 0.0
    anchor_windows: int = 0
    steps_completed: int = 0
    resumed_from_step: int = 0
    # Productive fraction of job wall-clock.  Source "ml_goodput_measurement"
    # = the real badput algebra (init/prep/compile count against it); source
    # "host_input_wait_proxy" = 1 - host-input-wait/elapsed, a lower bound on
    # device goodput (1.0 when the run was too short to measure).
    goodput: float = 0.0
    goodput_source: str = "host_input_wait_proxy"
    # Goodput over the post-compile window only (1 - input-wait/elapsed,
    # both measured after step 1 retires).  At bench scale the strict
    # figure above is dominated by one-time compile; this one is the
    # steady-state number a long run would converge to.
    goodput_post_compile: float = 0.0
    # {badput_kind: fraction of job wall-clock}, e.g. {"tpu_initialization":
    # 0.02, "training_prep": 0.01, "data_loading_sync": 0.05, "other": ...}.
    badput: Dict[str, float] = dataclasses.field(default_factory=dict)
    # XLA's own per-step FLOP count for the train step
    # (TrainLoopConfig.collect_cost_analysis=True) — the auditable
    # cross-check for analytic MFU numerators.  Source "compiled" = cost
    # analysis of the optimized executable; "lowered" = HLO cost analysis
    # of the unoptimized module (fallback when the backend's compiled
    # analysis is unavailable).  None when collection was off or failed.
    cost_analysis_flops_per_step: Optional[float] = None
    cost_analysis_source: str = ""
    # Effective device-resident multi-step window the loop ran with
    # (TrainLoopConfig.window_steps / TPP_WINDOW_STEPS, default log_every);
    # 1 = the per-step host loop.
    window_steps: int = 1
    # Elastic-resume replay: steps this run re-executed because the
    # previous run was interrupted past its last durable window (the
    # window_progress marker outran the restored checkpoint).  0 for
    # uninterrupted runs.  Replayed examples are accounted as lost work,
    # never as fresh progress — the no-double-counting contract asserted
    # in tests/test_multichip_window.py.
    replayed_steps: int = 0
    # Gradient-exchange mode the loop ran with: "" = implicit GSPMD,
    # "psum_bucketed" = chunked in-scan psums, "ordered" = fixed-block
    # mesh-size-invariant reduction (TrainLoopConfig.dp_collective).
    dp_collective: str = ""
    # Model-FLOPs utilization: cost-analysis FLOPs/step x post-warmup
    # steps / attributed device-compute seconds / (peak chip FLOPs x
    # chips).  Needs collect_cost_analysis=True and a known peak
    # (TrainLoopConfig.peak_flops_per_chip / TPP_PEAK_FLOPS / device-kind
    # table); None otherwise.  Also published live as the train_mfu gauge.
    mfu: Optional[float] = None
    # XLA backend compiles observed AFTER the first window retired —
    # the training twin of serving_aot_compiles_after_warm_total.  Every
    # one is a mid-run recompile stall; steady state is 0.
    compiles_after_warm: int = 0
    # Post-warmup windowed-loop wall-clock attributed per phase
    # (infeed_wait | device_compute | device_collective | host; the
    # phases of each window sum to its wall-clock).  Empty on the
    # per-step (window_steps<=1) path, which cannot separate device from
    # host time without a per-step sync.
    window_phase_seconds: Dict[str, float] = dataclasses.field(
        default_factory=dict
    )
