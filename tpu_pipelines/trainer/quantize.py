"""Weight quantization for serving payloads (the Rewriter's math).

AQT-style post-training weight-only quantization: each large floating
weight tensor is stored as an int8 ``qvalue`` plus a per-channel float32
``scale`` (symmetric, first-axis channels), and the serving loader
dequantizes INSIDE the jitted forward pass — ``q.astype(f32) * s`` fused
into the computation by XLA — so the resident params tree stays int8
(4x smaller) and ops that touch a slice of a tensor (embedding gathers)
read a quarter of the bytes.  When the installed ``aqtp`` package is
importable its calibrated quantizer produces the (qvalue, scale) pair;
otherwise a numerically-identical symmetric max/127 fallback does.

Representation: a quantized leaf is replaced by a plain dict subtree

    {"__aqt_int8_q__": int8[...], "__aqt_int8_s__": float32[d0,1,...]}

which round-trips through orbax (a pytree of arrays), keeps the payload
self-contained, and needs no aqt import at load time.  Per-FIRST-axis
scales are exact under both canonical uses: for an embedding table
``[V, D]`` each row carries its own scale (the gathered rows dequantize
independently), and for a matmul weight ``[D, H]`` a per-input-channel
scale is algebraically a rescaling of the input — quality comparable to
per-output-channel at identical storage.

Small or 0/1-D leaves (biases, norms, scalars) stay float: quantizing
them saves nothing and costs quality (standard weight-only practice).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

log = logging.getLogger("tpu_pipelines.trainer.quantize")

QUANT_Q = "__aqt_int8_q__"
QUANT_S = "__aqt_int8_s__"

# The three serving dtypes a payload spec can declare (export.py records
# them; the Rewriter emits one payload per variant name).
DTYPE_FLOAT32 = "float32"
DTYPE_BFLOAT16 = "bfloat16"
DTYPE_AQT_INT8 = "aqt_int8"

# Leaves smaller than this many elements stay float (quantization saves
# ~3 bytes/element; below a few KiB the scale tensor + quality cost win).
DEFAULT_MIN_QUANT_SIZE = 4096


def is_quantized_leaf(node: Any) -> bool:
    return isinstance(node, dict) and QUANT_Q in node and QUANT_S in node


def _is_float_dtype(dtype: Any) -> bool:
    """True for numpy floats AND the ml_dtypes extension floats (bfloat16
    has numpy kind 'V', so ``np.issubdtype(..., np.floating)`` misses it)."""
    if dtype is None:
        return False
    dt = np.dtype(dtype)
    return np.issubdtype(dt, np.floating) or dt.name in (
        "bfloat16", "float16"
    )


def _quantize_array(w) -> Tuple[Any, Any]:
    """(qvalue int8, scale f32) with per-first-axis symmetric scales.

    Prefers the installed aqt calibrated quantizer; the fallback is the
    same symmetric max/127 math (dequant ``q * s`` in both cases).
    """
    import jax.numpy as jnp

    axes = tuple(range(1, np.ndim(w)))
    try:
        from aqt.jax.v2 import aqt_quantizer

        q = aqt_quantizer.quantizer_make(8, initialize_calibration=True)
        qt, _ = q.quant(jnp.asarray(w), calibration_axes=axes)
        return (
            jnp.asarray(qt.qvalue, jnp.int8),
            jnp.asarray(qt.scale[0], jnp.float32),
        )
    except Exception as e:  # noqa: BLE001 — aqt drift: identical fallback
        log.debug("aqt quantizer unavailable (%s); symmetric fallback", e)
    w = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=axes, keepdims=True) if axes else (
        jnp.abs(w)
    )
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    qvalue = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return qvalue, scale


def _should_quantize(leaf: Any, min_size: int) -> bool:
    if not _is_float_dtype(getattr(leaf, "dtype", None)):
        return False
    return np.ndim(leaf) >= 2 and int(np.size(leaf)) >= int(min_size)


def quantize_params(
    params: Any, min_size: int = DEFAULT_MIN_QUANT_SIZE
) -> Tuple[Any, Dict[str, Any]]:
    """Quantize eligible leaves of a (nested-dict) params tree.

    Returns ``(tree, report)``: the tree with eligible leaves replaced by
    quantized subtrees, and a JSON-native report (per-leaf path/shape/
    bytes, totals) the Rewriter records on its execution.
    """
    quantized: List[Dict[str, Any]] = []

    def walk(node: Any, path: str) -> Any:
        if isinstance(node, dict):
            return {
                k: walk(v, f"{path}/{k}" if path else str(k))
                for k, v in node.items()
            }
        if _should_quantize(node, min_size):
            qvalue, scale = _quantize_array(node)
            quantized.append({
                "path": path,
                "shape": [int(d) for d in np.shape(node)],
                "bytes_float": int(np.size(node)) * np.dtype(
                    getattr(node, "dtype", np.float32)
                ).itemsize,
                "bytes_int8": int(np.size(qvalue)) + int(
                    np.size(scale)
                ) * 4,
            })
            return {QUANT_Q: qvalue, QUANT_S: scale}
        return node

    tree = walk(params, "")
    report = {
        "quantized_leaves": quantized,
        "num_quantized": len(quantized),
        "min_quant_size": int(min_size),
    }
    return tree, report


def dequantize_params(tree: Any, dtype: Optional[Any] = None) -> Any:
    """Replace quantized subtrees with dense ``q * s`` arrays.

    jnp ops throughout, so calling this INSIDE a jitted function fuses
    the dequant into the consumer (XLA sinks the convert through gathers
    — the int8 bandwidth win survives); calling it outside jit gives a
    concrete dense tree (used by parity tests).
    """
    import jax.numpy as jnp

    target = dtype or jnp.float32

    def walk(node: Any) -> Any:
        if is_quantized_leaf(node):
            return (
                node[QUANT_Q].astype(target) * node[QUANT_S].astype(target)
            )
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return node

    return walk(tree)


def tree_is_quantized(tree: Any) -> bool:
    if is_quantized_leaf(tree):
        return True
    if isinstance(tree, dict):
        return any(tree_is_quantized(v) for v in tree.values())
    return False


def cast_params(params: Any, dtype: Any) -> Any:
    """Cast every floating leaf to ``dtype`` (ints/quantized untouched) —
    the one-time load cast behind the bf16 fast path."""

    def walk(node: Any) -> Any:
        if is_quantized_leaf(node):
            return node
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if _is_float_dtype(getattr(node, "dtype", None)):
            return node.astype(dtype)
        return node

    return walk(params)


def params_nbytes(tree: Any) -> int:
    """Resident bytes of a params tree (quantized subtrees count their
    int8 + scale storage, which is the point)."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None:
            nbytes = int(np.size(leaf)) * np.dtype(
                getattr(leaf, "dtype", np.float64)
            ).itemsize
        total += int(nbytes)
    return total


def infer_dtype(tree: Any) -> str:
    """Serving-dtype string for a params tree: quantized markers win,
    else the widest floating leaf dtype name, else float32."""
    if tree_is_quantized(tree):
        return DTYPE_AQT_INT8
    import jax

    names = set()
    for leaf in jax.tree_util.tree_leaves(tree):
        dtype = getattr(leaf, "dtype", None)
        if _is_float_dtype(dtype):
            names.add(np.dtype(dtype).name)
    if names == {"bfloat16"}:
        return DTYPE_BFLOAT16
    return DTYPE_FLOAT32 if not names or "float32" in names or (
        "float64" in names
    ) else sorted(names)[0]
