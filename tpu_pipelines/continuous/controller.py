"""ContinuousController: the long-lived span -> retrain -> deploy loop.

One controller = one continuously-retrained service (docs/CONTINUOUS.md):

    poll {SPAN}/{VERSION} pattern          (SpanWatcher)
      -> per-span ingest pipeline          (new/re-delivered spans only;
                                            the execution cache IS the
                                            incremental planner — an
                                            unchanged span costs a
                                            fingerprint, not a recompute)
      -> window pipeline                   (RollingWindowResolver ->
                                            SpanWindow/WindowStatisticsMerger
                                            -> Trainer -> Evaluator ->
                                            Pusher(serving_push_url))
      -> deploy observation                (a fleet auto-rollback inside
                                            the probation window un-blesses
                                            the triggering model in the
                                            metadata store)

Crash safety: every run the controller launches is an ordinary
LocalDagRunner run — traced (PR 4), metered (PR 5), retried under the
pipeline's classified policies (PR 7) — and the controller records which
pipeline it had in flight (``atomic_write_json`` state), so a restart
resumes the interrupted run via ``resume_from`` (PR 2) instead of
re-executing settled nodes.  Watcher acks persist AFTER the span run
succeeds: the loop is at-least-once, idempotent through the cache.

Stopping: ``run(stop_event)`` drains — the current pipeline run finishes
(its own deadlines/retry policies bound it; the TPP111 lint rule warns
when a handed pipeline carries neither), no new run starts, state is
persisted.  The CLI (``tpp continuous``) maps SIGINT/SIGTERM onto the
stop event; a second signal aborts hard.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import threading
import time
import urllib.parse
import urllib.request
from typing import Any, Callable, Dict, List, Optional

from tpu_pipelines.continuous.watcher import SpanDelivery, SpanWatcher
from tpu_pipelines.dsl.pipeline import Pipeline
from tpu_pipelines.metadata.types import EventType
from tpu_pipelines.robustness import atomic_write_json, load_json_tolerant

log = logging.getLogger("tpu_pipelines.continuous")


@dataclasses.dataclass
class ContinuousConfig:
    """Everything a controller needs; built by the user's
    ``create_continuous()`` (the ``tpp continuous`` module contract).

    The two pipeline factories MUST share one metadata store: the window
    pipeline's RollingWindowResolver reads the span pipeline's artifacts
    through it (``source_pipeline`` scoping).  The controller verifies
    this on first use and refuses otherwise.
    """

    # {SPAN} (optionally {VERSION}) input pattern the watcher polls.
    input_pattern: str
    # Per-span ingest pipeline: ExampleGen(span=..., version=...) ->
    # StatisticsGen(save_accumulators=True) [-> Transform ...].
    make_span_pipeline: Callable[[int, Optional[int]], Pipeline]
    # Window pipeline: RollingWindowResolver -> SpanWindow/
    # WindowStatisticsMerger -> Trainer -> Evaluator -> Pusher.
    make_window_pipeline: Callable[[], Pipeline]
    poll_interval_s: float = 10.0
    # Serving base URL for deploy observation, e.g.
    # "http://127.0.0.1:8501/v1/models/taxi" (the Pusher push-URL).  ""
    # disables rollback observation.
    serving_url: str = ""
    # How long after a deploy to watch for the fleet's auto-rollback; <0 =
    # the fleet's own probation default (TPP_SWAP_PROBATION_S, 120 s).
    probation_watch_s: float = -1.0
    probation_poll_s: float = 1.0
    # Directory for controller state (watcher acks, in-flight run marker);
    # "" = in-memory only (no resume across controller restarts).
    state_dir: str = ""
    # Lint gate level for handed pipelines ("error"/"warn"/None=env
    # TPP_LINT).  Pipelines are analyzed with the continuous flag, arming
    # TPP111 (unbounded nodes wedge the loop).
    lint: Optional[str] = None
    # Metrics registry for the controller gauges (None = process default).
    registry: Any = None
    # Live drift plane (observability/drift.py): when True, a drift
    # breach — handed in via :meth:`ContinuousController.notify_drift`
    # (the sampler's on_alert / SLO monitor's on_breach target) or read
    # off the serving /metrics scrape between ticks — marks the window
    # dirty and triggers an out-of-cadence retrain
    # (``continuous_drift_triggered_runs_total``).
    drift_retrain: bool = True
    # A training/serving SKEW breach at/above this distance arms strict
    # ExampleValidator on the next window run (fail_on_anomalies=True,
    # and the batch skew comparator armed at this threshold when the
    # pipeline left it off) — the live plane escalating the batch gate.
    # 0 disables the escalation.
    skew_strict_threshold: float = 0.0


class ContinuousController:
    def __init__(self, cfg: ContinuousConfig):
        import os

        self.cfg = cfg
        state_path = ""
        self._pending_path = ""
        if cfg.state_dir:
            os.makedirs(cfg.state_dir, exist_ok=True)
            state_path = os.path.join(cfg.state_dir, "watcher.json")
            self._pending_path = os.path.join(cfg.state_dir, "pending.json")
        self.watcher = SpanWatcher(cfg.input_pattern, state_path=state_path)
        self._linted: set = set()
        # A failed window run retries next tick.  A persisted pending
        # marker means the prior controller died mid-run (or left a
        # failed window behind): start dirty so the interrupted retrain
        # resumes on the first tick instead of waiting for the next span.
        self._window_dirty = bool(self._load_pending())
        self._metadata_path: Optional[str] = None
        self.last_deploy: Optional[Dict[str, Any]] = None
        self.last_iteration: Dict[str, Any] = {}
        self._iterations = 0
        # Previous window run's MetricsHistory headline: the baseline the
        # next retrain's telemetry is compared against (ring-durable
        # telemetry is what makes the comparison survive restarts).
        self._last_window_telemetry: Optional[Dict[str, Any]] = None
        # Drift-breach intake (observability/drift.py): callbacks land in
        # _drift_pending under the lock; consumed breaches move to
        # _drift_evidence until a window run succeeds and records them —
        # a failed retrain keeps the evidence armed for the retry tick.
        self._drift_lock = threading.Lock()
        self._drift_pending: List[Dict[str, Any]] = []
        self._drift_evidence: List[Dict[str, Any]] = []
        self._last_drift_alerts: Optional[float] = None
        self._skew_strict = False
        self._init_metrics(cfg.registry)

    # ------------------------------------------------------------- metrics

    def _init_metrics(self, registry) -> None:
        if registry is None:
            from tpu_pipelines.observability.metrics import default_registry

            registry = default_registry()
        self.registry = registry
        self._g_seen = registry.gauge(
            "continuous_spans_seen",
            "Spans the watcher has acknowledged (processed at least once).",
        )
        self._c_processed = registry.counter(
            "continuous_spans_processed_total",
            "Span deliveries whose ingest pipeline run succeeded "
            "(version re-deliveries re-count).",
        )
        self._c_runs = registry.counter(
            "continuous_runs_total",
            "Pipeline runs launched by the controller, by kind and "
            "outcome.",
            labels=("kind", "outcome"),
        )
        self._g_work_saved = registry.gauge(
            "continuous_incremental_work_saved",
            "Last active iteration's cache-satisfied fraction of node "
            "executions (1.0 = nothing recomputed).",
        )
        self._c_deploys = registry.counter(
            "continuous_deploys_total",
            "Blessed models deployed into the serving fleet (push-URL "
            "reload notified).",
        )
        self._c_rollbacks = registry.counter(
            "continuous_rollbacks_observed_total",
            "Fleet auto-rollbacks observed inside the probation window; "
            "each un-blessed the triggering model in the metadata store.",
        )
        self._c_iterations = registry.counter(
            "continuous_iterations_total",
            "Controller loop iterations, by activity.",
            labels=("activity",),
        )
        self._c_drift_runs = registry.counter(
            "continuous_drift_triggered_runs_total",
            "Out-of-cadence window retrains triggered by a live drift/"
            "skew breach (observability/drift.py), evidence recorded on "
            "the run's drift_evidence context.",
        )

    # ---------------------------------------------------------------- lint

    def _lint_once(self, pipeline: Pipeline) -> None:
        """Analyze a handed pipeline (continuous flag armed -> TPP111);
        gate at cfg.lint / env TPP_LINT level, log findings otherwise.
        Once per pipeline name — factories return fresh equivalent
        objects each call."""
        if pipeline.name in self._linted:
            return
        from tpu_pipelines.analysis import (
            analyze_pipeline,
            gate_or_raise,
            resolve_lint_level,
        )

        findings = analyze_pipeline(pipeline, continuous=True)
        level = resolve_lint_level(self.cfg.lint)
        if level:
            gate_or_raise(
                findings, level,
                f"continuous controller ({pipeline.name})",
            )
        for f in findings:
            log.warning("lint: %s", f.format())
        self._linted.add(pipeline.name)

    # --------------------------------------------------------------- drift

    def notify_drift(self, breach: Dict[str, Any]) -> None:
        """Drift-breach intake — the callback target for a co-located
        ``TrafficSampler(on_alert=...)`` or ``SLOMonitor(on_breach=...)``.
        Thread-safe; non-drift breaches (latency/error SLOs are the
        fleet's probation-rollback business) are ignored.  Consumed on
        the next tick: the window goes dirty and the retrain counts in
        ``continuous_drift_triggered_runs_total``."""
        if breach.get("slo") != "drift":
            return
        with self._drift_lock:
            self._drift_pending.append(dict(breach))

    def _metrics_url(self) -> str:
        parts = urllib.parse.urlsplit(self.cfg.serving_url)
        return urllib.parse.urlunsplit(
            (parts.scheme, parts.netloc, "/metrics", "", "")
        )

    def _poll_drift(self) -> Optional[Dict[str, Any]]:
        """Scrape-side breach detection for a fleet in another process:
        an increase in ``serving_drift_alerts_total`` since the last tick
        synthesizes one breach (the first scrape only baselines — alerts
        predating this controller are not its retrains to run)."""
        if not self.cfg.serving_url:
            return None
        from tpu_pipelines.observability.drift import parse_drift_scrape

        try:
            with urllib.request.urlopen(
                self._metrics_url(), timeout=5
            ) as r:
                text = r.read().decode("utf-8", "replace")
        except Exception as e:  # noqa: BLE001 — serving briefly unreachable
            log.debug("drift metrics poll failed: %s", e)
            return None
        report = parse_drift_scrape(text)
        alerts = float(report.get("alerts_total") or 0.0)
        prev, self._last_drift_alerts = self._last_drift_alerts, alerts
        if prev is None or alerts <= prev:
            return None
        return {
            "slo": "drift",
            "source": "scrape",
            "alerts_delta": alerts - prev,
            "max_distance": report.get("max_distance", 0.0),
            "max_skew": report.get("max_skew", 0.0),
        }

    @staticmethod
    def _breach_skew(breach: Dict[str, Any]) -> float:
        """The training/serving-skew distance a breach carries (0 for a
        pure window-over-window drift breach)."""
        if "max_skew" in breach:
            return float(breach.get("max_skew") or 0.0)
        if str(breach.get("kind", "")).startswith("skew"):
            return float(breach.get("distance") or 0.0)
        return 0.0

    def _take_drift(self) -> List[Dict[str, Any]]:
        with self._drift_lock:
            breaches, self._drift_pending = self._drift_pending, []
        scraped = self._poll_drift()
        if scraped is not None:
            breaches.append(scraped)
        return breaches

    def _arm_strict_validation(self, pipeline: Pipeline) -> None:
        """Skew escalation: force every ExampleValidator in the window
        pipeline strict (fail_on_anomalies), arming the batch skew
        comparator at the controller's threshold when the pipeline left
        both skew knobs off — the next deploy re-earns its blessing
        against the baseline the live plane saw it violate."""
        for comp in pipeline.components:
            if type(comp).__name__ != "ExampleValidator":
                continue
            comp.exec_properties["fail_on_anomalies"] = True
            if not (
                comp.exec_properties.get("skew_linf_threshold")
                or comp.exec_properties.get("skew_js_threshold")
            ):
                comp.exec_properties["skew_linf_threshold"] = (
                    self.cfg.skew_strict_threshold
                )
            log.warning(
                "continuous: strict validation armed on %s (live skew "
                "breach >= %.3f)", comp.id, self.cfg.skew_strict_threshold,
            )

    def _record_drift_evidence(
        self, run_id: str, breaches: List[Dict[str, Any]]
    ) -> None:
        """Attach the live windows' snapshot scores to the triggered run
        in the shared metadata store: a ``drift_evidence`` context named
        after the run id, next to its pipeline_run context — the audit
        trail answering WHY an out-of-cadence retrain happened."""
        if self._metadata_path is None:
            return
        from tpu_pipelines.metadata import open_store
        from tpu_pipelines.metadata.types import Context

        try:
            store = open_store(self._metadata_path)
            try:
                store.put_context(Context(
                    type_name="drift_evidence",
                    name=run_id,
                    properties={
                        "triggered_run": run_id,
                        "breaches": breaches,
                    },
                ))
            finally:
                store.close()
        except Exception as e:  # noqa: BLE001 — evidence is best-effort
            log.warning(
                "could not record drift evidence for %s: %s", run_id, e
            )

    # ------------------------------------------------------------ run loop

    def run(
        self,
        stop_event: Optional[threading.Event] = None,
        max_iterations: int = 0,
    ) -> None:
        """The controller loop; returns when ``stop_event`` is set (after
        draining the in-flight iteration) or ``max_iterations`` elapsed."""
        stop = stop_event if stop_event is not None else threading.Event()
        done = 0
        while not stop.is_set():
            self.run_once(stop)
            done += 1
            if max_iterations and done >= max_iterations:
                break
            if stop.wait(self.cfg.poll_interval_s):
                break
        log.info(
            "continuous controller stopped after %d iteration(s) "
            "(drained)", done,
        )

    def run_once(self, stop: Optional[threading.Event] = None) -> Dict:
        """One watch -> ingest -> retrain -> deploy-observe iteration."""
        stop = stop if stop is not None else threading.Event()
        self._iterations += 1
        t0 = time.monotonic()
        deliveries = self.watcher.poll()
        statuses: List[str] = []
        processed = 0
        for d in deliveries:
            if stop.is_set():
                break  # drain: no new run starts after the stop signal
            result = self._run_pipeline(
                self.cfg.make_span_pipeline(d.span, d.version),
                kind="span", delivery=d,
            )
            if result is not None and result.succeeded:
                self.watcher.ack([d])
                processed += 1
                self._c_processed.inc()
                statuses.extend(
                    nr.status for nr in result.nodes.values()
                )
                self._window_dirty = True
        self._g_seen.set(len(self.watcher.seen_spans()))

        # Live drift plane: a breach (callback or scrape delta) marks the
        # window dirty exactly like a fresh span would — the retrain is
        # the same window pipeline, just out of cadence.
        if self.cfg.drift_retrain and not stop.is_set():
            fresh = self._take_drift()
            if fresh:
                self._drift_evidence.extend(fresh)
                self._window_dirty = True
                if self.cfg.skew_strict_threshold > 0 and any(
                    self._breach_skew(b) >= self.cfg.skew_strict_threshold
                    for b in fresh
                ):
                    self._skew_strict = True
                log.warning(
                    "continuous: %d drift breach(es) consumed -> "
                    "out-of-cadence retrain armed%s",
                    len(fresh),
                    " (strict validation)" if self._skew_strict else "",
                )

        deployed: Optional[Dict[str, Any]] = None
        window_size = 0
        drift_recorded = 0
        telemetry: Optional[Dict[str, Any]] = None
        telemetry_flags: List[str] = []
        if (
            self._window_dirty
            and not stop.is_set()
            and self.watcher.seen_spans()
        ):
            window_pipeline = self.cfg.make_window_pipeline()
            if self._skew_strict:
                self._arm_strict_validation(window_pipeline)
            result = self._run_pipeline(window_pipeline, kind="window")
            if result is not None and result.succeeded:
                self._window_dirty = False
                if self._drift_evidence:
                    self._c_drift_runs.inc()
                    self._record_drift_evidence(
                        result.run_id, self._drift_evidence
                    )
                    drift_recorded = len(self._drift_evidence)
                    self._drift_evidence = []
                    self._skew_strict = False
                statuses.extend(
                    nr.status for nr in result.nodes.values()
                )
                deployed = self._detect_deploy(result)
                window_size = self._window_span_count(result)
                telemetry, telemetry_flags = self._window_telemetry(
                    window_pipeline.pipeline_root, result.run_id
                )
            else:
                # Survive a controller restart too: the marker re-arms
                # _window_dirty in __init__ (resume/caching make the
                # retried run cheap).
                self._store_pending({"window_dirty": True})

        active = bool(processed or deployed or statuses)
        self._c_iterations.labels("active" if active else "idle").inc()
        executed = statuses.count("COMPLETE")
        cached = statuses.count("CACHED")
        # Incremental work saved: of the spans the window retrained over,
        # the fraction whose ingest+stats were REUSED (no run launched, or
        # the run cache-hit) rather than recomputed this iteration.  A
        # cold bootstrap reads 0.0; steady state with window K reads
        # (K-1)/K.  Falls back to the cache-satisfied node fraction when
        # no window ran.
        if window_size:
            work_saved = max(0.0, 1.0 - processed / window_size)
        elif cached + executed:
            work_saved = cached / (cached + executed)
        else:
            work_saved = None
        if work_saved is not None:
            self._g_work_saved.set(work_saved)
        summary = {
            "iteration": self._iterations,
            "deliveries": [d.key for d in deliveries],
            "spans_processed": processed,
            "nodes_executed": executed,
            "nodes_cached": cached,
            "work_saved_ratio": (
                round(work_saved, 4) if work_saved is not None else None
            ),
            "deployed": deployed,
            "wall_s": round(time.monotonic() - t0, 3),
        }
        if telemetry is not None:
            summary["train_telemetry"] = telemetry
            if telemetry_flags:
                summary["train_telemetry_regressions"] = telemetry_flags
        if drift_recorded:
            summary["drift_triggered"] = True
            summary["drift_breaches"] = drift_recorded
        if deployed is not None:
            self._c_deploys.inc()
            deployed["deploy_latency_s"] = summary["wall_s"]
            self.last_deploy = deployed
            rolled = self._observe_probation(deployed, stop)
            summary["rollback_observed"] = rolled
        self.last_iteration = summary
        log.info("continuous iteration: %s", json.dumps(summary))
        return summary

    # ----------------------------------------------------------- pipelines

    def _run_pipeline(self, pipeline: Pipeline, kind: str,
                      delivery: Optional[SpanDelivery] = None):
        """Run one pipeline with lint, crash-resume, and outcome metrics.

        A pipeline name found in the persisted pending marker resumes via
        ``resume_from="latest"`` — the restart-after-crash path; a refused
        resume (changed DAG, no prior run) falls back to a fresh run."""
        from tpu_pipelines.orchestration import LocalDagRunner

        if self._metadata_path is None:
            self._metadata_path = pipeline.metadata_path
        elif pipeline.metadata_path != self._metadata_path:
            raise ValueError(
                "continuous pipelines must share one metadata store "
                f"(window resolver reads span artifacts through it): "
                f"{pipeline.metadata_path!r} != {self._metadata_path!r}"
            )
        self._lint_once(pipeline)
        resume = None
        pending = self._load_pending()
        if pending.get("pipeline") == pipeline.name:
            resume = "latest"
        self._store_pending({
            "pipeline": pipeline.name, "kind": kind,
            "delivery": delivery.key if delivery else None,
        })
        runner = LocalDagRunner()
        try:
            try:
                result = runner.run(
                    pipeline, resume_from=resume, raise_on_failure=False
                )
            except ValueError as e:
                if resume is None:
                    raise
                log.info(
                    "resume of %s refused (%s); running fresh",
                    pipeline.name, e,
                )
                result = runner.run(pipeline, raise_on_failure=False)
        except Exception:  # noqa: BLE001 — the loop must survive a bad run
            log.exception("%s pipeline %s crashed", kind, pipeline.name)
            self._c_runs.labels(kind, "error").inc()
            return None
        finally:
            self._store_pending({})
        self._c_runs.labels(
            kind, "succeeded" if result.succeeded else "failed"
        ).inc()
        if not result.succeeded:
            failed = [
                f"{nr.node_id}: {nr.error.splitlines()[-1] if nr.error else ''}"
                for nr in result.nodes.values() if nr.status == "FAILED"
            ]
            log.warning(
                "%s pipeline %s run %s failed at %s (will retry on the "
                "next tick via resume/caching)",
                kind, pipeline.name, result.run_id, failed,
            )
        return result

    def _window_telemetry(
        self, pipeline_root: str, run_id: str
    ) -> "tuple[Optional[Dict[str, Any]], List[str]]":
        """Read the just-finished window run's training-telemetry
        headline from the durable snapshot ring and diff it against the
        previous window's (both survive controller restarts and trainer
        exits — the ring, not a live scrape, is the source).  Returns
        (headline or None, regression flag list); empty when the ring
        recorded nothing (TPP_METRICS_HISTORY unset)."""
        from tpu_pipelines.observability.export import diff_metrics
        from tpu_pipelines.observability.metrics_history import (
            MetricsHistory,
        )

        if not pipeline_root:
            return None, []
        try:
            headline = MetricsHistory.for_pipeline_root(
                pipeline_root
            ).headline(run_id)
        except OSError:
            return None, []
        if not headline:
            return None, []
        flags: List[str] = []
        prev = self._last_window_telemetry
        if prev:
            flags = diff_metrics(
                {"train_telemetry": prev},
                {"train_telemetry": headline},
            )["regression_flags"]
            if flags:
                log.warning(
                    "window retrain %s telemetry regressed vs previous "
                    "window: %s", run_id, flags,
                )
        self._last_window_telemetry = headline
        return headline, flags

    def _load_pending(self) -> Dict[str, Any]:
        if not self._pending_path:
            return {}
        return load_json_tolerant(self._pending_path) or {}

    def _store_pending(self, marker: Dict[str, Any]) -> None:
        if self._pending_path:
            atomic_write_json(self._pending_path, marker)

    # -------------------------------------------------------------- deploy

    @staticmethod
    def _window_span_count(result) -> int:
        """Spans the window run's SpanWindow artifact covered (0 when the
        run carried no window artifact)."""
        for nr in result.nodes.values():
            for arts in nr.outputs.values():
                for art in arts:
                    spans = art.properties.get("window_spans")
                    if art.type_name == "Examples" and isinstance(
                        spans, list
                    ):
                        return len(spans)
        return 0

    @staticmethod
    def _detect_deploy(result) -> Optional[Dict[str, Any]]:
        """Did this window run push AND hot-reload a version into the
        fleet?  Read off the PushedModel artifact the Pusher published.
        CACHED pusher executions are prior pushes replayed by the
        execution cache, and ADOPTED ones are a resumed run's already-
        published push — neither is a new deploy, nothing to observe."""
        for nr in result.nodes.values():
            if nr.status != "COMPLETE" or nr.adopted:
                continue
            for arts in nr.outputs.values():
                for art in arts:
                    if art.type_name != "PushedModel":
                        continue
                    if not art.properties.get("pushed"):
                        return None  # blessing gate said no
                    return {
                        "run_id": result.run_id,
                        # The fleet-confirmed reload version when the
                        # notify answered; the push-destination dir name
                        # otherwise (same string by the Pusher layout).
                        "version": str(
                            art.properties.get("reload_version")
                            or art.properties.get("pushed_version", "")
                        ),
                        "reload_notified": bool(
                            art.properties.get("reload_notified")
                        ),
                        "pushed_artifact_id": art.id,
                    }
        return None

    def _probation_window_s(self) -> float:
        if self.cfg.probation_watch_s >= 0:
            return self.cfg.probation_watch_s
        import os

        from tpu_pipelines.serving.fleet.fleet import (
            DEFAULT_SWAP_PROBATION_S,
            ENV_SWAP_PROBATION,
        )

        try:
            return float(
                os.environ.get(ENV_SWAP_PROBATION, "").strip()
                or DEFAULT_SWAP_PROBATION_S
            )
        except ValueError:
            return DEFAULT_SWAP_PROBATION_S

    def _health_url(self) -> str:
        parts = urllib.parse.urlsplit(self.cfg.serving_url)
        return urllib.parse.urlunsplit(
            (parts.scheme, parts.netloc, "/healthz", "", "")
        )

    def _fetch_health(self) -> Optional[Dict[str, Any]]:
        try:
            with urllib.request.urlopen(self._health_url(), timeout=5) as r:
                return json.load(r)
        except Exception as e:  # noqa: BLE001 — serving briefly unreachable
            log.debug("healthz poll failed: %s", e)
            return None

    def _observe_probation(
        self, deployed: Dict[str, Any], stop: threading.Event
    ) -> bool:
        """Watch the fleet for ``probation_watch_s`` after a deploy: a
        quarantine of the pushed version means the SLO monitor breached
        and the fleet auto-rolled back (docs/OBSERVABILITY.md) — record
        it and un-bless the triggering model so the rolling resolver
        never baselines it.  Returns True when a rollback was observed.

        On stop-drain the watch performs one final check and exits; a
        rollback happening after that is reconciled by the NEXT deploy's
        quarantine check (the fleet keeps the version quarantined)."""
        if not self.cfg.serving_url or not deployed.get("reload_notified"):
            return False
        version = deployed.get("version", "")
        deadline = time.monotonic() + self._probation_window_s()
        while True:
            health = self._fetch_health()
            fleet = (health or {}).get("fleet") or {}
            if version and version in (
                fleet.get("quarantined_versions") or []
            ):
                self._record_rollback(deployed)
                return True
            if stop.is_set() or time.monotonic() >= deadline:
                return False
            if stop.wait(self.cfg.probation_poll_s):
                # Drain: one last look before handing back control.
                health = self._fetch_health()
                fleet = (health or {}).get("fleet") or {}
                if version and version in (
                    fleet.get("quarantined_versions") or []
                ):
                    self._record_rollback(deployed)
                    return True
                return False

    def _record_rollback(self, deployed: Dict[str, Any]) -> None:
        """The fleet rolled the deploy back: un-bless the triggering
        model in the metadata store (properties AND on-disk markers), so
        resolver strategies — which walk blessed=True blessings — never
        pick it as a baseline, and audit trails show why."""
        import os

        self._c_rollbacks.inc()
        deployed["rolled_back"] = True
        reason = (
            f"serving fleet auto-rollback: version "
            f"{deployed.get('version')} quarantined inside the post-swap "
            f"probation window (run {deployed.get('run_id')})"
        )
        log.warning("continuous: %s", reason)
        from tpu_pipelines.components.evaluator import (
            BLESSING_FILE,
            NOT_BLESSED_FILE,
        )
        from tpu_pipelines.metadata import open_store

        store = open_store(self._metadata_path)
        try:
            pushed = store.get_artifact(
                int(deployed.get("pushed_artifact_id") or 0)
            )
            if pushed is None:
                return
            # Walk push -> producing execution -> its blessing/model
            # INPUTs: the artifacts of the run that deployed this version.
            ex_ids = [
                ev.execution_id
                for ev in store.get_events_by_artifact(pushed.id)
                if ev.type == EventType.OUTPUT
            ]
            for ex_id in ex_ids:
                for ev in store.get_events_by_execution(ex_id):
                    if ev.type != EventType.INPUT:
                        continue
                    art = store.get_artifact(ev.artifact_id)
                    if art is None:
                        continue
                    if art.type_name == "ModelBlessing":
                        art.properties.update({
                            "blessed": False,
                            "unblessed_reason": reason,
                        })
                        store.put_artifact(art)
                        try:
                            blessed_marker = os.path.join(
                                art.uri, BLESSING_FILE
                            )
                            if os.path.exists(blessed_marker):
                                os.remove(blessed_marker)
                            with open(
                                os.path.join(art.uri, NOT_BLESSED_FILE), "w"
                            ) as f:
                                json.dump({"reasons": [reason]}, f)
                        except OSError as e:
                            log.warning(
                                "could not rewrite blessing markers under "
                                "%s: %s", art.uri, e,
                            )
                    elif art.type_name == "Model" and ev.path == "model":
                        art.properties.update({
                            "rollback_quarantined": True,
                            "unblessed_reason": reason,
                        })
                        store.put_artifact(art)
            pushed.properties.update({
                "rolled_back": True, "rollback_reason": reason,
            })
            store.put_artifact(pushed)
        finally:
            store.close()

    # -------------------------------------------------------------- status

    def status(self) -> Dict[str, Any]:
        return {
            "pattern": self.cfg.input_pattern,
            "spans_seen": self.watcher.seen_spans(),
            "iterations": self._iterations,
            "last_iteration": self.last_iteration,
            "last_deploy": self.last_deploy,
        }
