"""Window assembly: logically-complete artifacts from per-span pieces.

The merge half of the incremental contract (docs/CONTINUOUS.md).  Per-span
pipelines produce per-span Examples and mergeable per-span statistics; the
two components here stitch a rolling window of them into artifacts that
downstream Trainer/Evaluator consume exactly as if one cold full-window
run had produced them:

  * :class:`SpanWindow` — hardlink union of the per-span shard files into
    one native-layout Examples artifact.  Zero data copied (same
    filesystem), zero rows re-encoded; the window's global shard order is
    span-ascending, each span's shards in their own order — the SAME
    order a cold ``StatisticsGen`` over the window artifact folds in.
  * :class:`WindowStatisticsMerger` — folds the per-span PRE-MERGE
    accumulators (``StatisticsGen(save_accumulators=True)``) in that
    identical global shard order and finalizes once, so the merged
    statistics equal the cold full-window pass bit for bit while every
    shard fits its reservoir (the PR 3 merge-exactness regime).

Both are ordinary cached components: an unchanged window (same input
artifact fingerprints) is a cache hit, which is what makes the
controller's no-new-span iterations nearly free.
"""

from __future__ import annotations

import os
import shutil
from typing import Dict, List

from tpu_pipelines.data import examples_io
from tpu_pipelines.dsl.component import component


def assemble_window(uris: List[str], out_uri: str) -> Dict[str, int]:
    """Union per-span Examples artifacts into one native-layout artifact.

    For each split (union across sources), every source's shard files are
    hardlinked (copy fallback across filesystems) into ``out_uri`` under
    fresh ``data-NNNNN-of-MMMMM`` names, source order preserved — span
    order times shard order, the fold order every consumer of the window
    sees.  Returns per-split shard counts.
    """
    if not uris:
        raise ValueError("assemble_window: no source artifacts")
    splits: List[str] = []
    for uri in uris:
        for s in examples_io.split_names(uri):
            if s not in splits:
                splits.append(s)
    if not splits:
        raise ValueError(f"assemble_window: no splits under {uris!r}")
    shard_counts: Dict[str, int] = {}
    for split in sorted(splits):
        sources: List[str] = []
        for uri in uris:
            if split in examples_io.split_names(uri):
                sources.extend(examples_io.split_shard_paths(uri, split))
        total = len(sources)
        d = examples_io.split_dir(out_uri, split)
        os.makedirs(d, exist_ok=True)
        for i, src in enumerate(sources):
            dst = os.path.join(d, examples_io.shard_file_name(i, total))
            try:
                os.link(src, dst)
            except OSError:
                shutil.copy2(src, dst)
        shard_counts[split] = total
    return shard_counts


@component(
    inputs={"examples": "Examples"},
    outputs={"window": "Examples"},
)
def SpanWindow(ctx):
    """Hardlink-union the resolver's span window into one Examples
    artifact (span-ascending — the wiring contract with
    RollingWindowResolver, whose output order is span-ascending)."""
    arts = ctx.inputs.get("examples") or []
    if not arts:
        raise ValueError(
            "SpanWindow: empty window — the rolling resolver found no "
            "per-span Examples yet (has the span ingest pipeline run?)"
        )
    out = ctx.output("window")
    shard_counts = assemble_window([a.uri for a in arts], out.uri)
    spans = [a.properties.get("span") for a in arts]
    counts = {
        split: examples_io.num_rows(out.uri, split)
        for split in sorted(shard_counts)
    }
    out.properties["split_names"] = sorted(shard_counts)
    out.properties["split_counts"] = counts
    out.properties["window_spans"] = spans
    return {
        "window_spans": spans,
        "num_examples": sum(counts.values()),
        "data_shards": shard_counts,
    }


@component(
    inputs={"statistics": "ExampleStatistics"},
    outputs={"statistics": "ExampleStatistics"},
)
def WindowStatisticsMerger(ctx):
    """Merge per-span statistics into full-window statistics WITHOUT
    touching the data: fold each split's pre-merge shard accumulators in
    global (span, shard) order, finalize once, save.  Bit-identical to a
    cold StatisticsGen over the SpanWindow artifact while shards fit
    their reservoirs — asserted by the ``continuous.taxi_spans`` bench
    leg's lineage-identity check."""
    from tpu_pipelines.data.statistics import (
        load_split_accumulators,
        merge_accumulators,
        save_statistics,
    )

    arts = ctx.inputs.get("statistics") or []
    if not arts:
        raise ValueError(
            "WindowStatisticsMerger: empty window — no per-span "
            "statistics artifacts resolved (were they produced with "
            "save_accumulators=True?)"
        )
    per_split: Dict[str, list] = {}
    split_order: List[str] = []
    for art in arts:  # span-ascending (resolver output order)
        accs = load_split_accumulators(art.uri)
        for split, shard_accs in accs.items():
            if split not in per_split:
                per_split[split] = []
                split_order.append(split)
            per_split[split].extend(shard_accs)
    stats = {}
    for split in split_order:
        merged = merge_accumulators(per_split[split])
        stats[split] = merged.finalize()
    out = ctx.output("statistics")
    save_statistics(out.uri, stats)
    spans = [a.properties.get("span") for a in arts]
    out.properties["split_names"] = sorted(stats)
    out.properties["window_spans"] = spans
    return {
        "window_spans": spans,
        "merged_shards": {s: len(per_split[s]) for s in split_order},
        **{
            f"num_examples_{s}": stats[s].num_examples for s in split_order
        },
    }
