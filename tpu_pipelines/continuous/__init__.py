"""Continuous pipelines: span-driven incremental runs that deploy into
the live serving fleet (docs/CONTINUOUS.md, ROADMAP item 1).

The subsystem that turns one-shot batch runs into an always-on loop:

  * :class:`SpanWatcher` polls a ``{SPAN}``/``{VERSION}`` input pattern
    and reports new spans — and version re-deliveries of old spans — as
    work, with crash-durable acknowledgement state.
  * :class:`~tpu_pipelines.components.resolver.RollingWindowResolver`
    (components/resolver.py) selects the last-K-spans Examples window,
    their per-span statistics, and the latest blessed baseline model.
  * :class:`SpanWindow` / :class:`WindowStatisticsMerger` give downstream
    nodes a logically-complete artifact: the window Examples is a
    hardlink union of the per-span shard files, and the merged statistics
    fold the per-span PRE-MERGE accumulators in global shard order — so
    the incremental result reproduces a cold full-window pass exactly
    (while every shard fits its reservoir), at the cost of only the NEW
    span's computation.
  * :class:`ContinuousController` is the long-lived loop: watch, run the
    per-span ingest pipeline (execution-cache = incremental), run the
    window pipeline (retrain only when the window changed), deploy
    through the Pusher push-URL into the fleet's canary-gated hot-swap,
    and OBSERVE the fleet: a post-deploy rollback inside the probation
    window un-blesses the triggering model in the metadata store so the
    rolling resolver never baselines it.
"""

from tpu_pipelines.continuous.controller import (  # noqa: F401
    ContinuousConfig,
    ContinuousController,
)
from tpu_pipelines.continuous.watcher import (  # noqa: F401
    SpanDelivery,
    SpanWatcher,
)
from tpu_pipelines.continuous.window import (  # noqa: F401
    SpanWindow,
    WindowStatisticsMerger,
    assemble_window,
)
