"""Span watcher: poll a ``{SPAN}``/``{VERSION}`` pattern for new work.

The arrival detector of the continuous controller (docs/CONTINUOUS.md).
Deliveries are identified by their ``(span, version)`` pair — the TFX
span/version convention where data inside a delivered directory is
immutable and corrections arrive as a NEW ``{VERSION}`` of the same span.
A version re-delivery of an already-processed span is therefore reported
as fresh work, never as old news; content edits inside an existing
version directory are deliberately NOT watched for (the execution cache
still catches them when the span pipeline runs, but nothing wakes the
loop — re-deliver under a new version instead).

Acknowledgement state is crash-durable when a state path is configured
(``atomic_write_json``): a controller that dies between poll and ack
re-reports the same deliveries on restart, making the loop at-least-once
— safe, because the runs it triggers are themselves idempotent through
the execution cache.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Dict, Iterable, List, Optional, Tuple

from tpu_pipelines.robustness import atomic_write_json, load_json_tolerant
from tpu_pipelines.utils.span import list_spans

log = logging.getLogger("tpu_pipelines.continuous")


@dataclasses.dataclass(frozen=True)
class SpanDelivery:
    """One (span, version) arrival; ``path`` is the concrete directory."""

    span: int
    version: Optional[int]
    path: str

    @property
    def key(self) -> str:
        return f"{self.span}:{'' if self.version is None else self.version}"


class SpanWatcher:
    """Tracks which ``(span, version)`` deliveries have been processed.

    ``poll()`` returns the unacknowledged deliveries, span-ascending, at
    most one per span (the NEWEST version — superseded intermediate
    versions are skipped, not queued: retraining on version 2 when
    version 3 already landed would be wasted work).  ``ack()`` marks
    deliveries processed and persists the state.
    """

    def __init__(self, pattern: str, state_path: str = ""):
        self.pattern = pattern
        self.state_path = state_path
        # span -> acknowledged version rank (None-version layouts use -1;
        # a higher version re-delivery outranks every prior ack).
        self._acked: Dict[int, int] = {}
        if state_path and os.path.exists(state_path):
            raw = load_json_tolerant(state_path) or {}
            try:
                self._acked = {
                    int(k): int(v)
                    for k, v in (raw.get("acked") or {}).items()
                }
            except (TypeError, ValueError):
                log.warning(
                    "span watcher state %r unreadable; starting from "
                    "scratch (at-least-once: already-processed spans "
                    "re-report and cache-hit)", state_path,
                )
                self._acked = {}

    @staticmethod
    def _rank(version: Optional[int]) -> int:
        return -1 if version is None else int(version)

    def seen_spans(self) -> List[int]:
        return sorted(self._acked)

    def poll(self) -> List[SpanDelivery]:
        """Unacknowledged deliveries, one per span, span-ascending."""
        newest: Dict[int, Tuple[Optional[int], str]] = {}
        for span, version, path in list_spans(self.pattern):
            cur = newest.get(span)
            if cur is None or self._rank(version) >= self._rank(cur[0]):
                newest[span] = (version, path)
        out = [
            SpanDelivery(span=span, version=version, path=path)
            for span, (version, path) in sorted(newest.items())
            if self._rank(version) > self._acked.get(span, -(1 << 30))
        ]
        return out

    def ack(self, deliveries: Iterable[SpanDelivery]) -> None:
        changed = False
        for d in deliveries:
            rank = self._rank(d.version)
            if rank > self._acked.get(d.span, -(1 << 30)):
                self._acked[d.span] = rank
                changed = True
        if changed:
            self._persist()

    def _persist(self) -> None:
        if not self.state_path:
            return
        atomic_write_json(
            self.state_path,
            {"pattern": self.pattern,
             "acked": {str(k): v for k, v in self._acked.items()}},
        )
