"""TransformGraph: analysis, host/device evaluation, serialization.

The one-graph-two-places skew guarantee (SURVEY.md §7 hard part #1): the DAG
serialized here is the only definition of preprocessing.  It is evaluated by
`apply_host` when materializing transformed examples, and by
`split_host_device` at serving/inference time, where the numeric subgraph
becomes a pure jax-traceable function compiled on-chip together with the model
(the `jit_compile=True` co-location from BASELINE).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from tpu_pipelines.data.schema import FeatureType, Schema
from tpu_pipelines.transform.expr import (
    NUMERIC,
    OPS,
    STRING,
    ColumnRef,
    GraphBuilder,
    Node,
    TftNamespace,
    is_ref,
    ref_id,
)

GRAPH_FILE = "transform_graph.json"
STATE_FILE = "analyzer_state.npz"
VOCAB_DIR = "vocabularies"
# v2: Node.inputs encodes node references as {"ref": id} (bare ints are
# literal scalars).  v1 graphs (bare-int refs) are rejected, not mis-read.
GRAPH_FORMAT = "transform-graph/v2"


class _LazyInputs:
    """Dict-like view handed to preprocessing_fn; creates inputs on access."""

    def __init__(self, builder: GraphBuilder, dtypes: Dict[str, str]):
        self._b = builder
        self._dtypes = dtypes

    def __getitem__(self, name: str) -> ColumnRef:
        if name not in self._dtypes:
            raise KeyError(
                f"preprocessing_fn requested unknown feature {name!r}; "
                f"schema has {sorted(self._dtypes)}"
            )
        return self._b.input(name, self._dtypes[name])

    def keys(self):
        return self._dtypes.keys()

    def __contains__(self, name: str) -> bool:
        return name in self._dtypes


def _schema_dtypes(schema: Schema) -> Dict[str, str]:
    return {
        name: STRING if f.type == FeatureType.BYTES else NUMERIC
        for name, f in schema.features.items()
    }


def _stable_hash_strings(values: np.ndarray, buckets: int) -> np.ndarray:
    from tpu_pipelines.utils.hashing import hash_buckets

    return hash_buckets(values, buckets).astype(np.int32)


class TransformGraph:
    """A resolved (or being-resolved) preprocessing DAG."""

    def __init__(
        self,
        nodes: List[Node],
        outputs: Dict[str, int],
        state: Optional[Dict[int, Dict[str, Any]]] = None,
    ):
        self.nodes = nodes
        self.outputs = outputs
        self.state: Dict[int, Dict[str, Any]] = state or {}
        # Lazy (host_fn, jitted device_fn) pair for apply_device.
        self._device_apply = None

    # ------------------------------------------------------------ building

    @classmethod
    def build(
        cls,
        preprocessing_fn: Callable,
        schema: Schema,
    ) -> "TransformGraph":
        builder = GraphBuilder()
        tft = TftNamespace(builder)
        inputs = _LazyInputs(builder, _schema_dtypes(schema))
        out = preprocessing_fn(inputs, tft)
        if not isinstance(out, dict) or not out:
            raise ValueError(
                "preprocessing_fn must return a non-empty dict of ColumnRefs"
            )
        outputs: Dict[str, int] = {}
        for name, ref in out.items():
            if not isinstance(ref, ColumnRef):
                raise TypeError(
                    f"preprocessing_fn output {name!r} is "
                    f"{type(ref).__name__}, expected ColumnRef"
                )
            outputs[name] = ref.id
        return cls(builder.nodes, outputs)

    # ------------------------------------------------------------ analysis

    def analyze(self, data: Dict[str, np.ndarray]) -> None:
        """Full-pass analysis of an in-memory dataset (single chunk)."""
        self.analyze_chunks(lambda: iter([data]))

    def analyze_chunks(
        self,
        chunks_fn: Callable[[], Any],
        on_chip: Optional[bool] = None,
    ) -> None:
        """Resolve every analyzer by streaming chunks — the Beam-less
        full pass (SURVEY.md §3.4): per-chunk partial states accumulate and
        merge, so no column is ever materialized whole.

        ``chunks_fn()`` returns a fresh iterator of dict-of-numpy chunks per
        pass.  Nested analyzers (z-score of a bucketized column) resolve in
        multiple passes: pass k handles analyzers whose upstream analyzers
        resolved in passes < k — the tf.Transform phase structure.

        ``on_chip``: numeric accumulators (moments, min/max) run as jitted
        reductions on the default jax device; None = auto (on when a TPU
        backend is present), False = pure numpy.
        """
        if on_chip is None:
            on_chip = _tpu_present()
        upstream_analyzers = self._upstream_analyzers()
        guard = 0
        while True:
            unresolved = [
                n for n in self.nodes
                if n.op in OPS and OPS[n.op].is_analyzer
                and n.id not in self.state
            ]
            if not unresolved:
                break
            ready = [
                n for n in unresolved
                if all(
                    a in self.state for a in upstream_analyzers[n.id]
                    if a != n.id
                )
            ]
            if not ready:
                raise RuntimeError(
                    "analyzer dependency cycle: "
                    f"{[n.op for n in unresolved]}"
                )
            # Analyzers whose state is derivable without data (vocab files).
            pending = []
            for node in ready:
                st = _finalize_dataless(node)
                if st is not None:
                    self.state[node.id] = st
                else:
                    pending.append(node)
            if not pending:
                guard += 1
                if guard > len(self.nodes) + 1:
                    raise RuntimeError("analysis did not converge")
                continue
            # One streaming pass accumulating all pending-ready analyzers.
            accs = {n.id: _acc_init(n) for n in pending}
            needed = [n.id for n in pending]
            for chunk in chunks_fn():
                vals = self._eval_available(chunk, needed)
                for node in pending:
                    arg = vals[ref_id(node.inputs[0])]
                    accs[node.id] = _acc_update(
                        node, accs[node.id], arg, on_chip
                    )
            for node in pending:
                self.state[node.id] = _acc_finalize(node, accs[node.id])

    def _upstream_analyzers(self) -> Dict[int, set]:
        """Per node: ids of analyzer nodes among its ancestors (and itself's
        direct analyzer inputs) — the phase-ordering relation."""
        up: Dict[int, set] = {}
        for node in self.nodes:  # nodes are already topologically ordered
            s: set = set()
            for a in node.inputs:
                if is_ref(a):
                    aid = ref_id(a)
                    s |= up[aid]
                    if OPS.get(self.nodes[aid].op) and OPS[self.nodes[aid].op].is_analyzer:
                        s.add(aid)
            up[node.id] = s
        return up

    def _eval_available(
        self, data: Dict[str, Any], target_ids: List[int]
    ) -> Dict[int, Any]:
        """Evaluate just the nodes feeding ``target_ids``'s inputs, using
        resolved analyzer states only (callers guarantee reachability)."""
        need: set = set()
        stack = [
            ref_id(a)
            for t in target_ids
            for a in self.nodes[t].inputs if is_ref(a)
        ]
        while stack:
            nid = stack.pop()
            if nid in need:
                continue
            need.add(nid)
            stack.extend(
                ref_id(a) for a in self.nodes[nid].inputs if is_ref(a)
            )
        subset = [n.id for n in self.nodes if n.id in need]
        return self._eval(data, np, subset=subset)

    # ---------------------------------------------------------- evaluation

    def apply_host(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """Vectorized numpy evaluation (materialization / host fallback)."""
        vals = self._eval(batch, np)
        return {name: vals[nid] for name, nid in self.outputs.items()}

    def apply_device(
        self, batch: Dict[str, np.ndarray]
    ) -> Dict[str, np.ndarray]:
        """Materialize one batch through the host/device split: string ops
        on host, the whole numeric subgraph as ONE jitted computation on the
        default jax device (the BASELINE "Transform ... jit_compile=True
        on-chip" path for materialization, not just analyzer reductions).
        Numerically equal to apply_host up to f32 rounding — both are
        interpretations of the same DAG; tested for equality e2e.
        """
        if self._device_apply is None:
            import jax

            host_fn, device_fn, iface_names = self.split_host_device()
            if any(
                self.nodes[int(k[1:])].dtype == STRING for k in iface_names
            ):
                # A string-valued output crosses the interface (e.g. an
                # identity passthrough of a raw string column): jit cannot
                # ingest or return string arrays, so this graph materializes
                # host-side.  Numeric-only graphs — the common case once
                # strings are vocab'd/hashed — take the device path.
                self._device_apply = (None, None)
            else:
                self._device_apply = (host_fn, jax.jit(device_fn))
        host_fn, jitted = self._device_apply
        if jitted is None:
            return self.apply_host(batch)
        out = jitted(host_fn(batch))
        return {k: np.asarray(v) for k, v in out.items()}

    @property
    def device_apply_active(self) -> Optional[bool]:
        """None before apply_device first ran; False when it decided this
        graph cannot jit (string interface) and is silently using the host
        path; True when chunks really go through the jitted device fn.
        Callers recording "ran on device" must check this, not assume."""
        if self._device_apply is None:
            return None
        return self._device_apply[1] is not None

    def _eval(
        self,
        data: Dict[str, Any],
        xp,
        subset: Optional[List[int]] = None,
        preset: Optional[Dict[int, Any]] = None,
    ) -> Dict[int, Any]:
        vals: Dict[int, Any] = dict(preset or {})
        nodes = (
            self.nodes if subset is None
            else [self.nodes[i] for i in subset]
        )
        for node in nodes:
            if node.id in vals:
                continue
            if node.op == "input":
                if node.name not in data:
                    raise KeyError(
                        f"transform input feature {node.name!r} missing from batch"
                    )
                vals[node.id] = data[node.name]
                continue
            args = [
                vals[ref_id(a)] if is_ref(a) else a for a in node.inputs
            ]
            opdef = OPS[node.op]
            if opdef.is_analyzer:
                if node.id not in self.state:
                    raise RuntimeError(
                        f"analyzer node #{node.id} ({node.op}) has no "
                        "state; run analyze() first"
                    )
                vals[node.id] = _apply_analyzer(
                    node, self.state[node.id], args[0], xp
                )
            else:
                vals[node.id] = _apply_stateless(node, args, xp)
        return vals

    # ------------------------------------------------- host/device split

    def split_host_device(
        self,
    ) -> Tuple[Callable, Callable, List[str]]:
        """Partition at the string→numeric frontier.

        Returns ``(host_fn, device_fn, interface_names)``:
          - ``host_fn(batch) -> {iface_name: np.ndarray}`` runs string ops
            (vocab lookup, hashing) plus passthrough of numeric inputs;
          - ``device_fn(iface) -> outputs`` is pure numeric, jax-traceable —
            embed it inside a jitted serving/training step;
          - the interface is the list of array names crossing host→device.

        Skew safety: both functions are interpretations of the same DAG.
        """
        host_nodes: set = set()
        for node in self.nodes:
            if node.op == "input":
                if node.dtype == STRING:
                    host_nodes.add(node.id)
                continue
            arg_ids = [ref_id(a) for a in node.inputs if is_ref(a)]
            consumes_string = any(
                self.nodes[a].dtype == STRING for a in arg_ids
            )
            if consumes_string or node.dtype == STRING:
                host_nodes.add(node.id)

        # Interface: numeric-valued nodes that device-side nodes consume but
        # are produced on host (string-derived ids), plus numeric inputs.
        iface_ids: List[int] = []
        for node in self.nodes:
            if node.id in host_nodes:
                continue
            if node.op == "input":
                if node.id not in iface_ids:
                    iface_ids.append(node.id)
                continue
            for a in node.inputs:
                if is_ref(a) and ref_id(a) in host_nodes:
                    if ref_id(a) not in iface_ids:
                        iface_ids.append(ref_id(a))
        # Outputs computed entirely on host also cross the boundary.
        for name, nid in self.outputs.items():
            if nid in host_nodes and nid not in iface_ids:
                iface_ids.append(nid)

        iface_names = [f"c{nid}" for nid in iface_ids]
        device_subset = [
            n.id for n in self.nodes if n.id not in host_nodes
        ]

        def host_fn(batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
            vals = self._eval_host_side(batch, host_nodes, iface_ids)
            return {f"c{nid}": vals[nid] for nid in iface_ids}

        def device_fn(iface: Dict[str, Any]) -> Dict[str, Any]:
            import jax.numpy as jnp

            preset = {nid: iface[f"c{nid}"] for nid in iface_ids}
            vals = self._eval(
                {}, jnp, subset=device_subset, preset=preset
            )
            return {name: vals[nid] for name, nid in self.outputs.items()}

        return host_fn, device_fn, iface_names

    def _eval_host_side(
        self, batch: Dict[str, np.ndarray], host_nodes: set, iface_ids: List[int]
    ) -> Dict[int, Any]:
        """Evaluate host nodes + numeric inputs needed at the interface."""
        vals: Dict[int, Any] = {}
        needed = set(iface_ids)
        for node in self.nodes:
            if node.op == "input":
                if node.id in host_nodes or node.id in needed:
                    if node.name not in batch:
                        raise KeyError(
                            f"feature {node.name!r} missing from batch"
                        )
                    vals[node.id] = batch[node.name]
                continue
            if node.id not in host_nodes:
                continue
            args = [
                vals[ref_id(a)] if is_ref(a) else a for a in node.inputs
            ]
            opdef = OPS[node.op]
            if opdef.is_analyzer:
                if node.id not in self.state:
                    raise RuntimeError(
                        f"analyzer node #{node.id} unresolved; run analyze()"
                    )
                vals[node.id] = _apply_analyzer(
                    node, self.state[node.id], args[0], np
                )
            else:
                vals[node.id] = _apply_stateless(node, args, np)
        return vals

    # -------------------------------------------------------- persistence

    def save(self, uri: str) -> None:
        os.makedirs(uri, exist_ok=True)
        graph_json = {
            "format": GRAPH_FORMAT,
            "nodes": [n.to_json() for n in self.nodes],
            "outputs": self.outputs,
        }
        with open(os.path.join(uri, GRAPH_FILE), "w") as f:
            json.dump(graph_json, f, indent=2, sort_keys=True)
        arrays: Dict[str, np.ndarray] = {}
        vocab_meta: Dict[str, Dict] = {}
        for nid, st in self.state.items():
            for key, val in st.items():
                if key.startswith("_"):
                    continue  # derived caches (e.g. tokenize _table)
                if key == "vocab":
                    # Human-inspectable vocabulary files, one term per line —
                    # the tf.Transform vocab-file convention.
                    vdir = os.path.join(uri, VOCAB_DIR)
                    os.makedirs(vdir, exist_ok=True)
                    vpath = os.path.join(vdir, f"vocab_{nid}.txt")
                    with open(vpath, "w") as f:
                        for term in val:
                            f.write(f"{term}\n")
                    vocab_meta[str(nid)] = {"size": len(val)}
                else:
                    arrays[f"{nid}:{key}"] = np.asarray(val)
        np.savez(os.path.join(uri, STATE_FILE), **arrays)
        with open(os.path.join(uri, "vocab_meta.json"), "w") as f:
            json.dump(vocab_meta, f)

    @classmethod
    def load(cls, uri: str) -> "TransformGraph":
        with open(os.path.join(uri, GRAPH_FILE)) as f:
            graph_json = json.load(f)
        fmt = graph_json.get("format")
        if fmt != GRAPH_FORMAT:
            raise ValueError(
                f"transform graph at {uri!r} has format {fmt!r}, expected "
                f"{GRAPH_FORMAT!r}; re-run the Transform component"
            )
        nodes = [Node.from_json(d) for d in graph_json["nodes"]]
        outputs = {k: int(v) for k, v in graph_json["outputs"].items()}
        state: Dict[int, Dict[str, Any]] = {}
        npz_path = os.path.join(uri, STATE_FILE)
        if os.path.exists(npz_path):
            data = np.load(npz_path)
            for key in data.files:
                nid_s, skey = key.split(":", 1)
                state.setdefault(int(nid_s), {})[skey] = data[key]
        meta_path = os.path.join(uri, "vocab_meta.json")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                vocab_meta = json.load(f)
            for nid_s in vocab_meta:
                vpath = os.path.join(uri, VOCAB_DIR, f"vocab_{nid_s}.txt")
                with open(vpath) as f:
                    vocab = [line.rstrip("\n") for line in f]
                state.setdefault(int(nid_s), {})["vocab"] = vocab
        return cls(nodes, outputs, state)

    # --------------------------------------------------------------- misc

    def output_feature_names(self) -> List[str]:
        return sorted(self.outputs)

    def input_feature_names(self) -> List[str]:
        """Raw columns the graph actually reads — the projection set for
        column-pruned reads (schema features the preprocessing_fn never
        touched don't need to leave the Parquet footer)."""
        return sorted({n.name for n in self.nodes if n.op == "input"})

    def tokenizer_vocab_sizes(self) -> Dict[str, int]:
        """Resolved vocab size per tokenize-producing output column.

        Lets a trainer module size its embedding table from what the
        tokenizer actually learned (plus OOV-free specials), instead of
        guessing — ids are always < this size.
        """
        out: Dict[str, int] = {}
        for name, nid in self.outputs.items():
            node = self.nodes[nid]
            if node.op == "tokenize" and nid in self.state:
                out[name] = len(self.state[nid]["vocab"])
        return out


# ---------------------------------------------------------------- operators


def _tpu_present() -> bool:
    try:
        import jax

        return jax.default_backend() == "tpu"
    except Exception:
        return False


_MOMENTS_JIT = None
_MINMAX_JIT = None


def _moments_chunk(col, on_chip: bool):
    """(count, sum, sum_sq) over non-NaN values of one chunk.

    On-chip: one jitted tree-reduction (sum/sum-of-squares on the device —
    the SURVEY §3.4 "analyzers as jitted reductions"); numpy in f64 otherwise.
    """
    if on_chip:
        global _MOMENTS_JIT
        import jax
        import jax.numpy as jnp

        if _MOMENTS_JIT is None:
            @jax.jit
            def _kernel(x):
                ok = ~jnp.isnan(x)
                xz = jnp.where(ok, x, 0.0)
                return (
                    jnp.sum(ok.astype(jnp.float32)),
                    jnp.sum(xz),
                    jnp.sum(xz * xz),
                )

            _MOMENTS_JIT = _kernel
        c, s, ss = _MOMENTS_JIT(
            jnp.asarray(np.asarray(col, np.float32).ravel())
        )
        return float(c), float(s), float(ss)
    x = np.asarray(col, np.float64).ravel()
    x = x[~np.isnan(x)]
    return float(len(x)), float(x.sum()), float((x * x).sum())


def _minmax_chunk(col, on_chip: bool):
    """(count, min, max) over non-NaN values of one chunk."""
    if on_chip:
        global _MINMAX_JIT
        import jax
        import jax.numpy as jnp

        if _MINMAX_JIT is None:
            @jax.jit
            def _kernel(x):
                ok = ~jnp.isnan(x)
                return (
                    jnp.sum(ok.astype(jnp.float32)),
                    jnp.min(jnp.where(ok, x, jnp.inf)),
                    jnp.max(jnp.where(ok, x, -jnp.inf)),
                )

            _MINMAX_JIT = _kernel
        c, lo, hi = _MINMAX_JIT(
            jnp.asarray(np.asarray(col, np.float32).ravel())
        )
        return float(c), float(lo), float(hi)
    x = np.asarray(col, np.float64).ravel()
    x = x[~np.isnan(x)]
    if not len(x):
        return 0.0, np.inf, -np.inf
    return float(len(x)), float(x.min()), float(x.max())


# Mergeable quantile summary for bucketize: raw values accumulate until the
# buffer exceeds _SKETCH_COMPRESS, then compress to _SKETCH_SIZE weighted
# quantile points.  Uncompressed summaries finalize through np.quantile
# exactly, so small datasets match the in-memory semantics bit-for-bit.
_SKETCH_SIZE = 2048
_SKETCH_COMPRESS = 8192


def _weighted_quantile(values, weights, qs):
    order = np.argsort(values, kind="stable")
    v, w = values[order], weights[order]
    cw = (np.cumsum(w) - 0.5 * w) / w.sum()
    return np.interp(qs, cw, v)


def _sketch_add(sk: Dict[str, Any], vals: np.ndarray) -> Dict[str, Any]:
    if len(vals):
        sk["values"] = np.concatenate([sk["values"], vals])
        sk["weights"] = np.concatenate(
            [sk["weights"], np.ones(len(vals), np.float64)]
        )
    if len(sk["values"]) > _SKETCH_COMPRESS:
        total = sk["weights"].sum()
        qs = (np.arange(_SKETCH_SIZE) + 0.5) / _SKETCH_SIZE
        sk["values"] = _weighted_quantile(sk["values"], sk["weights"], qs)
        sk["weights"] = np.full(
            _SKETCH_SIZE, total / _SKETCH_SIZE, np.float64
        )
        sk["compressed"] = True
    return sk


def _acc_init(node: Node) -> Dict[str, Any]:
    if node.op == "z_score":
        return {"count": 0.0, "sum": 0.0, "sumsq": 0.0}
    if node.op == "scale_to_0_1":
        return {"count": 0.0, "min": np.inf, "max": -np.inf}
    if node.op in ("vocab_apply", "tokenize"):
        return {"counts": {}}
    if node.op == "bucketize":
        return {
            "values": np.zeros(0, np.float64),
            "weights": np.zeros(0, np.float64),
            "compressed": False,
        }
    raise ValueError(f"unknown analyzer {node.op!r}")


def _acc_update(
    node: Node, acc: Dict[str, Any], col, on_chip: bool
) -> Dict[str, Any]:
    if node.op == "z_score":
        c, s, ss = _moments_chunk(col, on_chip)
        acc["count"] += c
        acc["sum"] += s
        acc["sumsq"] += ss
        return acc
    if node.op == "scale_to_0_1":
        c, lo, hi = _minmax_chunk(col, on_chip)
        acc["count"] += c
        acc["min"] = min(acc["min"], lo)
        acc["max"] = max(acc["max"], hi)
        return acc
    if node.op == "vocab_apply":
        uniq, counts = np.unique(_stringify_column(col), return_counts=True)
        merged = acc["counts"]
        for term, cnt in zip(uniq, counts):
            merged[str(term)] = merged.get(str(term), 0) + int(cnt)
        return acc
    if node.op == "bucketize":
        vals = np.asarray(col, np.float64).ravel()
        _sketch_add(acc, vals[~np.isnan(vals)])
        return acc
    if node.op == "tokenize":
        _count_pretokens_into(acc, col, node.params.get("lowercase", True))
        return acc
    raise ValueError(f"unknown analyzer {node.op!r}")


def _tokenize_stringify(col) -> np.ndarray:
    """Per-element ``str(value)`` semantics as a U-dtype array — the exact
    text the per-row Python engine tokenizes (floats keep their decimal
    text, None becomes ""), unlike ``_stringify_column`` whose int64 cast
    is vocab_apply's contract, not tokenize's."""
    arr = np.asarray(col)
    if arr.dtype == object:
        # None pretokenizes to no tokens ("" in the Python engine);
        # stringify would turn it into the literal "None".
        mask = np.frompyfunc(lambda x: x is None, 1, 1)(arr).astype(bool)
        if mask.any():
            arr = arr.copy()
            arr[mask] = ""
    return np.asarray(arr.ravel(), dtype="U")


def _split_ascii_rows(col, strs: Optional[np.ndarray] = None):
    """(ascii_rows: List[bytes], other_texts: List[str]) for native routing.

    All-ASCII columns (the common corpus) take one vectorized encode; mixed
    columns degrade to per-row routing so non-ASCII rows keep Python's exact
    unicode semantics.  ``strs`` passes in an already-stringified column so
    callers that stringified for another fast path don't pay twice.
    """
    if strs is None:
        strs = _tokenize_stringify(col)
    try:
        return [bytes(b) for b in np.char.encode(strs, "ascii")], []
    except UnicodeEncodeError:
        pass
    ascii_rows, others = [], []
    for s in strs:
        try:
            ascii_rows.append(str(s).encode("ascii"))
        except UnicodeEncodeError:
            others.append(str(s))
    return ascii_rows, others


def _count_pretokens_into(acc: Dict[str, Any], col, lowercase: bool) -> None:
    """Accumulate the vocab-build token counts for one chunk.

    The full-corpus counting pass is the stage the reference ran as a Beam
    CombinePerKey (SURVEY.md §3.4 / §2b); here, preference order mirrors
    the apply side (_apply_tokenize): the C++ count kernel for ASCII rows
    (token counts stay in the C++ hash map until finalize), a process-pool
    fan-out of the Python counter when the toolchain can't build the native
    core, and the plain in-process loop for small chunks.  Non-ASCII rows
    always count through Python's unicode-exact pretokenizer.
    """
    counts = acc["counts"]

    def count_py(texts) -> None:
        for text in texts:
            for tok in _pretokenize(text, lowercase):
                counts[tok] = counts.get(tok, 0) + 1

    from tpu_pipelines.transform import native_tokenizer

    native = acc.get("_native_counter")
    if native is None and "_native_counter" not in acc:
        try:
            native = native_tokenizer.NativeTokenCounter(lowercase)
        except RuntimeError:
            native = None
        acc["_native_counter"] = native
    if native is not None:
        strs = _tokenize_stringify(col)
        # All-ASCII fast path: the U-dtype UCS4 buffer crosses the FFI
        # as-is (one vectorized max() validates) — no encode pass, no
        # per-row Python objects at all.
        if native.add_unicode_array(strs):
            return
        ascii_rows, others = _split_ascii_rows(col, strs=strs)
        native.add_ascii_rows(ascii_rows)
        count_py(others)
        return

    import os as _os

    workers = min(_os.cpu_count() or 1, _TOK_MAX_WORKERS)
    if len(col) >= _TOK_MIN_PARALLEL_ROWS and workers > 1:
        # One pool for the WHOLE analysis pass, stashed on the accumulator
        # like the native counter (finalize shuts it down): a fresh spawn
        # per streamed chunk would pay worker startup dozens of times per
        # split and could dominate the counting it parallelizes.
        ex = acc.get("_count_pool")
        if ex is None:
            from concurrent.futures import ProcessPoolExecutor

            ex = acc["_count_pool"] = ProcessPoolExecutor(
                max_workers=workers, initializer=_count_init,
                initargs=(lowercase,),
            )
        chunks = [c for c in np.array_split(np.asarray(col, dtype=object),
                                            workers * 4) if len(c)]
        for part in ex.map(_count_chunk_py, chunks):
            for tok, n in part.items():
                counts[tok] = counts.get(tok, 0) + n
        return
    count_py(col)


# Worker-process state for pool-parallel vocab counting (mirrors _tok_init/
# _tok_chunk on the apply side).
_COUNT_LOWERCASE = True


def _count_init(lowercase: bool) -> None:
    global _COUNT_LOWERCASE
    _COUNT_LOWERCASE = lowercase


def _count_chunk_py(rows) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for text in rows:
        for tok in _pretokenize(text, _COUNT_LOWERCASE):
            out[tok] = out.get(tok, 0) + 1
    return out


def _acc_finalize(node: Node, acc: Dict[str, Any]) -> Dict[str, Any]:
    p = node.params
    if node.op == "z_score":
        c = acc["count"]
        if not c:
            return {"mean": 0.0, "std": 1.0}
        mean = acc["sum"] / c
        var = max(0.0, acc["sumsq"] / c - mean * mean)
        std = var ** 0.5
        return {"mean": mean, "std": std if std > 0 else 1.0}
    if node.op == "scale_to_0_1":
        if not acc["count"]:
            return {"min": 0.0, "max": 1.0}
        lo, hi = acc["min"], acc["max"]
        return {"min": lo, "max": hi if hi > lo else lo + 1.0}
    if node.op == "vocab_apply":
        terms = acc["counts"]
        uniq = np.asarray(sorted(terms), dtype=object)
        counts = np.asarray([terms[t] for t in uniq], np.int64)
        if p.get("frequency_threshold", 0):
            keep = counts >= p["frequency_threshold"]
            uniq, counts = uniq[keep], counts[keep]
        # Order: descending frequency, then lexical — deterministic.
        order = np.lexsort((uniq, -counts))
        vocab = [str(uniq[i]) for i in order]
        if p.get("top_k"):
            vocab = vocab[: p["top_k"]]
        return {"vocab": vocab}
    if node.op == "bucketize":
        qs = np.linspace(0, 1, p["num_buckets"] + 1)[1:-1]
        if not len(acc["values"]):
            return {"boundaries": np.zeros(0)}
        if acc["compressed"]:
            boundaries = _weighted_quantile(
                acc["values"], acc["weights"], qs
            )
        else:
            boundaries = np.quantile(acc["values"], qs)
        return {"boundaries": np.unique(boundaries)}
    if node.op == "tokenize":
        counts = acc["counts"]
        native = acc.get("_native_counter")
        if native is not None:
            # Drain the C++ hash map once; merge with the Python-side counts
            # from any non-ASCII rows.
            for tok, n in native.counts().items():
                counts[tok] = counts.get(tok, 0) + n
            acc["_native_counter"] = None
        pool = acc.pop("_count_pool", None)
        if pool is not None:
            pool.shutdown()
        # descending frequency, then lexical — deterministic
        terms = sorted(counts, key=lambda t: (-counts[t], t))
        budget = max(0, int(p.get("vocab_size", 8000)) - len(SPECIAL_TOKENS))
        return {"vocab": list(SPECIAL_TOKENS) + terms[:budget]}
    raise ValueError(f"unknown analyzer {node.op!r}")


def _finalize_dataless(node: Node) -> Optional[Dict[str, Any]]:
    """State derivable without a data pass (tokenize with a fixed vocab)."""
    if node.op == "tokenize" and node.params.get("vocab_file"):
        with open(node.params["vocab_file"]) as f:
            vocab = [line.rstrip("\n") for line in f if line.rstrip("\n")]
        missing = [t for t in SPECIAL_TOKENS if t not in vocab]
        if missing:
            raise ValueError(
                f"tokenize vocab_file {node.params['vocab_file']!r} lacks "
                f"special tokens {missing}; the ids-0-3 = "
                "[PAD]/[UNK]/[CLS]/[SEP] contract requires them"
            )
        return {"vocab": vocab}
    return None


def _stringify_column(col) -> np.ndarray:
    """Column → unicode array, vectorized (ints stringify like str(int))."""
    col = np.asarray(col)
    if col.dtype == object or col.dtype.kind in ("U", "S"):
        return np.asarray(col, dtype="U")
    return col.ravel().astype(np.int64).astype("U")


SPECIAL_TOKENS = ("[PAD]", "[UNK]", "[CLS]", "[SEP]")
_PUNCT_SPLIT = None  # compiled lazily


def _pretokenize(text, lowercase: bool) -> List[str]:
    """Whitespace + punctuation split (the BERT basic-tokenizer convention)."""
    global _PUNCT_SPLIT
    if _PUNCT_SPLIT is None:
        import re

        _PUNCT_SPLIT = re.compile(r"\w+|[^\w\s]")
    s = "" if text is None else str(text)
    if lowercase:
        s = s.lower()
    return _PUNCT_SPLIT.findall(s)


def _wordpiece(tok: str, table: Dict[str, int], unk: int) -> List[int]:
    """Greedy longest-match-first wordpiece (BERT); whole-word if present."""
    if tok in table:
        return [table[tok]]
    ids: List[int] = []
    start = 0
    while start < len(tok):
        end = len(tok)
        piece_id = None
        while start < end:
            sub = tok[start:end] if start == 0 else "##" + tok[start:end]
            if sub in table:
                piece_id = table[sub]
                break
            end -= 1
        if piece_id is None:
            return [unk]
        ids.append(piece_id)
        start = end
    return ids


def _tokenize_core(
    col, params: Dict[str, Any], table: Dict[str, int], has_wordpiece: bool
) -> np.ndarray:
    unk = table.get("[UNK]", 1)
    cls_id = table.get("[CLS]", 2)
    sep_id = table.get("[SEP]", 3)
    max_len = int(params["max_len"])
    lowercase = params.get("lowercase", True)
    out = np.zeros((len(col), max_len), dtype=np.int32)  # 0 = [PAD]
    for i, text in enumerate(col):
        ids = [cls_id]
        for tok in _pretokenize(text, lowercase):
            if has_wordpiece:
                ids.extend(_wordpiece(tok, table, unk))
            else:
                ids.append(table.get(tok, unk))
            if len(ids) >= max_len - 1:
                break
        ids = ids[: max_len - 1] + [sep_id]
        out[i, : len(ids)] = ids
    return out


# Worker-process state for pool-parallel tokenization: the vocab table ships
# once per worker (pool initializer), chunks ship only their rows.
_TOK_CTX: Optional[Tuple[Dict[str, Any], Dict[str, int], bool]] = None
_TOK_MIN_PARALLEL_ROWS = 4096
_TOK_MAX_WORKERS = 8


def _tok_init(params: Dict[str, Any], vocab: List[str]) -> None:
    global _TOK_CTX
    table = {v: i for i, v in enumerate(vocab)}
    _TOK_CTX = (params, table, any(v.startswith("##") for v in vocab))


def _tok_chunk(rows) -> np.ndarray:
    params, table, has_wordpiece = _TOK_CTX
    return _tokenize_core(rows, params, table, has_wordpiece)


def _apply_tokenize(node: Node, state: Dict[str, Any], col) -> np.ndarray:
    """Tokenize a column: C++ core first, process pool second, inline last.

    The wordpiece loop is irreducibly per-row work — what the reference ran
    embarrassingly-parallel under Beam (SURVEY.md §2b).  Preference order:
    the native C++ core (transform/native_tokenizer.py, ~7x the interpreter
    loop with no pool-spawn latency; non-ASCII rows still route through the
    Python engine for exact unicode semantics), then a ProcessPoolExecutor fan-out of the
    Python engine when the toolchain can't build the native core, then the
    plain in-process loop for small columns.
    """
    p = node.params
    vocab = state["vocab"]
    # Memoized on the state dict: predict() re-enters here per batch.
    table = state.get("_table")
    if table is None:
        table = state["_table"] = {v: i for i, v in enumerate(vocab)}
        state["_has_wordpiece"] = any(v.startswith("##") for v in vocab)
    has_wordpiece = state["_has_wordpiece"]

    from tpu_pipelines.transform import native_tokenizer

    native = native_tokenizer.encode_batch(
        col, p, state,
        lambda subset: _tokenize_core(subset, p, table, has_wordpiece),
        max_python_rows=_TOK_MIN_PARALLEL_ROWS,
    )
    if native is not None:
        return native

    import os as _os

    workers = min(_os.cpu_count() or 1, _TOK_MAX_WORKERS)
    if len(col) >= _TOK_MIN_PARALLEL_ROWS and workers > 1:
        from concurrent.futures import ProcessPoolExecutor

        chunks = [c for c in np.array_split(col, workers * 4) if len(c)]
        with ProcessPoolExecutor(
            max_workers=workers, initializer=_tok_init,
            initargs=(dict(p), list(vocab)),
        ) as ex:
            parts = list(ex.map(_tok_chunk, chunks))
        return np.concatenate(parts, axis=0)
    return _tokenize_core(col, p, table, has_wordpiece)


def _apply_analyzer(node: Node, state: Dict[str, Any], col, xp):
    if node.op == "z_score":
        x = xp.asarray(col, dtype=xp.float32)
        return (x - float(state["mean"])) / float(state["std"])
    if node.op == "scale_to_0_1":
        x = xp.asarray(col, dtype=xp.float32)
        lo, hi = float(state["min"]), float(state["max"])
        return (x - lo) / (hi - lo)
    if node.op == "vocab_apply":
        # Host-only (consumes strings / stringified ints).  Vectorized:
        # binary search over the sorted vocab, FNV bucketing for OOV rows —
        # no per-row Python loop (the Beam-parallelism replacement).
        assert xp is np, "vocab_apply must run host-side"
        vocab = state["vocab"]
        num_oov = node.params.get("num_oov_buckets", 1) or 0
        strs = _stringify_column(col)
        sorted_vocab = state.get("_sorted_vocab")
        if sorted_vocab is None:
            vocab_arr = np.asarray(vocab, dtype="U")
            order = np.argsort(vocab_arr, kind="stable")
            sorted_vocab = state["_sorted_vocab"] = vocab_arr[order]
            state["_sorted_order"] = order
        order = state["_sorted_order"]
        pos = np.searchsorted(sorted_vocab, strs)
        pos_c = np.minimum(pos, len(sorted_vocab) - 1)
        found = (
            (sorted_vocab[pos_c] == strs) if len(sorted_vocab)
            else np.zeros(len(strs), bool)
        )
        out = np.where(found, order[pos_c], -1).astype(np.int32)
        if num_oov > 0 and not found.all():
            from tpu_pipelines.utils.hashing import hash_buckets

            oov = hash_buckets(strs[~found], num_oov) + len(vocab)
            out[~found] = oov.astype(np.int32)
        return out
    if node.op == "bucketize":
        boundaries = xp.asarray(state["boundaries"], dtype=xp.float32)
        x = xp.asarray(col, dtype=xp.float32)
        return xp.searchsorted(boundaries, x).astype(xp.int32)
    if node.op == "tokenize":
        assert xp is np, "tokenize must run host-side"
        return _apply_tokenize(node, state, np.asarray(col))
    raise ValueError(f"unknown analyzer {node.op!r}")


def _is_string_array(x) -> bool:
    return isinstance(x, np.ndarray) and (
        x.dtype == object or x.dtype.kind in ("U", "S")
    )


def _apply_stateless(node: Node, args: List[Any], xp):
    op = node.op
    p = node.params
    if op == "identity":
        return args[0]
    if op == "fill_missing":
        x = args[0]
        default = p.get("default", 0)
        if _is_string_array(x):
            out = np.asarray(
                [default if v is None else v for v in x], dtype=object
            )
            return out
        x = xp.asarray(x, dtype=xp.float32)
        return xp.nan_to_num(x, nan=float(default))
    if op == "hash_strings":
        assert xp is np, "hash_strings must run host-side"
        return _stable_hash_strings(np.asarray(args[0]), p["hash_buckets"])
    if op == "equal" and "value" in p:
        assert xp is np, "string equality must run host-side"
        x = np.asarray(args[0])
        return (x.astype(str) == p["value"]).astype(np.float32)
    if op == "one_hot":
        x = xp.asarray(args[0]).astype(xp.int32)
        depth = p["depth"]
        eye = xp.eye(depth, dtype=xp.float32)
        clipped = xp.clip(x, 0, depth - 1)
        out = eye[clipped]
        # Out-of-range (e.g. OOV -1) rows become all-zero.
        mask = ((x >= 0) & (x < depth)).astype(xp.float32)
        return out * mask[..., None]
    if op == "cast":
        return xp.asarray(args[0]).astype(p.get("dtype", "float32"))
    if op == "clip":
        x = xp.asarray(args[0], dtype=xp.float32)
        return xp.clip(x, p["min_value"], p["max_value"])

    fa = [
        xp.asarray(a, dtype=xp.float32)
        if not isinstance(a, (int, float)) else a
        for a in args
    ]
    if op == "add":
        return fa[0] + fa[1]
    if op == "sub":
        return fa[0] - fa[1]
    if op == "mul":
        return fa[0] * fa[1]
    if op == "div":
        return fa[0] / fa[1]
    if op == "log1p":
        return xp.log1p(fa[0])
    if op == "log":
        return xp.log(fa[0])
    if op == "sqrt":
        return xp.sqrt(fa[0])
    if op == "abs":
        return xp.abs(fa[0])
    if op == "equal":
        return (fa[0] == fa[1]).astype(xp.float32)
    if op == "greater":
        return (fa[0] > fa[1]).astype(xp.float32)
    if op == "less":
        return (fa[0] < fa[1]).astype(xp.float32)
    if op == "where":
        return xp.where(fa[0] != 0, fa[1], fa[2])
    raise ValueError(f"unknown op {op!r}")
